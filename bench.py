"""Decode-throughput benchmark over the BASELINE.md config matrix.

Hardened for the tunneled-TPU environment (round-1 postmortem: one
transient tunnel outage produced `rc=1, parsed: null` and wiped the
round's perf evidence):

- Every config runs in its OWN subprocess with a hard timeout, so a hang
  in backend init (observed: even ``jnp.ones((2,2))`` can block forever
  when the tunnel is down) cannot take down the whole benchmark.
- A cheap probe subprocess runs first (with one retry); if the chip is
  unreachable the script still prints the final summary JSON — with an
  ``"error"`` field — and exits 0.
- Each config's result line is printed to stderr AS IT COMPLETES, and the
  full summary JSON line is RE-EMITTED on stdout after every config (last
  line wins) — an outer kill at any moment leaves a parseable artifact
  with everything that finished (round-2 postmortem: the single
  end-of-run summary never printed because the driver's budget expired
  first).
- Configs run in priority order (headline first) against a global
  deadline from ``BENCH_DEADLINE_S`` (default 1500 s — inside the
  driver's observed ~30 min budget); per-config timeouts are clipped to
  the remaining deadline and configs that can't fit are skipped, not
  silently truncated.
- Children print ``bench-phase`` breadcrumbs (params built, prefill
  compiled, decode compiled, each rep) to stderr; on a timeout the
  parent recovers the partial stderr from TimeoutExpired, so a burned
  config still says WHERE it died (compile vs execute).
- Subprocesses share a persistent XLA compilation cache dir so repeated
  compiles are amortized.

Matrix (BASELINE.md "Benchmark configurations"):
- llama1b bs=1/8/32 decode, prompt=128, decode=256 (config 1 family;
  bs=8 is the headline)
- int8 weight-only quant at bs=1/8
- gemma2_2b greedy decode bs=1 seq=128 (config 2)
- llama3b sampled decode, seq=2048 prompt, bs=8, KV cache (config 3)
- llama1b prefill TTFT at seq=8192, Pallas flash vs XLA attention
  (config 5 shape, single-chip)

Headline + baseline bookkeeping: the north-star target (BASELINE.json,
1,000 decode tok/s/chip) is unreachable at bs=1 by the HBM roofline
(1.24B bf16 params = 2.47 GB/step ÷ ~819 GB/s ≈ 331 steps/s), so the
headline ``value`` is the aggregate tok/s/chip at bs=8 and the JSON
carries BOTH ratios explicitly: ``vs_baseline`` (= bs8 aggregate / 1000,
the headline) and ``detail.vs_baseline_bs1_per_seq`` (the strict bs=1
per-sequence reading of the same target).  Decode configs also report
``hbm_gb_s`` (achieved weight+KV stream bandwidth) and
``hbm_roofline_frac`` (÷ 819 GB/s, the v5e spec number).

Measurement notes (tunneled TPU): the transport dedupes repeated
executions with identical live inputs and ``block_until_ready`` is not a
reliable fence, so every timed iteration feeds FRESH inputs (chained to
the previous iteration's output host-side) and forces a real D2H
materialization with ``np.asarray`` before reading the clock.

Prints ONE JSON line to stdout:
  {"metric": "decode_tokens_per_sec_per_chip", "value": N,
   "unit": "tokens/s/chip", "vs_baseline": N/1000, "detail": {...}}
(The reference publishes no numbers of its own — SURVEY §6; this
artifact IS the baseline.)
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import time

HBM_GB_S = 819.0  # TPU v5e HBM bandwidth spec
PEAK_BF16_FLOP_S = 197e12  # TPU v5e bf16 peak (MFU denominator)
NORTH_STAR_TOK_S = 1000.0  # BASELINE.json north_star
REPO = os.path.dirname(os.path.abspath(__file__))

# name -> measurement kwargs (per-config timeouts live in TIMEOUTS below)
DECODE_CONFIGS = {
    "llama1b_bs1": dict(model="llama1b", batch=1, prompt_len=128, decode_tokens=256),
    "llama1b_bs8": dict(model="llama1b", batch=8, prompt_len=128, decode_tokens=256),
    "llama1b_bs32": dict(model="llama1b", batch=32, prompt_len=128, decode_tokens=128),
    "int8_bs1": dict(model="llama1b", batch=1, prompt_len=128, decode_tokens=256, quant=True),
    "int8_bs8": dict(model="llama1b", batch=8, prompt_len=128, decode_tokens=256, quant=True),
    "int4_bs8": dict(model="llama1b", batch=8, prompt_len=128, decode_tokens=256,
                     quant="int4"),
    # W8A8 / W4A8: all-integer MXU einsums (no weight convert in the
    # operand stream) — the candidate fix for int8's 47.5%-of-roofline gap
    "int8a8_bs8": dict(model="llama1b", batch=8, prompt_len=128,
                       decode_tokens=256, quant="int8_a8"),
    "int4a8_bs8": dict(model="llama1b", batch=8, prompt_len=128,
                       decode_tokens=256, quant="int4_a8"),
    "gemma2_2b_bs1": dict(model="gemma2_2b", batch=1, prompt_len=128, decode_tokens=256),
    # Gemma-2 aggregate configs (VERDICT r4 task 3): the north star names
    # BOTH models at >1k tok/s/chip; at bs=1 a 5.23 GB model is
    # roofline-capped at ~157 tok/s, so the Gemma number must come from a
    # batched config exactly like llama's headline does
    "gemma2_2b_bs8": dict(model="gemma2_2b", batch=8, prompt_len=128, decode_tokens=256),
    "gemma2_2b_bs16": dict(model="gemma2_2b", batch=16, prompt_len=128, decode_tokens=256),
    # the fused Pallas decode-attention experiment (keep only if it wins)
    "llama1b_bs8_fdec": dict(model="llama1b", batch=8, prompt_len=128,
                             decode_tokens=256, decode_attn="flash_decode"),
    # flagship combo: Pallas decode kernel streaming the int8 KV cache
    "llama1b_bs8_fdec_kvq8": dict(model="llama1b", batch=8, prompt_len=128,
                                  decode_tokens=256, decode_attn="flash_decode",
                                  cache_dtype="int8"),
    "llama3b_seq2048_bs8": dict(
        model="llama3b", batch=8, prompt_len=2048, decode_tokens=64, sampler="top_p"
    ),
    # int8 KV cache at the long-context shape: cache HBM stream halves
    "llama3b_seq2048_bs8_kvq8": dict(
        model="llama3b", batch=8, prompt_len=2048, decode_tokens=64,
        sampler="top_p", cache_dtype="int8",
    ),
    # headline shape with the layer scan unrolled 2x (weight-stream
    # software pipelining experiment; promoted to default only if it wins)
    "llama1b_bs8_unroll2": dict(model="llama1b", batch=8, prompt_len=128,
                                decode_tokens=256,
                                env={"LLMTPU_SCAN_UNROLL": "2"}),
    # not in the default matrix: offline smoke test of the measurement path
    "smoke_tiny": dict(model="tiny", batch=2, prompt_len=16, decode_tokens=8),
}
PREFILL_CONFIGS = {
    "prefill8k_xla": dict(model="llama1b", prompt_len=8192, attn_impl="xla"),
    "prefill8k_flash": dict(model="llama1b", prompt_len=8192, attn_impl="flash"),
    "prefill8k_chunked": dict(model="llama1b", prompt_len=8192, attn_impl="xla",
                              chunk=1024),
}
# Ragged-batch decode: prompts of very different lengths, LEFT-padded
# (generate.generate_ragged).  The XLA path streams the full [B, S_cap]
# cache slab every step regardless of validity; the Pallas decode kernel
# skips each row's invisible blocks (leading pads + tail), so this is the
# workload where the kernel has a structural edge — the win-case evidence
# VERDICT r4 task 2 asks for, on a shape real serving actually has.
RAGGED_CONFIGS = {
    "ragged_bs8_xla": dict(model="llama1b", attn="xla"),
    "ragged_bs8_fdec": dict(model="llama1b", attn="flash_decode"),
    "smoke_ragged": dict(model="tiny", attn="xla", lens=(24, 16, 9, 4),
                         decode=8),
}
# serving-like length mix: mean visible ≈ 31% of the 4224-slot slab, so
# the XLA path streams ~1.1 GB/step of cache the kernel mostly skips
RAGGED_LENS = (4096, 2048, 1536, 1024, 768, 512, 256, 128)
RAGGED_DECODE = 64

# Continuous-batching serving engine (llm_np_cp_tpu/serve/): replay a
# Poisson arrival trace through ServeEngine's paged-pool decode and
# report TTFT/throughput percentiles — the request-level number the
# ROADMAP north star ("heavy traffic") is actually about, vs the
# batch-job numbers above.
SERVE_CONFIGS = {
    "serve_poisson_bs8": dict(model="llama1b", requests=32, rate=16.0,
                              prompt_len=512, max_tokens=64, slots=8,
                              block_size=128),
    # shared-prefix workload: 32 requests drawn from 8 distinct prompts
    # (4 repeats each) with the refcounted prefix cache on — hits skip
    # whole prefill chunks, so TTFT and prefill dispatch counts are the
    # observable, alongside the gather-vs-paged decode split.
    # extra_blocks: retention headroom beyond the worst-case sizing —
    # cache entries are reclaimed LRU whenever the free list runs short,
    # so a worst-case-tight pool would evict every entry before its
    # twin prompt arrives (8 prompts x <=4 shareable blocks each)
    "serve_prefix_shared": dict(model="llama1b", requests=32, rate=16.0,
                                prompt_len=512, max_tokens=64, slots=8,
                                block_size=128, distinct_prompts=8,
                                prefix_cache=True, extra_blocks=32),
    "smoke_serve": dict(model="tiny", requests=8, rate=100.0, prompt_len=16,
                        max_tokens=6, slots=2, block_size=8),
}

# HTTP front-end loadgen (llm_np_cp_tpu/serve/http/): the SAME Poisson
# trace replayed twice on one engine build — direct ServeEngine calls
# (realtime replay) vs in-process HTTP server + asyncio SSE clients — so
# the HTTP layer's TTFT/throughput overhead is a measured delta, not a
# guess.  serve_http_poisson mirrors serve_poisson_bs8's workload shape
# so its direct leg cross-checks that config's numbers.
SERVE_HTTP_CONFIGS = {
    "serve_http_poisson": dict(model="llama1b", requests=32, rate=16.0,
                               prompt_len=512, max_tokens=64, slots=8,
                               block_size=128),
    "smoke_serve_http": dict(model="tiny", requests=6, rate=50.0,
                             prompt_len=16, max_tokens=4, slots=2,
                             block_size=8),
}

# Chaos leg (llm_np_cp_tpu/serve/faults.py + the EngineRunner
# supervisor): the SAME Poisson trace replayed twice over HTTP — clean,
# then under a seeded fault schedule (a tick-thread crash mid-flight and
# a paged-kernel dispatch fault, plus transient 429s on the smoke) with
# supervised restarts on.  The observables are what an outage costs:
# recovery latency, p99 TTFT degradation vs the clean leg, and
# token-identical recovery (the teacher-forced replay contract).  The
# clean leg doubles as the "chaos off = unchanged numbers" reference.
SERVE_CHAOS_CONFIGS = {
    "serve_chaos_poisson": dict(model="llama1b", requests=32, rate=16.0,
                                prompt_len=512, max_tokens=64, slots=8,
                                block_size=128,
                                chaos="tick_crash@90;decode@40",
                                tick_deadline=60.0, backoff=0.2),
    "smoke_serve_chaos": dict(model="tiny", requests=8, rate=50.0,
                              prompt_len=16, max_tokens=6, slots=2,
                              block_size=8,
                              chaos="tick_crash@8;decode@4;http_429@2:2=0",
                              tick_deadline=30.0, backoff=0.05),
}

# Unified-tick leg (ServeEngine mixed_step): the SAME long-prefill-heavy
# Poisson trace (mixed chat+completion decode budgets, prompts skewed
# long so admissions land mid-decode) replayed three times on one engine
# geometry — phase-split tick, unified mixed tick (fused sampling
# epilogue), unified tick with the XLA logits tail — so the ragged
# kernel's headline claim AND the tick-tail fusion's Δhost_sync/
# Δroofline_util are measured deltas on identical arrivals at token
# parity.
SERVE_MIXED_CONFIGS = {
    "serve_mixed_poisson": dict(model="llama1b", requests=32, rate=16.0,
                                prompt_len=512, max_tokens=64, slots=8,
                                block_size=128),
    "smoke_serve_mixed": dict(model="tiny", requests=8, rate=50.0,
                              prompt_len=28, max_tokens=8, slots=2,
                              block_size=8),
}

# Speculative-serving leg (ServeEngine spec_k + serve/spec.py): the
# SAME Poisson arrival schedule replayed twice on one engine geometry —
# plain unified tick vs spec-enabled (every request opts in) — over a
# REPETITIVE-prompt workload (each prompt is a small random pattern
# tiled to length: the extractive/quoting shape where prompt-lookup
# drafting pays).  The observables are the draft-then-verify claims on
# identical arrivals: acceptance rate, decode tok/s and p99 TTFT vs the
# plain leg, TOKEN PARITY (deterministic verify keys make spec streams
# byte-identical), and dispatches-per-tick staying ~1 on the spec leg
# (drafting is host-side; verify lanes ride the one mixed dispatch).
SERVE_SPEC_CONFIGS = {
    "serve_spec_poisson": dict(model="llama1b", requests=32, rate=16.0,
                               prompt_len=512, max_tokens=64, slots=8,
                               block_size=128, spec_k=4, pattern_len=24),
    "smoke_serve_spec": dict(model="tiny", requests=8, rate=50.0,
                             prompt_len=20, max_tokens=12, slots=2,
                             block_size=8, spec_k=4, pattern_len=5),
}

# Mesh-sharded serving (ServeEngine mesh_plan + serve/replica.py): ONE
# shared-prompt Poisson trace (the serve_prefix_shared workload shape)
# replayed over three topologies on identical arrivals — single chip,
# TP=8 (one engine, kv-head-sharded pool), and DP=4 replicas x TP=2
# behind the prefix-affinity router.  The observables are the ROADMAP
# item-1 claims: per-chip tok/s against the 1629 tok/s/chip live
# capture (BENCH_TPU_LIVE_r4 — wired into the JSON for the next
# live-TPU window), p99 TTFT per topology, token parity across all
# legs, and the router's routed/spilled split (shared-prompt traffic
# must stay block-local).  Legs that need more devices than the
# backend exposes are skipped with a note, so the config degrades
# gracefully on a single chip.
SERVE_SHARDED_CONFIGS = {
    "serve_sharded_poisson": dict(model="llama1b", requests=32, rate=16.0,
                                  prompt_len=512, max_tokens=64, slots=8,
                                  block_size=128, distinct_prompts=8,
                                  prefix_cache=True, extra_blocks=32,
                                  tp=8, dp=(4, 2),
                                  env={"XLA_FLAGS": (
                                      os.environ.get("XLA_FLAGS", "")
                                      + " --xla_force_host_platform_"
                                        "device_count=8").strip()}),
    "smoke_serve_sharded": dict(model="tiny", requests=8, rate=50.0,
                                prompt_len=24, max_tokens=6, slots=2,
                                block_size=8, distinct_prompts=4,
                                prefix_cache=True, extra_blocks=16,
                                tp=2, dp=(2, 2)),
}

# Durable-journal restart leg (serve/journal.py + tools/serve_proc.py):
# REAL server subprocesses, three legs on identical arrivals — plain
# (no journal), journaled (same trace; the delta IS the journal's
# cost: client tok/s regression + off-thread fsync p99 from the
# scrape), and a kill -9 leg (chaos proc_kill SIGKILLs the server
# mid-decode; the parent respawns it on the same port + journal and
# every client resumes via Last-Event-ID).  Observables: token parity
# across the kill (journal replay is teacher-forced, so streams must
# be byte-identical to the plain leg), restart-to-first-resumed-token
# latency (client-observed: cut → first resumed token, including the
# respawned process's model build), and the journal overhead pair.
SERVE_RESTART_CONFIGS = {
    "serve_restart_poisson": dict(model="llama1b", requests=32, rate=16.0,
                                  prompt_len=512, max_tokens=64, slots=8,
                                  block_size=128, kill_tick=90),
    "smoke_serve_restart": dict(model="tiny", requests=8, rate=50.0,
                                prompt_len=16, max_tokens=8, slots=2,
                                block_size=8, kill_tick=14),
}

# Rolling-upgrade leg (serve/lifecycle.py + ReplicaSet.rolling_upgrade):
# ONE Poisson trace over a direct-mode DP fleet, two legs on identical
# arrivals — steady (no roll) and rolling (a full replica-by-replica
# weight swap triggered mid-trace: each replica drains its in-flight
# streams to peers, rebuilds on the "new" checkpoint via clone_fresh,
# and rejoins routing).  Observables are the zero-downtime claims:
# ZERO dropped streams, token parity across the roll (the drain is
# teacher-forced), p99 TTFT degradation during the roll bounded
# (ttft_p99_degradation — what tools/slo_gate.py
# --max-p99-ttft-degradation consumes in CI), and zero new compiles
# for a same-shaped swap (params are jit call arguments; pinned).
SERVE_ROLLING_CONFIGS = {
    "serve_rolling_upgrade": dict(model="llama1b", requests=32, rate=16.0,
                                  prompt_len=512, max_tokens=64, slots=8,
                                  block_size=128, replicas=3,
                                  roll_after_ticks=8),
    "smoke_serve_rolling": dict(model="tiny", requests=16, rate=50.0,
                                prompt_len=16, max_tokens=8, slots=2,
                                block_size=8, replicas=3,
                                roll_after_ticks=3),
}

# Tiered KV prefix cache (serve/host_tier.py): ONE shared-prompt
# Poisson trace whose prefix WORKING SET is ~4x the pool's block
# capacity (distinct prompts cycled round-robin, so every repeat
# arrives after its prefix blocks were LRU-reclaimed), replayed twice
# on identical arrivals — tier off (reclaim drops, repeats re-prefill)
# vs tier on (reclaim spills to host RAM, repeats restore via async
# device_put above the measured breakeven).  Observables: prefix
# hit-rate (strictly higher tier-on), prefill tokens dispatched
# (strictly fewer tier-on — the restored bytes are prefill the fleet
# did not redo), restore-latency p99, p99 TTFT, tok/s, TOKEN PARITY
# (restored K/V is bit-identical to recompute), and
# compiles_added_by_tier == 0 (restores land as ordinary pool blocks
# through one warmed program).  num_blocks deliberately OVERRIDES the
# worst-case sizing: capacity pressure is the whole point.
SERVE_TIER_CONFIGS = {
    "serve_prefix_tiered": dict(model="llama1b", requests=48, rate=16.0,
                                prompt_len=512, max_tokens=64, slots=8,
                                block_size=128, distinct_prompts=24,
                                num_blocks=14, tier_gb=4.0),
    "smoke_serve_prefix_tiered": dict(model="tiny", requests=16,
                                      rate=50.0, prompt_len=24,
                                      max_tokens=6, slots=2,
                                      block_size=8, distinct_prompts=8,
                                      num_blocks=12, tier_gb=1.0),
}

# Multi-tenant fairness leg (serve/tenants.py + the plan_tick
# fair-share prefill order): ONE merged arrival schedule built from
# three independent per-tenant Poisson processes at skewed rates — a
# chat-like tenant (short prompts, short decodes, high rate), a
# completion tenant (medium), and a prefill-heavy batch tenant (long
# prompts, few tokens, low rate) — replayed twice on one engine
# geometry: fairness off (prefill budget fills in admission order) vs
# fairness on (smallest-accumulated-cost-share tenant first).  The
# observables are the accounting-plane claims on identical arrivals:
# per-tenant attainment / goodput / cost share from the TenantLedger
# (what tools/slo_gate.py --min-tenant-attainment gates), each
# tenant's mean first-token RANK (ordinal, so the fairness reorder is
# visible without trusting CPU wall clocks), TOKEN PARITY between the
# legs (fairness reorders prefill scheduling, never content), and
# compiles_added_by_trace == 0 on both legs (ordering is host-side;
# the ragged buckets don't change).
SERVE_TENANT_CONFIGS = {
    "serve_tenant_poisson": dict(
        model="llama1b", slots=8, block_size=128,
        tenants=dict(
            chat=dict(requests=16, rate=24.0, prompt_len=128,
                      max_tokens=32),
            complete=dict(requests=10, rate=8.0, prompt_len=384,
                          max_tokens=64),
            batch=dict(requests=6, rate=3.0, prompt_len=512,
                       max_tokens=8),
        )),
    "smoke_serve_tenant": dict(
        model="tiny", slots=4, block_size=8,
        tenants=dict(
            chat=dict(requests=6, rate=120.0, prompt_len=16,
                      max_tokens=8),
            complete=dict(requests=3, rate=60.0, prompt_len=24,
                          max_tokens=10),
            batch=dict(requests=3, rate=30.0, prompt_len=48,
                       max_tokens=4),
        )),
}

SPEC_CONFIGS = {
    # batched self-speculation: bf16 target + int8 self-draft, γ=4
    "int8_spec_bs8": dict(model="llama1b", batch=8, prompt_len=128,
                          decode_tokens=256, gamma=4),
    # Configs that can plausibly WIN (VERDICT r4 task 5): bs=1 (where
    # decode is maximally bandwidth-bound and batching can't amortize the
    # weight stream) with drafts much cheaper than the int8 self-draft —
    # an int4 self-draft (¼ the stream) and a layer-skip draft (first 8
    # of 16 layers, int4: ~1/6 the stream).  γ kept small: per-cycle cost
    # is γ·draft + 1 verify, so big γ only pays at high acceptance.
    "spec_int4_bs1_g2": dict(model="llama1b", batch=1, prompt_len=128,
                             decode_tokens=256, gamma=2, draft="int4"),
    "spec_int4_bs1_g4": dict(model="llama1b", batch=1, prompt_len=128,
                             decode_tokens=256, gamma=4, draft="int4"),
    "spec_trunc8_bs1_g4": dict(model="llama1b", batch=1, prompt_len=128,
                               decode_tokens=256, gamma=4, draft="trunc8_int4"),
    # offline smoke for the speculative measurement path
    "smoke_spec": dict(model="tiny", batch=2, prompt_len=16, decode_tokens=8,
                       gamma=2),
}
# Priority order, round 5 (VERDICT r4 tasks 1–5): headline anchor first,
# then everything the r4 tunnel outage left UNVERIFIED (fused int4
# einsum, rewritten decode kernel, fdec_kvq8, unroll2), then the
# never-measured BASELINE configs (Gemma aggregate, llama-3B), then the
# experiments.  A burned config only costs its own timeout — the summary
# re-emits after each.
PRIORITY = [
    "llama1b_bs8",        # the headline + the anchor every twin compares to
    "int4_bs8",           # r4 fused-nibble einsum fix — never re-measured
    "llama1b_bs8_fdec_kvq8",  # kernel's best shot (VERDICT task 2) — never measured
    "llama1b_bs8_fdec",   # rewritten decode kernel at the headline shape
    "ragged_bs8_xla",     # ragged decode: the kernel's structural win case
    "ragged_bs8_fdec",
    "serve_poisson_bs8",  # continuous-batching serving engine (serve/)
    "serve_prefix_shared",  # prefix-cache reuse + gather-vs-paged decode
    "serve_prefix_tiered",  # host-RAM KV tier: spill/restore vs drop/recompute
    "serve_mixed_poisson",  # unified ragged tick vs phase-split head-to-head
    "serve_spec_poisson",  # draft-then-verify vs plain on identical arrivals
    "serve_http_poisson",  # HTTP front-end overhead vs direct engine calls
    "serve_chaos_poisson",  # supervised recovery under a seeded fault schedule
    "serve_restart_poisson",  # kill -9 + journal replay + client resume
    "serve_rolling_upgrade",  # zero-downtime weight swap over the DP fleet
    "serve_sharded_poisson",  # TP pool sharding + DP replicas vs single chip
    "serve_tenant_poisson",  # fair-share prefill + per-tenant accounting
    "gemma2_2b_bs8",      # Gemma north-star number (VERDICT task 3)
    "int8_bs8",           # roofline-gap anchor (VERDICT task 6)
    "int8a8_bs8",         # W8A8 int8-MXU einsums vs that anchor
    "int4a8_bs8",         # W4A8: ¼ weight stream, all-integer contraction
    "decomp",             # ...and the diagnostic that locates that gap
    "llama3b_seq2048_bs8",  # BASELINE config 3 — no number in 4 rounds (task 4)
    "llama1b_bs8_unroll2",  # layer-scan unroll experiment vs bs8
    "gemma2_2b_bs16",
    "prefill8k_xla",
    "prefill8k_flash",
    "prefill8k_chunked",  # BASELINE config 5 via chunked prefill
    "spec_int4_bs1_g2",   # speculation configs that can win (task 5)
    "spec_int4_bs1_g4",
    "spec_trunc8_bs1_g4",
    "gemma2_2b_bs1",      # re-capture: prior-round coverage, cheap
    "llama1b_bs1",
    "llama1b_bs32",
    "int8_spec_bs8",      # the documented-negative bs=8 self-spec point
    "int8_bs1",
    "llama3b_seq2048_bs8_kvq8",
]
# diagnostic children that run as priority slots but aren't matrix configs
EXTRA_CHILDREN = {"decomp"}
# every non-smoke config must be in PRIORITY — a config added to the dicts
# but not the ordering would otherwise silently never run
assert set(PRIORITY) == {
    n
    for n in list(DECODE_CONFIGS) + list(SPEC_CONFIGS)
    + list(PREFILL_CONFIGS) + list(RAGGED_CONFIGS) + list(SERVE_CONFIGS)
    + list(SERVE_HTTP_CONFIGS) + list(SERVE_CHAOS_CONFIGS)
    + list(SERVE_MIXED_CONFIGS) + list(SERVE_SPEC_CONFIGS)
    + list(SERVE_SHARDED_CONFIGS) + list(SERVE_RESTART_CONFIGS)
    + list(SERVE_ROLLING_CONFIGS) + list(SERVE_TIER_CONFIGS)
    + list(SERVE_TENANT_CONFIGS)
    if not n.startswith("smoke")
} | EXTRA_CHILDREN, "PRIORITY out of sync with config dicts"

TIMEOUTS = {
    "llama1b_bs8": 600,
    "gemma2_2b_bs8": 600,  # 2.6B params: first-touch compile + 3 reps
    "gemma2_2b_bs16": 600,  # same model, 2x tokens per rep
    "decomp": 850,  # 6 decode-loop compiles (full/half × 3 quant modes) + head
    "ragged_bs8_xla": 600,  # 2 prefill + 2 loop compiles + 3 rep pairs
    "ragged_bs8_fdec": 600,
    # ~290 host-driven device dispatches (32 prefills + ~256 decode
    # ticks) + 4 program compiles; per-tick host latency dominates —
    # and when the paged probe passes the trace replays ONCE PER IMPL
    # (gather + paged), roughly doubling the measured span
    "serve_poisson_bs8": 850,
    "serve_prefix_shared": 850,
    # two trace replays (tier-off + tier-on) on one param build, under
    # DELIBERATE pool-capacity pressure (admissions serialize on
    # blocks, so the trace span stretches well past the shared config)
    "serve_prefix_tiered": 1100,
    # two realtime replays of the trace (direct + HTTP) at wall-clock
    # arrival pacing (~2s traffic span each) on top of the serve compile
    # budget; the HTTP leg adds event-loop + SSE framing time per token
    "serve_http_poisson": 850,
    # three trace replays (split + unified-fused + unified-XLA-tail) on
    # one param build, each with its own warmup — each unified leg warms
    # one mixed_step compile per packed-width bucket
    "serve_mixed_poisson": 1100,
    # two unified-tick replays (plain + spec) on one param build; the
    # spec leg's verify lanes widen the sample operands, so its bucket
    # warmup compiles its own mixed_step set
    "serve_spec_poisson": 850,
    # clean + chaos HTTP legs at realtime pacing, plus a supervised
    # restart (backoff + pool rebuild + teacher-forced replay prefills)
    # inside the chaos leg's measured span
    "serve_chaos_poisson": 850,
    # three trace replays (single / TP / DP x TP) on one param build;
    # the sharded legs re-place params + pool per topology and the DP
    # leg warms every replica
    "serve_sharded_poisson": 850,
    # FIVE server subprocesses (plain / journaled / journaled_sync /
    # kill / restart), each paying its own model build + warmup, plus
    # the realtime client traffic spans
    "serve_restart_poisson": 1300,
    # two trace replays over a 3-replica direct-mode fleet on one param
    # build, each replica warmed, plus the roll's three clone_fresh
    # rebuilds + teacher-forced drain re-prefills inside the measured
    # span
    "serve_rolling_upgrade": 850,
    # two trace replays (fairness off/on) on one param build; the
    # merged 32-request trace mixes three prompt-length bands, so the
    # bucket warmup compiles one mixed_step set per leg
    "serve_tenant_poisson": 850,
    # prefill-dominated: the marginal measurement's extra prefill+half
    # decode per rep nearly doubles measured-phase wall time
    "llama3b_seq2048_bs8": 700,
    "llama3b_seq2048_bs8_kvq8": 600,
}
DEFAULT_TIMEOUT = 420
PROBE_TIMEOUT = 180
MIN_CONFIG_BUDGET_S = 120  # don't launch a config with less than this left


def _deadline_s() -> float:
    return float(os.environ.get("BENCH_DEADLINE_S", "1500"))


def _half_len(decode_tokens: int) -> int:
    """Half-length decode dispatch of the marginal-rate measurement —
    ONE definition so run_warm AOT-compiles exactly the length
    _measure_decode dispatches."""
    return max(decode_tokens // 2, 1)


def _phase(config: str, phase: str, t0: float, **extra) -> None:
    """Timestamped breadcrumb on stderr.  These survive a parent-side
    timeout kill (recovered from TimeoutExpired.stderr), so a burned
    config still records whether it died in compile or execute."""
    rec = {"config": config, "phase": phase, "t": round(time.perf_counter() - t0, 1)}
    rec.update(extra)
    print("bench-phase " + json.dumps(rec), file=sys.stderr, flush=True)


# ----------------------------------------------------------------------
# Child-process side
# ----------------------------------------------------------------------

def _child_jax():
    import jax

    # BENCH_PLATFORM=cpu routes the smoke test off-TPU.  The env var
    # JAX_PLATFORMS alone is not enough: the site customization registers
    # the tunnel backend and re-pins jax_platforms via jax.config.
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    jax.config.update("jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache"))
    return jax


def _build_model(name: str, quant=False, tag: str | None = None, t0: float | None = None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from llm_np_cp_tpu.config import GEMMA_2_2B, LLAMA_3_2_1B, LLAMA_3_2_3B, tiny_config
    from llm_np_cp_tpu.models.transformer import init_params

    config = {
        "llama1b": LLAMA_3_2_1B,
        "llama3b": LLAMA_3_2_3B,
        "gemma2_2b": GEMMA_2_2B,
        "tiny": tiny_config("llama"),
    }[name]
    # Breadcrumb BEFORE the first device op (VERDICT r4 weak #6: with no
    # pre-build phases, a dead tunnel, a slow params materialization and a
    # hung compile were indistinguishable in a timeout diagnosis).
    if tag is not None and t0 is not None:
        _phase(tag, "params_init_start", t0)
    # Random bf16 weights — no checkpoint downloads in this environment;
    # decode throughput is weight-value-independent.  init_params is ONE
    # jitted program: a single dispatch, on-device materialization.
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.bfloat16)
    # fence: make "params_built" mean MATERIALIZED, not just dispatched
    np.asarray(jax.tree.leaves(params)[0][..., :1])
    if quant:  # True/"int8" → 8-bit, "int4" → 4-bit, "*_a8" → act quant
        from llm_np_cp_tpu.quant import quantize_params

        params = quantize_params(
            params, bits=4 if str(quant).startswith("int4") else 8,
            act_quant=str(quant).endswith("_a8"),
        )
    return config, params


def _tree_bytes(tree) -> int:
    import jax

    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


def _chained_reps(one, seed_prompt, vocab_size, reps=3):
    """Run ``one(prompt_host, tag)`` reps+1 times (first is compile warmup)
    with FRESH inputs each rep, chained through the previous output — the
    tunneled transport dedupes repeated executions with identical live
    inputs, so a repeated (executable, args) pair measures nothing.

    ``one`` returns a result dict that includes ``"chain"``: an int derived
    from a materialized (host) output, proving the execution completed and
    perturbing the next prompt; ``tag`` ("warmup"/"repN") lets it emit
    bench-phase breadcrumbs.  Returns ``(warm_s, results)``: the warmup
    wall-clock (the compile-phase cost, reported separately) and the
    ``reps`` measured dicts.
    """
    carry = seed_prompt
    t0 = time.perf_counter()
    out = one(carry, "warmup")  # compile
    # a measurement fn can report time its warmup spent EXECUTING extra
    # segments (e.g. _measure_decode's half-run) so the compile-phase
    # number stays comparable across rounds
    warm_s = time.perf_counter() - t0 - out.get("extra_s", 0.0)
    results = []
    for i in range(reps):
        carry = (carry + out["chain"] + i + 1) % vocab_size
        out = one(carry, f"rep{i}")
        results.append(out)
    return warm_s, results


def _measure_decode(name, config, params, prefill, loop, batch, prompt_len,
                    decode_tokens, reps=3, t_start=None,
                    cache_dtype=None):
    """Median TTFT + aggregate decode rate over ``reps`` fresh-input runs.

    Warmup is split into two timed phases (prefill compile, decode-loop
    compile) with ``bench-phase`` breadcrumbs, so a timeout kill records
    which compile burned the budget (VERDICT r2 weak #2: the bs=8 600 s
    timeout was undiagnosable from artifacts).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from llm_np_cp_tpu.cache import KVCache, align_capacity

    key = jax.random.PRNGKey(0)
    # the same capacity sizing Generator._init_cache uses in production
    max_seq = align_capacity(prompt_len + decode_tokens + 8)
    rng = np.random.default_rng(batch)
    if t_start is None:
        t_start = time.perf_counter()

    cache_dtype = cache_dtype or jnp.bfloat16

    half = _half_len(decode_tokens)

    def one(prompt_host, tag):
        cache = KVCache.init(config, batch, max_seq, dtype=cache_dtype)
        t0 = time.perf_counter()
        tok0, cache, _ = prefill(params, jnp.asarray(prompt_host, jnp.int32), cache, key)
        np.asarray(tok0)  # force real D2H — block_until_ready is not a fence here
        t1 = time.perf_counter()
        _phase(name, f"{tag}:prefill_done", t_start, dt=round(t1 - t0, 1))
        toks, cache, _steps = loop(params, tok0, cache, key, decode_tokens)
        toks_host = np.asarray(toks)
        t2 = time.perf_counter()
        _phase(name, f"{tag}:decode_done", t_start, dt=round(t2 - t1, 1))
        # a HALF-length dispatch of the same loop: the fixed per-dispatch
        # transport cost (tunnel RTT, ~0.1-0.3 s) cancels in the marginal
        # rate Δtokens/Δtime, isolating the steady-state on-chip rate the
        # e2e number under-reports.  Fresh cache + perturbed prompt — the
        # full run's cache was donated, and identical live inputs dedupe.
        cache_h = KVCache.init(config, batch, max_seq, dtype=cache_dtype)
        tok_h, cache_h, _ = prefill(
            params,
            jnp.asarray((prompt_host + 1) % config.vocab_size, jnp.int32),
            cache_h, key,
        )
        np.asarray(tok_h)  # fence: keep prefill out of the half timing
        t3 = time.perf_counter()
        toks_h, _, _ = loop(params, tok_h, cache_h, key, half)
        np.asarray(toks_h)
        t4 = time.perf_counter()
        _phase(name, f"{tag}:half_done", t_start, dt=round(t4 - t3, 1))
        return {
            "ttft": t1 - t0,
            "rate": batch * decode_tokens / (t2 - t1),
            "t_full": t2 - t1,
            "t_half": t4 - t3,
            "extra_s": t4 - t2,  # the half segment (its prefill included)
            "chain": int(toks_host.sum()),
        }

    compile_s, runs = _chained_reps(
        one, rng.integers(0, config.vocab_size, (batch, prompt_len)),
        config.vocab_size, reps,
    )
    t_full = float(np.median([r["t_full"] for r in runs]))
    t_half = float(np.median([r["t_half"] for r in runs]))
    marginal = None
    if t_full > t_half * 1.1:
        marginal = batch * (decode_tokens - half) / (t_full - t_half)
    return (
        float(np.median([r["ttft"] for r in runs])),
        float(np.median([r["rate"] for r in runs])),
        compile_s,
        marginal,
    )


def run_decode_config(name: str) -> dict:
    import numpy as np

    from llm_np_cp_tpu.generate import make_decode_loop_fn, make_prefill_fn
    from llm_np_cp_tpu.ops.sampling import Sampler

    t0 = time.perf_counter()
    spec = DECODE_CONFIGS[name]
    config, params = _build_model(
        spec["model"], quant=spec.get("quant", False), tag=name, t0=t0
    )
    _phase(name, "params_built", t0)
    sampler = Sampler(kind=spec.get("sampler", "greedy"))
    prefill = make_prefill_fn(config, sampler)
    loop = make_decode_loop_fn(
        config, sampler, attn_impl=spec.get("decode_attn", "xla")
    )
    batch, prompt_len, decode_tokens = spec["batch"], spec["prompt_len"], spec["decode_tokens"]

    import jax.numpy as jnp

    kv_quant = spec.get("cache_dtype") == "int8"
    ttft, rate, compile_s, marginal = _measure_decode(
        name, config, params, prefill, loop, batch, prompt_len, decode_tokens,
        t_start=t0, cache_dtype=jnp.int8 if kv_quant else None,
    )

    # Roofline accounting: each decode step streams the full weight set plus
    # the valid KV prefix for every sequence (mean length over the run).
    param_bytes = _tree_bytes(params)
    mean_len = prompt_len + decode_tokens / 2
    kv_elem_bytes = 1 + 4 / config.head_dim if kv_quant else 2
    kv_bytes_per_tok = int(
        config.num_hidden_layers * 2 * config.num_key_value_heads
        * config.head_dim * kv_elem_bytes
    )
    step_bytes = param_bytes + batch * mean_len * kv_bytes_per_tok
    steps_per_s = rate / batch
    hbm_gb_s = steps_per_s * step_bytes / 1e9
    return {
        "config": name,
        "ok": True,
        "decode_tok_s_chip": round(rate, 1),
        "per_seq_tok_s": round(rate / batch, 1),
        # steady-state rate with the fixed per-dispatch transport cost
        # cancelled (two-length marginal); e2e rate stays the headline
        **({"decode_tok_s_chip_marginal": round(marginal, 1)}
           if marginal is not None else {}),
        "ttft_s_p50": round(ttft, 4),
        "hbm_gb_s": round(hbm_gb_s, 1),
        "hbm_roofline_frac": round(hbm_gb_s / HBM_GB_S, 3),
        "param_gb": round(param_bytes / 1e9, 2),
        "compile_s": round(compile_s, 1),
        "batch": batch,
        "prompt_len": prompt_len,
        "decode_tokens": decode_tokens,
    }


def run_prefill_config(name: str) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from llm_np_cp_tpu.cache import KVCache, align_capacity
    from llm_np_cp_tpu.generate import make_chunked_prefill_fn, make_prefill_fn
    from llm_np_cp_tpu.ops.sampling import Sampler

    t_start = time.perf_counter()
    spec = PREFILL_CONFIGS[name]
    config, params = _build_model(spec["model"], tag=name, t0=t_start)
    _phase(name, "params_built", t_start)
    prompt_len = spec["prompt_len"]
    chunk = spec.get("chunk")
    if chunk:
        prefill = make_chunked_prefill_fn(
            config, Sampler(kind="greedy"), chunk_size=chunk,
            attn_impl=spec["attn_impl"],
        )
    else:
        prefill = make_prefill_fn(
            config, Sampler(kind="greedy"), attn_impl=spec["attn_impl"]
        )
    key = jax.random.PRNGKey(0)
    rng = np.random.default_rng(0)

    def one(prompt_host, tag):
        cache = KVCache.init(
            config, 1, align_capacity(prompt_len + 8), dtype=jnp.bfloat16
        )
        t0 = time.perf_counter()
        tok0, _, _ = prefill(params, jnp.asarray(prompt_host, jnp.int32), cache, key)
        out = np.asarray(tok0)
        dt = time.perf_counter() - t0
        _phase(name, f"{tag}:prefill_done", t_start, dt=round(dt, 1))
        return {"ttft": dt, "chain": int(out.sum())}

    compile_s, runs = _chained_reps(
        one, rng.integers(0, config.vocab_size, (1, prompt_len)),
        config.vocab_size,
    )
    ttft = float(np.median([r["ttft"] for r in runs]))
    # MFU vs the v5e bf16 peak (VERDICT r3 weak #5): matmul FLOPs are
    # 2·N_params·S (the tied head's vocab matmul counts via N; the embed
    # gather is free) plus causal attention 2·L·S²·H·D per QKᵀ/PV pair.
    n_params = sum(x.size for x in jax.tree.leaves(params))
    flops = 2.0 * n_params * prompt_len + (
        2.0 * config.num_hidden_layers * prompt_len**2
        * config.num_attention_heads * config.head_dim
    )
    return {
        "config": name,
        "ok": True,
        "ttft_s_p50": round(ttft, 4),
        "prefill_tok_s": round(prompt_len / ttft, 1),
        "mfu": round(flops / ttft / PEAK_BF16_FLOP_S, 4),
        "prompt_len": prompt_len,
        "attn_impl": spec["attn_impl"],
        **({"chunk": chunk} if chunk else {}),
        "compile_s": round(compile_s, 1),
    }


def run_ragged_config(name: str) -> dict:
    """Aggregate decode rate over a ragged batch (mixed prompt lengths,
    left-padded).  Rates come from the difference of two matched calls
    (full- vs half-length decode, identical prompt shapes): the prefill
    cost and the fixed per-dispatch transport cancel in
    Δtokens/Δtime, isolating the steady-state decode rate — the number
    where the kernel's per-row block skipping should show up against the
    XLA path's full-slab streaming."""
    import jax.numpy as jnp
    import numpy as np

    from llm_np_cp_tpu.generate import Generator
    from llm_np_cp_tpu.ops.sampling import Sampler

    t0 = time.perf_counter()
    spec = RAGGED_CONFIGS[name]
    lens = spec.get("lens", RAGGED_LENS)
    n_full = spec.get("decode", RAGGED_DECODE)
    n_half = max(n_full // 2, 1)
    b = len(lens)
    config, params = _build_model(spec["model"], tag=name, t0=t0)
    _phase(name, "params_built", t0)
    gen = Generator(
        params, config, sampler=Sampler(kind="greedy"),
        decode_attn_impl=spec["attn"],
    )
    # Generator's Mosaic gate downgrades a rejected kernel to XLA; record
    # the verdict so a downgraded run can't masquerade as a kernel number
    gate_error = None
    if spec["attn"] == "flash_decode":
        from llm_np_cp_tpu.ops.pallas.support import kernel_error

        gate_error = kernel_error("decode_attention")
    rng = np.random.default_rng(11)

    def one(seed_val, tag):
        prompts = [
            (rng.integers(0, config.vocab_size, L) + seed_val)
            % config.vocab_size
            for L in lens
        ]
        t1 = time.perf_counter()
        res_f = gen.generate_ragged(prompts, n_full, seed=int(seed_val) % 97)
        t2 = time.perf_counter()
        res_h = gen.generate_ragged(
            [(p + 1) % config.vocab_size for p in prompts], n_half,
            seed=int(seed_val) % 89,
        )
        t3 = time.perf_counter()
        _phase(name, f"{tag}:pair_done", t0,
               dt_full=round(t2 - t1, 1), dt_half=round(t3 - t2, 1))
        return {
            "t_full": t2 - t1,
            "t_half": t3 - t2,
            "ttft": res_f.ttft_s,
            "extra_s": t3 - t2,
            "chain": int(np.asarray(res_f.tokens).sum() % 10007)
            + int(np.asarray(res_h.tokens).sum() % 101),
        }

    _, runs = _chained_reps(one, 3, 10**9)
    t_full = float(np.median([r["t_full"] for r in runs]))
    t_half = float(np.median([r["t_half"] for r in runs]))
    marginal = (
        b * (n_full - n_half) / (t_full - t_half)
        if t_full > t_half * 1.05 else None
    )
    from llm_np_cp_tpu.cache import align_capacity

    cap = align_capacity(max(lens) + n_full)
    slab_gb = (
        config.num_hidden_layers * 2 * b * cap
        * config.num_key_value_heads * config.head_dim * 2 / 1e9
    )
    return {
        "config": name,
        "ok": True,
        # e2e number includes prefill of the ragged batch; marginal is
        # the steady-state decode rate (prefill+transport cancelled)
        **({"decode_tok_s_chip_marginal": round(marginal, 1)}
           if marginal is not None else {}),
        "decode_tok_s_chip_e2e": round(b * n_full / t_full, 1),
        "ttft_s_p50": round(float(np.median([r["ttft"] for r in runs])), 4),
        "attn": spec["attn"],
        **({"kernel_downgraded_to_xla": gate_error} if gate_error else {}),
        "prompt_lens": list(lens),
        "decode_tokens": n_full,
        "cache_capacity": cap,
        "cache_slab_gb": round(slab_gb, 2),
    }


def run_serve_config(name: str) -> dict:
    """Continuous-batching serving scenario: replay a Poisson arrival
    trace through ServeEngine and report the REQUEST-level numbers
    (TTFT percentiles, per-request decode tok/s, preemptions, pool
    occupancy) that the batch-shaped configs above cannot measure.
    Wall-clock here includes scheduler/host time — that is the point:
    serving throughput is what a user-facing deployment gets.

    When the paged (block-table-native, zero-gather) decode kernel
    passes the Mosaic compile probe, the SAME trace replays once per
    impl — ``attn_impl=gather`` vs ``attn_impl=paged`` on identical
    arrivals is the head-to-head the ROADMAP follow-up asked for; the
    flat headline keys report the paged run when available."""
    import jax.numpy as jnp
    import numpy as np

    from llm_np_cp_tpu.ops.sampling import Sampler
    from llm_np_cp_tpu.serve import ServeEngine, poisson_trace

    t0 = time.perf_counter()
    spec = SERVE_CONFIGS[name]
    config, params = _build_model(spec["model"], tag=name, t0=t0)
    _phase(name, "params_built", t0)
    from llm_np_cp_tpu.ops.pallas.support import (
        kernel_error,
        paged_kernel_name,
    )
    from llm_np_cp_tpu.serve.engine import pool_geometry

    bs = spec["block_size"]
    chunk = min(bs * 2, 256)
    _, sized_blocks, max_seq_len = pool_geometry(
        spec["prompt_len"], spec["max_tokens"], spec["slots"], bs,
        prefill_chunk=chunk,
    )
    num_blocks = spec.get(
        "num_blocks", sized_blocks + spec.get("extra_blocks", 0)
    )
    cache_dtype = spec.get("cache_dtype", "bf16")
    # probe the SAME kernel the engine's gate will check (int8 pools use
    # the int8 variant) so the attn_impl label can't drift from what ran
    paged_err = kernel_error(paged_kernel_name(cache_dtype == "int8"))
    impls = {"gather": "xla"}
    if paged_err is None:
        impls["paged"] = "paged"

    # seed 13 for both the trace rng and per-request sampler seeds:
    # `serve-bench --seed 13` with matching flags replays the SAME trace
    rng = np.random.default_rng(13)
    trace = poisson_trace(
        rng, spec["requests"], rate_rps=spec["rate"],
        prompt_len_range=(max(spec["prompt_len"] // 4, 1),
                          spec["prompt_len"]),
        max_new_tokens=spec["max_tokens"], vocab_size=config.vocab_size,
        seed_base=13,
        distinct_prompts=spec.get("distinct_prompts"),
    )
    _phase(name, "trace_built", t0)

    per_impl: dict = {}
    for impl_name, decode_attn_impl in impls.items():
        engine = ServeEngine(
            params, config,
            sampler=Sampler(kind="greedy"),
            max_slots=spec["slots"],
            num_blocks=num_blocks,
            block_size=bs,
            max_seq_len=max_seq_len,
            prefill_chunk=chunk,
            cache_dtype=jnp.int8 if cache_dtype == "int8" else jnp.bfloat16,
            decode_attn_impl=decode_attn_impl,
            enable_prefix_cache=spec.get("prefix_cache", False),
        )
        # compile outside the measured span: the replay must report
        # steady-state serving numbers, not first-compile stalls
        engine.warmup([int(t["prompt"].size) for t in trace],
                      max_new_tokens=spec["max_tokens"])
        _phase(name, f"warmed_{impl_name}", t0)
        snap = engine.replay_trace(trace)
        _phase(name, f"trace_drained_{impl_name}", t0, ticks=snap["ticks"])
        per_impl[impl_name] = {
            "ok": snap["finished"] == spec["requests"],
            "throughput_tok_s": round(snap["throughput_tok_s"], 1),
            "ttft_s_p50": round(snap.get("ttft_s_p50", float("nan")), 4),
            "ttft_s_p99": round(snap.get("ttft_s_p99", float("nan")), 4),
            "decode_tok_s_p50": round(snap.get("decode_tok_s_p50",
                                               float("nan")), 1),
            "preemptions": snap["preemptions"],
            "occupancy_p99": round(snap.get("occupancy_p99", 0.0), 3),
            "active_slots_mean": round(snap.get("active_slots_mean", 0.0), 2),
            "kv_mib_tick_mean": round(
                snap.get("kv_bytes_tick_mean", 0.0) / 2**20, 3
            ),
            "prefix_hit_rate": round(snap["prefix_hit_rate"], 3)
            if "prefix_hit_rate" in snap else None,
            "ticks": snap["ticks"],
            "compile_counts": engine.compile_counts(),
        }
        del engine

    headline = per_impl.get("paged", per_impl["gather"])
    return {
        "config": name,
        "ok": all(r["ok"] for r in per_impl.values()),
        "requests": spec["requests"],
        "rate_rps": spec["rate"],
        "slots": spec["slots"],
        "pool_blocks": num_blocks,
        "block_size": bs,
        "prefix_cache": bool(spec.get("prefix_cache", False)),
        "distinct_prompts": spec.get("distinct_prompts"),
        "attn_impl": "paged" if "paged" in per_impl else "gather",
        **{k: v for k, v in headline.items() if k != "ok"},
        "impls": per_impl,
        "paged_kernel_probe": paged_err or "ok",
    }


def run_serve_mixed_config(name: str) -> dict:
    """Unified ragged tick vs phase-split, plus the tick-tail fusion
    head-to-head: ONE long-prefill-heavy Poisson trace (prompts skewed
    toward the long end, mixed chat+completion decode budgets) replayed
    through three engines of identical geometry — ``mixed_step="off"``
    (admission → prefill chunks → grow → decode, one dispatch per
    phase), ``mixed_step="on"`` (one ragged mixed dispatch per tick
    with the SLO token-budget planner; fused sampling epilogue when the
    probe passes), and ``mixed_xla_tail`` (the same unified tick with
    ``sample_epilogue="off"`` — the XLA final_logits+sampler oracle).
    The observables are the ISSUE's acceptance targets: p99 TTFT,
    decode tok/s, token parity between ALL legs, dispatches per tick
    (strictly fewer unified), and for the fused-vs-unfused pair on
    identical arrivals: Δhost_sync p99 + share, Δroofline utilization,
    and the one-fetch ceiling (host_fetches <= 1 per tick,
    trace-verified) — what ``tools/slo_gate.py --min-bandwidth-util``
    gates on live captures."""
    import jax.numpy as jnp
    import numpy as np

    from llm_np_cp_tpu.ops.sampling import Sampler
    from llm_np_cp_tpu.serve import ServeEngine, TraceRecorder, poisson_trace
    from tools.summarize_trace import mixed_utilization

    t0 = time.perf_counter()
    spec = SERVE_MIXED_CONFIGS[name]
    config, params = _build_model(spec["model"], tag=name, t0=t0)
    _phase(name, "params_built", t0)
    from llm_np_cp_tpu.ops.pallas.support import (
        kernel_error,
        ragged_kernel_name,
    )
    from llm_np_cp_tpu.serve.engine import pool_geometry

    bs = spec["block_size"]
    chunk = min(bs * 2, 256)
    _, num_blocks, max_seq_len = pool_geometry(
        spec["prompt_len"], spec["max_tokens"], spec["slots"], bs,
        prefill_chunk=chunk,
    )
    ragged_err = kernel_error(ragged_kernel_name(False))
    from llm_np_cp_tpu.serve.telemetry import TelemetryModel

    # one shared roofline model for both legs (immutable, config+params
    # derived): the legs record achieved GB/s / utilization / MFU so
    # tools/slo_gate.py --min-bandwidth-util can gate live captures
    telemetry = TelemetryModel(config, params)

    # long-prefill-heavy: prompts in the TOP half of the length range,
    # decode budgets mixed chat (short) + completion (long) — the shape
    # where a monolithic prefill visibly stalls the decode batch
    rng = np.random.default_rng(17)
    trace = poisson_trace(
        rng, spec["requests"], rate_rps=spec["rate"],
        prompt_len_range=(max(spec["prompt_len"] // 2, 2),
                          spec["prompt_len"]),
        max_new_tokens=(max(spec["max_tokens"] // 8, 1),
                        spec["max_tokens"]),
        vocab_size=config.vocab_size, seed_base=17,
    )
    _phase(name, "trace_built", t0)

    per_leg: dict = {}
    tokens_by_leg: dict = {}
    legs = (("split", "off", "auto"), ("mixed", "on", "auto"),
            ("mixed_xla_tail", "on", "off"))
    for leg, mode, epilogue in legs:
        # the fused-vs-unfused pair reads its host_sync column from the
        # trace plane (per-tick host_sync_us + the one-fetch ceiling)
        tracer = TraceRecorder() if mode == "on" else None
        engine = ServeEngine(
            params, config,
            sampler=Sampler(kind="greedy"),
            max_slots=spec["slots"],
            num_blocks=num_blocks,
            block_size=bs,
            max_seq_len=max_seq_len,
            prefill_chunk=chunk,
            cache_dtype=jnp.bfloat16,
            mixed_step=mode,
            sample_epilogue=epilogue,
            telemetry=telemetry,
            tracer=tracer,
        )
        engine.warmup([int(t["prompt"].size) for t in trace],
                      max_new_tokens=spec["max_tokens"])
        engine.n_dispatches = 0  # count the measured span only
        _phase(name, f"warmed_{leg}", t0)
        snap = engine.replay_trace(trace)
        _phase(name, f"trace_drained_{leg}", t0, ticks=snap["ticks"])
        tokens_by_leg[leg] = {
            r.req_id: list(r.generated)
            for r in engine.scheduler.finished
        }
        per_leg[leg] = {
            "ok": snap["finished"] == spec["requests"],
            "throughput_tok_s": round(snap["throughput_tok_s"], 1),
            "ttft_s_p50": round(snap.get("ttft_s_p50", float("nan")), 4),
            "ttft_s_p99": round(snap.get("ttft_s_p99", float("nan")), 4),
            "decode_tok_s_p50": round(snap.get("decode_tok_s_p50",
                                               float("nan")), 1),
            "ticks": snap["ticks"],
            "dispatches": engine.n_dispatches,
            "dispatches_per_tick": round(
                engine.n_dispatches / max(snap["ticks"], 1), 3
            ),
            "preemptions": snap["preemptions"],
            "mixed_prefill_tokens": snap["mixed_prefill_tokens"],
            "mixed_decode_tokens": snap["mixed_decode_tokens"],
            # roofline telemetry (CPU: the absolute GB/s is meaningless
            # — no HBM — but the fields prove the plumbing and give
            # slo_gate --min-bandwidth-util its input on live captures)
            "roofline_gbps_mean": round(
                snap.get("roofline_gbps_mean", 0.0), 4),
            "roofline_util_mean": round(
                snap.get("roofline_util_mean", 0.0), 8),
            "mfu_mean": round(snap.get("mfu_mean", 0.0), 8),
            "hbm_gbps": snap.get("hbm_gbps"),
            "compile_counts": engine.compile_counts(),
            "epilogue": engine.epilogue_impl,
        }
        if mode == "on":
            per_leg[leg]["ragged_attn_impl"] = engine.ragged_attn_impl
            per_leg[leg]["tick_token_budget"] = engine.tick_token_budget
            per_leg[leg]["buckets"] = list(engine.mixed_buckets)
            util = mixed_utilization(tracer.events()) or {}
            per_leg[leg]["host_sync_us_p99"] = round(
                util.get("host_sync_us_p99", 0.0), 1)
            per_leg[leg]["host_sync_share"] = round(
                util.get("host_sync_share", 0.0), 4)
            per_leg[leg]["host_fetches_max"] = util.get(
                "host_fetches_max", 0)
        del engine

    parity = tokens_by_leg["split"] == tokens_by_leg["mixed"]
    fused_parity = tokens_by_leg["mixed"] == tokens_by_leg["mixed_xla_tail"]
    m, s = per_leg["mixed"], per_leg["split"]
    xt = per_leg["mixed_xla_tail"]
    return {
        "config": name,
        "ok": (all(r["ok"] for r in per_leg.values()) and parity
               and fused_parity),
        "requests": spec["requests"],
        "rate_rps": spec["rate"],
        "slots": spec["slots"],
        "pool_blocks": num_blocks,
        "block_size": bs,
        "token_parity_mixed_vs_split": parity,
        # the tick-tail fusion pair: identical arrivals, fused epilogue
        # vs the XLA logits tail — token parity is the non-negotiable
        # bar, the deltas are the win (signs meaningful on live HBM;
        # on CPU the fields prove the plumbing)
        "token_parity_fused_vs_xla_tail": fused_parity,
        "epilogue": m["epilogue"],
        "host_sync_p99_delta_us": round(
            xt["host_sync_us_p99"] - m["host_sync_us_p99"], 1),
        "roofline_util_delta": round(
            m["roofline_util_mean"] - xt["roofline_util_mean"], 8),
        "host_fetches_max": m["host_fetches_max"],
        # headline: the unified tick's deltas on identical arrivals
        "ttft_s_p99": m["ttft_s_p99"],
        "ttft_s_p99_split": s["ttft_s_p99"],
        "decode_tok_s_p50": m["decode_tok_s_p50"],
        "decode_tok_s_p50_split": s["decode_tok_s_p50"],
        "throughput_tok_s": m["throughput_tok_s"],
        "dispatches_per_tick": m["dispatches_per_tick"],
        "dispatches_per_tick_split": s["dispatches_per_tick"],
        "dispatch_win": m["dispatches"] < s["dispatches"],
        # headline roofline mirror (the unified leg's — what
        # slo_gate --min-bandwidth-util consumes)
        "roofline_gbps_mean": m["roofline_gbps_mean"],
        "roofline_util_mean": m["roofline_util_mean"],
        "hbm_gbps": m["hbm_gbps"],
        "legs": per_leg,
        "ragged_kernel_probe": ragged_err or "ok",
    }


def run_serve_spec_config(name: str) -> dict:
    """Speculative serving vs plain unified tick: ONE Poisson arrival
    schedule over repetitive prompts (random patterns tiled to length —
    the extractive shape where prompt-lookup drafting pays) replayed
    through two engines of identical geometry — ``spec_k=0`` and
    ``spec_k=K`` with every request opted in.  Observables: acceptance
    rate and mean accept length, decode tok/s and p99 TTFT deltas on
    identical arrivals, TOKEN PARITY between the legs (the deterministic
    (seed, content-pos) verify keys make accepted streams byte-identical
    to plain decode), and dispatches-per-tick staying ~1 on the spec leg
    (drafting is host-side; verify lanes ride the one mixed dispatch).
    Both legs carry SLO trackers so ``tools/slo_gate.py`` can gate on
    the leg summaries (attainment/goodput/burn in the JSON)."""
    import jax.numpy as jnp
    import numpy as np

    from llm_np_cp_tpu.ops.sampling import Sampler
    from llm_np_cp_tpu.serve import ServeEngine, poisson_trace
    from llm_np_cp_tpu.serve.slo import SLOPolicy, SLOTracker

    t0 = time.perf_counter()
    spec = SERVE_SPEC_CONFIGS[name]
    config, params = _build_model(spec["model"], tag=name, t0=t0)
    _phase(name, "params_built", t0)
    from llm_np_cp_tpu.ops.pallas.support import (
        kernel_error,
        ragged_kernel_name,
    )
    from llm_np_cp_tpu.serve.engine import pool_geometry

    bs = spec["block_size"]
    chunk = min(bs * 2, 256)
    _, num_blocks, max_seq_len = pool_geometry(
        spec["prompt_len"], spec["max_tokens"], spec["slots"], bs,
        prefill_chunk=chunk,
    )
    ragged_err = kernel_error(ragged_kernel_name(False))
    from llm_np_cp_tpu.serve.telemetry import TelemetryModel

    telemetry = TelemetryModel(config, params)

    rng = np.random.default_rng(23)
    trace = poisson_trace(
        rng, spec["requests"], rate_rps=spec["rate"],
        prompt_len_range=(max(spec["prompt_len"] // 4, 2),
                          spec["prompt_len"]),
        max_new_tokens=spec["max_tokens"], vocab_size=config.vocab_size,
        seed_base=23,
    )
    # repetitive prompts: tile a small per-request random pattern to the
    # drawn length, so the suffix n-gram always has a prior occurrence
    # (the prompt-lookup draft's win case: quoting/extractive traffic)
    pat = spec["pattern_len"]
    for item in trace:
        base = rng.integers(1, config.vocab_size, size=pat,
                            dtype=np.int64).astype(np.int32)
        item["prompt"] = np.resize(base, item["prompt"].size)
    _phase(name, "trace_built", t0)

    per_leg: dict = {}
    tokens_by_leg: dict = {}
    for leg, k in (("plain", 0), ("spec", spec["spec_k"])):
        engine = ServeEngine(
            params, config,
            sampler=Sampler(kind="greedy"),
            max_slots=spec["slots"],
            num_blocks=num_blocks,
            block_size=bs,
            max_seq_len=max_seq_len,
            prefill_chunk=chunk,
            cache_dtype=jnp.bfloat16,
            mixed_step="on",
            spec_k=k,
            telemetry=telemetry,
        )
        engine.warmup([int(t["prompt"].size) for t in trace],
                      max_new_tokens=spec["max_tokens"])
        engine.metrics.slo = SLOTracker(
            SLOPolicy(ttft_s=5.0, tpot_s=1.0, target=0.99),
            clock=engine.clock,
        )
        engine.n_dispatches = 0  # count the measured span only
        _phase(name, f"warmed_{leg}", t0)
        leg_trace = [
            dict(item, speculative=k > 0) for item in trace
        ]
        snap = engine.replay_trace(leg_trace)
        _phase(name, f"trace_drained_{leg}", t0, ticks=snap["ticks"])
        tokens_by_leg[leg] = {
            r.req_id: list(r.generated)
            for r in engine.scheduler.finished
        }
        per_leg[leg] = {
            "ok": snap["finished"] == spec["requests"],
            "throughput_tok_s": round(snap["throughput_tok_s"], 1),
            "ttft_s_p50": round(snap.get("ttft_s_p50", float("nan")), 4),
            "ttft_s_p99": round(snap.get("ttft_s_p99", float("nan")), 4),
            "decode_tok_s_p50": round(snap.get("decode_tok_s_p50",
                                               float("nan")), 1),
            "ticks": snap["ticks"],
            "dispatches": engine.n_dispatches,
            "dispatches_per_tick": round(
                engine.n_dispatches / max(snap["ticks"], 1), 3
            ),
            "preemptions": snap["preemptions"],
            "goodput_tok_s": round(snap.get("goodput_tok_s", 0.0), 1),
            "slo_attainment": snap.get("slo_attainment"),
            "slo_burn_rate_5m": snap.get("slo_burn_rate_5m", 0.0),
            # roofline telemetry: on the spec leg the verify lanes ride
            # the same HBM sweep, so utilization per emitted token is
            # the whole speculative win made visible
            "roofline_gbps_mean": round(
                snap.get("roofline_gbps_mean", 0.0), 4),
            "roofline_util_mean": round(
                snap.get("roofline_util_mean", 0.0), 8),
            "mfu_mean": round(snap.get("mfu_mean", 0.0), 8),
            "hbm_gbps": snap.get("hbm_gbps"),
            "compile_counts": engine.compile_counts(),
        }
        if k:
            per_leg[leg].update({
                "spec_k": k,
                "spec_drafted_tokens": snap.get("spec_drafted_tokens", 0),
                "spec_accepted_tokens": snap.get("spec_accepted_tokens", 0),
                "acceptance_rate": round(
                    snap.get("spec_accept_rate", 0.0), 4
                ),
                "spec_accept_len_mean": round(
                    snap.get("spec_accept_len_mean", 0.0), 3
                ),
                "ragged_attn_impl": engine.ragged_attn_impl,
            })
        del engine
    parity = tokens_by_leg["plain"] == tokens_by_leg["spec"]
    p, s = per_leg["plain"], per_leg["spec"]
    return {
        "config": name,
        "ok": all(r["ok"] for r in per_leg.values()) and parity
        and s["spec_drafted_tokens"] > 0,
        "requests": spec["requests"],
        "rate_rps": spec["rate"],
        "slots": spec["slots"],
        "spec_k": spec["spec_k"],
        "token_parity_spec_vs_plain": parity,
        # headline: what a verify sweep buys on identical arrivals
        "acceptance_rate": s["acceptance_rate"],
        "spec_accept_len_mean": s["spec_accept_len_mean"],
        "throughput_tok_s": s["throughput_tok_s"],
        "throughput_tok_s_plain": p["throughput_tok_s"],
        "ttft_s_p99": s["ttft_s_p99"],
        "ttft_s_p99_plain": p["ttft_s_p99"],
        "decode_tok_s_p50": s["decode_tok_s_p50"],
        "decode_tok_s_p50_plain": p["decode_tok_s_p50"],
        "dispatches_per_tick": s["dispatches_per_tick"],
        "ticks_spec_vs_plain": [s["ticks"], p["ticks"]],
        # headline roofline mirror (the spec leg's — what
        # slo_gate --min-bandwidth-util consumes)
        "roofline_gbps_mean": s["roofline_gbps_mean"],
        "roofline_util_mean": s["roofline_util_mean"],
        "hbm_gbps": s["hbm_gbps"],
        "legs": per_leg,
        "ragged_kernel_probe": ragged_err or "ok",
    }


def run_serve_tier_config(name: str) -> dict:
    """Tiered KV prefix cache: the SAME capacity-stressed shared-prompt
    trace (prefix working set ~4x pool blocks; distinct prompts cycled
    so every repeat outlives its cached blocks) through two engines of
    identical geometry — ``host_tier=None`` (LRU reclaim drops, every
    repeat re-prefills) vs ``host_tier=HostTier(...)`` (reclaim spills
    to host RAM, repeats restore above the measured breakeven).  The
    observables are the ISSUE's acceptance targets: strictly higher
    prefix hit-rate and strictly fewer prefill tokens dispatched on the
    tier leg, restore-latency p99, p99 TTFT / tok/s deltas, token
    parity, and ``compiles_added_by_tier == 0``.  Both legs carry SLO
    trackers so ``tools/slo_gate.py`` can gate the leg summaries."""
    import math

    import jax.numpy as jnp
    import numpy as np

    from llm_np_cp_tpu.ops.sampling import Sampler
    from llm_np_cp_tpu.serve import ServeEngine, poisson_trace
    from llm_np_cp_tpu.serve.host_tier import HostTier
    from llm_np_cp_tpu.serve.slo import SLOPolicy, SLOTracker

    t0 = time.perf_counter()
    spec = SERVE_TIER_CONFIGS[name]
    config, params = _build_model(spec["model"], tag=name, t0=t0)
    _phase(name, "params_built", t0)
    from llm_np_cp_tpu.ops.pallas.support import (
        kernel_error,
        ragged_kernel_name,
    )

    bs = spec["block_size"]
    chunk = min(bs * 2, 256)
    num_blocks = spec["num_blocks"]  # deliberately capacity-starved
    max_seq_len = -(-(spec["prompt_len"] + spec["max_tokens"] + chunk)
                    // bs) * bs
    ragged_err = kernel_error(ragged_kernel_name(False))

    # uniform full-length prompts: every distinct prompt contributes
    # the same shareable block count, so the working-set ratio is exact
    rng = np.random.default_rng(29)
    trace = poisson_trace(
        rng, spec["requests"], rate_rps=spec["rate"],
        prompt_len_range=(spec["prompt_len"], spec["prompt_len"]),
        max_new_tokens=spec["max_tokens"], vocab_size=config.vocab_size,
        seed_base=29, distinct_prompts=spec["distinct_prompts"],
    )
    unit = math.lcm(bs, chunk) // bs
    w = -(-spec["prompt_len"] // chunk) * chunk
    keys_per_prompt = ((w - chunk) // (unit * bs)) * unit
    working_set = spec["distinct_prompts"] * keys_per_prompt
    _phase(name, "trace_built", t0, working_set_blocks=working_set,
           pool_capacity=num_blocks - 1)

    per_leg: dict = {}
    tokens_by_leg: dict = {}
    for leg in ("tier_off", "tier_on"):
        tier = HostTier(int(spec["tier_gb"] * 2**30)) \
            if leg == "tier_on" else None
        engine = ServeEngine(
            params, config,
            sampler=Sampler(kind="greedy"),
            max_slots=spec["slots"],
            num_blocks=num_blocks,
            block_size=bs,
            max_seq_len=max_seq_len,
            prefill_chunk=chunk,
            cache_dtype=jnp.bfloat16,
            mixed_step="on",
            enable_prefix_cache=True,
            host_tier=tier,
        )
        engine.warmup([int(t["prompt"].size) for t in trace],
                      max_new_tokens=spec["max_tokens"])
        warm_compiles = dict(engine.compile_counts())
        engine.metrics.slo = SLOTracker(
            SLOPolicy(ttft_s=5.0, tpot_s=1.0, target=0.99),
            clock=engine.clock,
        )
        engine.n_dispatches = 0  # count the measured span only
        _phase(name, f"warmed_{leg}", t0)
        snap = engine.replay_trace(trace)
        if tier is not None:
            tier.drain()
        _phase(name, f"trace_drained_{leg}", t0, ticks=snap["ticks"])
        tokens_by_leg[leg] = {
            r.req_id: list(r.generated)
            for r in engine.scheduler.finished
        }
        counts = engine.compile_counts()
        per_leg[leg] = {
            "ok": snap["finished"] == spec["requests"],
            "throughput_tok_s": round(snap["throughput_tok_s"], 1),
            "ttft_s_p50": round(snap.get("ttft_s_p50", float("nan")), 4),
            "ttft_s_p99": round(snap.get("ttft_s_p99", float("nan")), 4),
            "ticks": snap["ticks"],
            "preemptions": snap["preemptions"],
            "prefix_hit_rate": round(snap.get("prefix_hit_rate", 0.0), 4),
            "prefix_blocks_hit": snap.get("prefix_blocks_hit", 0),
            "prefix_evicted_blocks": snap.get("prefix_evicted_blocks", 0),
            "mixed_prefill_tokens": snap["mixed_prefill_tokens"],
            "goodput_tok_s": round(snap.get("goodput_tok_s", 0.0), 1),
            "slo_attainment": snap.get("slo_attainment"),
            "compile_counts": counts,
            "compiles_added_by_trace": (
                counts.get("mixed_step", 0)
                - warm_compiles.get("mixed_step", 0)
            ),
        }
        if tier is not None:
            st = tier.stats()
            per_leg[leg].update({
                "tier_spilled_blocks": st["spilled_blocks"],
                "tier_restored_blocks": st["restored_blocks"],
                "tier_restored_bytes": st["restored_bytes"],
                "tier_restore_misses": st["restore_misses"],
                "tier_skipped_blocks": st["skipped_blocks"],
                "tier_restore_s_p99": round(
                    snap.get("tier_restore_s_p99", 0.0), 6),
                "tier_breakeven_ratio": round(
                    snap.get("tier_breakeven_ratio", 0.0), 3),
                "tier_restore_gbps": round(st["restore_gbps"], 3),
            })
            tier.close()
        del engine
    parity = tokens_by_leg["tier_off"] == tokens_by_leg["tier_on"]
    off, on = per_leg["tier_off"], per_leg["tier_on"]
    hit_win = on["prefix_hit_rate"] > off["prefix_hit_rate"]
    prefill_win = (on["mixed_prefill_tokens"]
                   < off["mixed_prefill_tokens"])
    return {
        "config": name,
        "ok": (all(r["ok"] for r in per_leg.values()) and parity
               and hit_win and prefill_win
               and on["tier_restored_blocks"] > 0
               and on["compiles_added_by_trace"] == 0),
        "requests": spec["requests"],
        "rate_rps": spec["rate"],
        "slots": spec["slots"],
        "pool_blocks": num_blocks,
        "block_size": bs,
        "distinct_prompts": spec["distinct_prompts"],
        # the capacity stress in one number: shareable prefix blocks
        # the trace's working set needs over the pool's total blocks
        "working_set_over_capacity": round(
            working_set / max(num_blocks - 1, 1), 2),
        "token_parity_tier_vs_off": parity,
        # headline: what the host tier buys on identical arrivals
        "prefix_hit_rate": on["prefix_hit_rate"],
        "prefix_hit_rate_off": off["prefix_hit_rate"],
        "hit_rate_win": hit_win,
        "prefill_tokens": on["mixed_prefill_tokens"],
        "prefill_tokens_off": off["mixed_prefill_tokens"],
        "prefill_tokens_saved": (off["mixed_prefill_tokens"]
                                 - on["mixed_prefill_tokens"]),
        "restored_blocks": on["tier_restored_blocks"],
        "restored_bytes": on["tier_restored_bytes"],
        "restore_s_p99": on["tier_restore_s_p99"],
        "breakeven_ratio": on["tier_breakeven_ratio"],
        "ttft_s_p99": on["ttft_s_p99"],
        "ttft_s_p99_off": off["ttft_s_p99"],
        "throughput_tok_s": on["throughput_tok_s"],
        "throughput_tok_s_off": off["throughput_tok_s"],
        "compiles_added_by_tier": on["compiles_added_by_trace"],
        "legs": per_leg,
        "ragged_kernel_probe": ragged_err or "ok",
    }


def run_serve_tenant_config(name: str) -> dict:
    """Multi-tenant fairness: three per-tenant Poisson processes at
    skewed rates merged into ONE arrival schedule, replayed twice on
    one engine geometry — fairness off vs on — reporting per-tenant
    attainment / goodput / cost share from the TenantLedger, mean
    first-token ranks (the ordinal view of the prefill reorder), token
    parity between the legs, and zero added compiles."""
    import jax.numpy as jnp
    import numpy as np

    from llm_np_cp_tpu.ops.sampling import Sampler
    from llm_np_cp_tpu.serve import ServeEngine, TenantLedger, poisson_trace
    from llm_np_cp_tpu.serve.engine import pool_geometry
    from llm_np_cp_tpu.serve.slo import SLOPolicy, SLOTracker

    t0 = time.perf_counter()
    spec = SERVE_TENANT_CONFIGS[name]
    config, params = _build_model(spec["model"], tag=name, t0=t0)
    _phase(name, "params_built", t0)

    bs = spec["block_size"]
    chunk = min(bs * 2, 256)
    tenants = spec["tenants"]
    max_prompt = max(t["prompt_len"] for t in tenants.values())
    max_new = max(t["max_tokens"] for t in tenants.values())
    _, num_blocks, max_seq_len = pool_geometry(
        max_prompt, max_new, spec["slots"], bs, prefill_chunk=chunk,
    )

    # one rng per tenant: each tenant is its OWN Poisson process at its
    # own rate (seed offsets keep per-request sampler seeds unique);
    # the merged, arrival-sorted schedule is identical for both legs
    trace: list[dict] = []
    for idx, (tenant, tspec) in enumerate(sorted(tenants.items())):
        rng = np.random.default_rng(31 + idx)
        sub = poisson_trace(
            rng, tspec["requests"], rate_rps=tspec["rate"],
            prompt_len_range=(max(tspec["prompt_len"] // 2, 1),
                              tspec["prompt_len"]),
            max_new_tokens=tspec["max_tokens"],
            vocab_size=config.vocab_size,
            seed_base=31 + 1000 * idx,
        )
        trace.extend(dict(item, tenant=tenant) for item in sub)
    trace.sort(key=lambda item: item["arrival_s"])
    n_requests = len(trace)
    _phase(name, "trace_built", t0, requests=n_requests)

    per_leg: dict = {}
    tokens_by_leg: dict = {}
    for leg in ("fair_off", "fair_on"):
        ledger = TenantLedger(
            fairness=(leg == "fair_on"),
            policy=SLOPolicy(ttft_s=5.0, tpot_s=1.0, target=0.99),
        )
        engine = ServeEngine(
            params, config,
            sampler=Sampler(kind="greedy"),
            max_slots=spec["slots"],
            num_blocks=num_blocks,
            block_size=bs,
            max_seq_len=max_seq_len,
            prefill_chunk=chunk,
            cache_dtype=jnp.bfloat16,
            mixed_step="on",
            tenants=ledger,
        )
        ledger.clock = engine.clock
        engine.warmup([int(t["prompt"].size) for t in trace],
                      max_new_tokens=max_new)
        warm_compiles = dict(engine.compile_counts())
        engine.metrics.slo = SLOTracker(ledger.policy, clock=engine.clock)
        _phase(name, f"warmed_{leg}", t0)
        snap = engine.replay_trace(trace)
        _phase(name, f"trace_drained_{leg}", t0, ticks=snap["ticks"])
        finished = list(engine.scheduler.finished)
        tokens_by_leg[leg] = {
            r.req_id: list(r.generated) for r in finished
        }
        # ordinal fairness observable: each tenant's mean rank in
        # first-token order — reorder wins survive CPU clock noise
        ranked = sorted(
            (r for r in finished if r.first_token_time is not None),
            key=lambda r: r.first_token_time,
        )
        ranks: dict[str, list[int]] = {}
        for rank, r in enumerate(ranked):
            ranks.setdefault(r.tenant, []).append(rank)
        ten_detail: dict[str, dict] = {}
        for tenant, ent in ledger.snapshot()["tenants"].items():
            d: dict = {
                "requests": ent["requests"],
                "tokens": ent["tokens"],
                "cost_share": round(ent["cost_share"], 4),
                "throttled": ent["throttled"],
                "first_token_rank_mean": round(
                    sum(ranks.get(tenant, [0]))
                    / max(len(ranks.get(tenant, [])), 1), 2),
            }
            if "slo" in ent:
                d["slo_attainment"] = ent["slo"].get("slo_attainment")
                d["goodput_tok_s"] = round(
                    ent["slo"].get("goodput_tok_s", 0.0), 1)
            ten_detail[tenant] = d
        counts = engine.compile_counts()
        per_leg[leg] = {
            "ok": (snap["finished"] == n_requests
                   and set(ten_detail) == set(tenants)
                   and all(ten_detail[t]["requests"]
                           == tenants[t]["requests"] for t in tenants)),
            "throughput_tok_s": round(snap["throughput_tok_s"], 1),
            "ttft_s_p50": round(snap.get("ttft_s_p50", float("nan")), 4),
            "ttft_s_p99": round(snap.get("ttft_s_p99", float("nan")), 4),
            "ticks": snap["ticks"],
            "goodput_tok_s": round(snap.get("goodput_tok_s", 0.0), 1),
            "slo_attainment": snap.get("slo_attainment"),
            "compiles_added_by_trace": (
                counts.get("mixed_step", 0)
                - warm_compiles.get("mixed_step", 0)
            ),
            "tenants": ten_detail,
        }
        del engine
    parity = tokens_by_leg["fair_off"] == tokens_by_leg["fair_on"]
    off, on = per_leg["fair_off"], per_leg["fair_on"]

    def worst_att(leg: dict) -> float | None:
        atts = [d["slo_attainment"] for d in leg["tenants"].values()
                if d.get("slo_attainment") is not None]
        return min(atts) if atts else None

    return {
        "config": name,
        "ok": (all(r["ok"] for r in per_leg.values()) and parity
               and off["compiles_added_by_trace"] == 0
               and on["compiles_added_by_trace"] == 0),
        "requests": n_requests,
        "slots": spec["slots"],
        "pool_blocks": num_blocks,
        "block_size": bs,
        "tenant_mix": {
            t: dict(requests=ts["requests"], rate_rps=ts["rate"])
            for t, ts in sorted(tenants.items())
        },
        "token_parity_fair_vs_off": parity,
        # headline: worst tenant's attainment with/without fairness —
        # what tools/slo_gate.py --min-tenant-attainment consumes
        "worst_tenant_attainment": worst_att(on),
        "worst_tenant_attainment_off": worst_att(off),
        "throughput_tok_s": on["throughput_tok_s"],
        "throughput_tok_s_off": off["throughput_tok_s"],
        "ttft_s_p99": on["ttft_s_p99"],
        "ttft_s_p99_off": off["ttft_s_p99"],
        "compiles_added_by_fairness": on["compiles_added_by_trace"],
        "legs": per_leg,
    }


# the per-chip decode rate of the last live hardware capture — the
# reference every sharded leg's tok_s_per_chip is ratioed against so
# the next live-TPU window reads scaling efficiency straight off the
# JSON (CPU runs record the ratio too; it is meaningless there and
# labeled as such by backend)
LIVE_REF_TOK_S_PER_CHIP = 1629.0
LIVE_REF_SOURCE = "BENCH_TPU_LIVE_r4"


def run_serve_sharded_config(name: str) -> dict:
    """Mesh-sharded serving: the SAME shared-prompt Poisson trace over
    three topologies — single chip, TP=N (one engine, kv-head-sharded
    paged pool), DP x TP replicas behind the prefix-affinity router —
    reporting per-chip tok/s (vs the live capture reference), p99 TTFT,
    token parity across every leg, and the router's routed/spilled
    verdicts with the fleet prefix hit rate."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from llm_np_cp_tpu.ops.sampling import Sampler
    from llm_np_cp_tpu.parallel.sharding import MeshPlan
    from llm_np_cp_tpu.serve import ReplicaSet, ServeEngine, poisson_trace
    from llm_np_cp_tpu.serve.engine import pool_geometry

    t0 = time.perf_counter()
    spec = SERVE_SHARDED_CONFIGS[name]
    config, params = _build_model(spec["model"], tag=name, t0=t0)
    _phase(name, "params_built", t0)

    bs = spec["block_size"]
    chunk = min(bs * 2, 256)
    _, sized_blocks, max_seq_len = pool_geometry(
        spec["prompt_len"], spec["max_tokens"], spec["slots"], bs,
        prefill_chunk=chunk,
    )
    num_blocks = sized_blocks + spec.get("extra_blocks", 0)
    n_dev = jax.device_count()
    tp = spec["tp"]
    dp_replicas, dp_tp = spec["dp"]

    rng = np.random.default_rng(13)
    trace = poisson_trace(
        rng, spec["requests"], rate_rps=spec["rate"],
        prompt_len_range=(max(spec["prompt_len"] // 4, 1),
                          spec["prompt_len"]),
        max_new_tokens=spec["max_tokens"], vocab_size=config.vocab_size,
        seed_base=13, distinct_prompts=spec.get("distinct_prompts"),
    )
    lens = [int(t["prompt"].size) for t in trace]
    _phase(name, "trace_built", t0)

    from llm_np_cp_tpu.serve.slo import SLOPolicy, SLOTracker

    # goodput/attainment/burn recorded per topology leg (fleet legs
    # aggregate across replicas via ReplicaSet.snapshot); ok never
    # depends on the attainment VALUE on a CPU child
    slo_policy = SLOPolicy(ttft_s=spec.get("slo_ttft", 2.5),
                           tpot_s=spec.get("slo_tpot", 1.0))

    def build_engine(plan, devices):
        eng = ServeEngine(
            params, config,
            sampler=Sampler(kind="greedy"),
            max_slots=spec["slots"],
            num_blocks=num_blocks,
            block_size=bs,
            max_seq_len=max_seq_len,
            prefill_chunk=chunk,
            cache_dtype=jnp.bfloat16,
            enable_prefix_cache=spec.get("prefix_cache", False),
            mixed_step="auto",
            mesh_plan=plan,
            mesh_devices=devices,
        )
        eng.metrics.slo = SLOTracker(slo_policy, clock=eng.clock)
        return eng

    legs = {
        "single": dict(chips=1, replicas=1, tp=1),
        "tp": dict(chips=tp, replicas=1, tp=tp),
        "dp_tp": dict(chips=dp_replicas * dp_tp, replicas=dp_replicas,
                      tp=dp_tp),
    }
    per_leg: dict = {}
    tokens_by_leg: dict = {}
    for leg, shape in legs.items():
        if shape["chips"] > n_dev:
            per_leg[leg] = {
                "ok": True,
                "skipped": f"needs {shape['chips']} devices, "
                           f"have {n_dev}",
            }
            continue
        plan = MeshPlan(model=shape["tp"]) if shape["tp"] > 1 else None
        devices = jax.devices()
        per = shape["tp"]
        engines = [
            build_engine(
                plan,
                devices[i * per:(i + 1) * per] if plan is not None
                else None,
            )
            for i in range(shape["replicas"])
        ]
        for e in engines:
            e.warmup(lens, max_new_tokens=spec["max_tokens"])
        _phase(name, f"warmed_{leg}", t0, chips=shape["chips"])
        if shape["replicas"] > 1:
            fleet = ReplicaSet(engines)
            snap = fleet.replay_trace(trace)
            tokens_by_leg[leg] = {
                r.req_id: list(r.generated) for r in fleet.finished
            }
            router = {
                "router_routed": snap["router_routed"],
                "router_spilled": snap["router_spilled"],
            }
            compile_counts = engines[0].compile_counts()
        else:
            snap = engines[0].replay_trace(trace)
            tokens_by_leg[leg] = {
                r.req_id: list(r.generated)
                for r in engines[0].scheduler.finished
            }
            router = {}
            compile_counts = engines[0].compile_counts()
        _phase(name, f"trace_drained_{leg}", t0, ticks=snap["ticks"])
        tok_s = snap["throughput_tok_s"]
        per_leg[leg] = {
            "ok": snap["finished"] == spec["requests"],
            "chips": shape["chips"],
            "mesh": engines[0].mesh_desc,
            "throughput_tok_s": round(tok_s, 1),
            "tok_s_per_chip": round(tok_s / shape["chips"], 1),
            "tok_s_per_chip_vs_live_ref": round(
                tok_s / shape["chips"] / LIVE_REF_TOK_S_PER_CHIP, 4
            ),
            "ttft_s_p50": round(snap.get("ttft_s_p50", float("nan")), 4),
            "ttft_s_p99": round(snap.get("ttft_s_p99", float("nan")), 4),
            "prefix_hit_rate": round(snap["prefix_hit_rate"], 3)
            if "prefix_hit_rate" in snap else None,
            "slo_attainment": round(
                snap.get("slo_attainment", float("nan")), 4),
            "goodput_tok_s": round(snap.get("goodput_tok_s", 0.0), 1),
            "slo_burn_rate_5m": round(
                snap.get("slo_burn_rate_5m", 0.0), 3),
            "ticks": snap["ticks"],
            "compile_counts": compile_counts,
            **router,
        }
        del engines
    ran = {k: v for k, v in per_leg.items() if "skipped" not in v}
    # ordered per-request parity: request ids are assigned in submission
    # order in every leg (single engine and ReplicaSet both), so keying
    # by id catches a cross-request stream swap that a multiset compare
    # would miss — exactly the routing/recovery bug class this config
    # exists to surface
    streams = {
        leg: tuple(
            tuple(tokens_by_leg[leg][rid])
            for rid in sorted(tokens_by_leg[leg])
        )
        for leg in tokens_by_leg
    }
    parity = len(set(streams.values())) <= 1
    headline = (per_leg.get("dp_tp") if "dp_tp" in ran
                else per_leg.get("tp") if "tp" in ran
                else per_leg["single"])
    return {
        "config": name,
        "ok": all(r["ok"] for r in per_leg.values()) and parity
        and bool(ran),
        "backend": jax.default_backend(),
        "devices": n_dev,
        "requests": spec["requests"],
        "rate_rps": spec["rate"],
        "slots": spec["slots"],
        "pool_blocks": num_blocks,
        "block_size": bs,
        "distinct_prompts": spec.get("distinct_prompts"),
        "token_parity_across_legs": parity,
        "tok_s_per_chip": headline.get("tok_s_per_chip"),
        "ttft_s_p99": headline.get("ttft_s_p99"),
        "slo_ttft_s": slo_policy.ttft_s,
        "slo_tpot_s": slo_policy.tpot_s,
        "slo_attainment": headline.get("slo_attainment"),
        "goodput_tok_s": headline.get("goodput_tok_s"),
        "live_ref": {
            "tok_s_per_chip": LIVE_REF_TOK_S_PER_CHIP,
            "source": LIVE_REF_SOURCE,
            "comparable": jax.default_backend() == "tpu",
        },
        "legs": per_leg,
    }


def _client_pct(vals: list, q: float) -> float:
    """Client-observed-TTFT percentile — the SAME estimator as
    ServeMetrics._pcts (np.percentile linear interpolation), shared by
    the HTTP and chaos legs: a different one would fold estimator
    mismatch into the deltas those configs exist to measure."""
    import numpy as np

    return float(np.percentile(vals, q)) if vals else float("nan")


def _run_http_trace_leg(
    engine, model_id: str, trace: list, *, client_timeout: float,
    retries: int = 3, backoff_s: float = 0.25, scrape: bool = False,
    server_kwargs: dict | None = None,
) -> tuple[list, dict, str | None]:
    """One realtime HTTP replay of ``trace``: in-process HttpServer, one
    SSE client per request sleeping until its arrival time (with
    transient 429/503 retry — a queue blip must not burn the leg, and a
    retried request's TTFT honestly carries the added wait), an optional
    Prometheus scrape before drain, and the runner's supervision stats.
    The ONE leg runner shared by the HTTP-overhead and chaos configs so
    their client machinery cannot drift."""
    import asyncio

    from llm_np_cp_tpu.serve.http.client import astream_completion, http_get
    from llm_np_cp_tpu.serve.http.server import HttpServer

    async def leg():
        server = HttpServer(engine, model_id=model_id, drain_timeout=60.0,
                            **(server_kwargs or {}))
        await server.start("127.0.0.1", 0)

        async def one(item):
            await asyncio.sleep(item["arrival_s"])
            return await astream_completion(
                server.host, server.port,
                {"model": model_id,
                 "prompt": [int(t) for t in item["prompt"]],
                 "max_tokens": item["max_new_tokens"],
                 "seed": item.get("seed", 0)},
                timeout=client_timeout, retries=retries,
                backoff_s=backoff_s,
            )

        results = await asyncio.gather(*(one(item) for item in trace))
        prom = None
        if scrape:
            loop = asyncio.get_running_loop()
            _, raw = await loop.run_in_executor(
                None, http_get, server.host, server.port, "/metrics")
            prom = raw.decode()
        runner = server.runner
        stats = {
            "restarts": runner.restarts,
            "recovery_latency_s": [
                round(v, 4) for v in runner.recovery_latency_s
            ],
            "decode_impl_final": runner.engine.decode_attn_impl,
            "compile_counts": runner.engine.compile_counts(),
        }
        server.begin_drain()
        await server.serve_until_shutdown()
        return list(results), stats, prom

    return asyncio.run(leg())


def run_serve_http_config(name: str) -> dict:
    """HTTP front-end overhead: ONE engine, the SAME Poisson trace, two
    realtime replays — direct ``ServeEngine`` calls, then the in-process
    asyncio HTTP server driven by SSE streaming clients at the same
    arrival times.  The delta between the two legs' TTFT/throughput is
    the HTTP layer's cost (event loop, bridge queues, SSE framing) —
    measured, not guessed.  The HTTP leg's TTFT is CLIENT-observed
    (request sent → first SSE chunk parsed), which is what a user sees.
    """
    import jax.numpy as jnp
    import numpy as np

    from llm_np_cp_tpu.ops.sampling import Sampler
    from llm_np_cp_tpu.serve import ServeEngine, ServeMetrics, poisson_trace
    from llm_np_cp_tpu.serve.engine import pool_geometry

    t0 = time.perf_counter()
    spec = SERVE_HTTP_CONFIGS[name]
    config, params = _build_model(spec["model"], tag=name, t0=t0)
    _phase(name, "params_built", t0)

    bs = spec["block_size"]
    chunk = min(bs * 2, 256)
    _, num_blocks, max_seq_len = pool_geometry(
        spec["prompt_len"], spec["max_tokens"], spec["slots"], bs,
        prefill_chunk=chunk,
    )
    engine = ServeEngine(
        params, config,
        sampler=Sampler(kind="greedy"),
        max_slots=spec["slots"],
        num_blocks=num_blocks,
        block_size=bs,
        max_seq_len=max_seq_len,
        prefill_chunk=chunk,
        cache_dtype=jnp.bfloat16,
    )
    # SLO goodput accounting rides every leg: generous CPU-scale
    # targets (this records attainment/goodput/burn alongside tok/s —
    # tools/slo_gate.py gates live-TPU runs on them; ok never depends
    # on the attainment VALUE, only on the plumbing)
    from llm_np_cp_tpu.serve.slo import SLOPolicy, SLOTracker

    slo_policy = SLOPolicy(ttft_s=spec.get("slo_ttft", 2.5),
                           tpot_s=spec.get("slo_tpot", 1.0))

    def fresh_metrics():
        m = ServeMetrics(clock=engine.clock)
        m.slo = SLOTracker(slo_policy, clock=engine.clock)
        return m

    engine.metrics = fresh_metrics()
    rng = np.random.default_rng(13)
    trace = poisson_trace(
        rng, spec["requests"], rate_rps=spec["rate"],
        prompt_len_range=(max(spec["prompt_len"] // 4, 1),
                          spec["prompt_len"]),
        max_new_tokens=spec["max_tokens"], vocab_size=config.vocab_size,
        seed_base=13,
    )
    engine.warmup([int(t["prompt"].size) for t in trace],
                  max_new_tokens=spec["max_tokens"])
    _phase(name, "warmed", t0)

    # leg 1: direct engine calls at wall-clock arrival pacing — the
    # no-HTTP baseline every client-observed number compares against
    direct = engine.replay_trace(trace, realtime=True)
    direct_tokens = {
        r.req_id: list(r.generated) for r in engine.scheduler.finished
    }
    _phase(name, "direct_done", t0, ticks=direct["ticks"])

    # leg 2: same trace through the HTTP server, one SSE client per
    # request sleeping until its arrival time
    engine.metrics = fresh_metrics()
    engine.scheduler.finished.clear()
    results, _http_stats, prom = _run_http_trace_leg(
        engine, spec["model"], trace,
        client_timeout=TIMEOUTS.get(name, DEFAULT_TIMEOUT) / 2,
        scrape=True,
    )
    _phase(name, "http_done", t0)

    http_ok = [r for r in results if r["status"] == 200]
    parity = all(
        r["token_ids"] == direct_tokens.get(rid, None)
        for rid, r in zip(sorted(direct_tokens), http_ok)
    ) if len(http_ok) == len(direct_tokens) else False
    ttft_http = [r["ttft_s"] for r in http_ok if r["ttft_s"]]
    http_snap = engine.metrics.snapshot()
    pct = _client_pct
    d_p50 = direct.get("ttft_s_p50", float("nan"))
    d_p99 = direct.get("ttft_s_p99", float("nan"))
    h_p50, h_p99 = pct(ttft_http, 50), pct(ttft_http, 99)

    # leg 3: tracing overhead — the SAME trace, direct realtime replay
    # again but with a TraceRecorder attached (request spans + tick
    # phases + profiler annotations live).  The delta vs the untraced
    # direct leg is what --trace-out costs a production replay; it must
    # stay small or the instrument perturbs what it measures.
    import shutil
    import tempfile

    from llm_np_cp_tpu.serve.request_log import RequestLog, read_request_log
    from llm_np_cp_tpu.serve.tracing import TraceRecorder

    engine.metrics = fresh_metrics()
    engine.scheduler.finished.clear()
    engine.tracer = TraceRecorder(ring=500_000)
    # the canonical request log rides the traced leg: one JSON line per
    # terminal, asserted consistent with the metrics the same leg
    # recorded (request-log ↔ metrics parity)
    rl_dir = tempfile.mkdtemp(prefix="serve_http_rl_")
    rl_path = os.path.join(rl_dir, "requests.jsonl")
    engine.request_log = RequestLog(rl_path)
    traced = engine.replay_trace(trace, realtime=True)
    # ids keep counting across legs — compare token streams in submit
    # order (both legs replay the same arrivals through submit())
    trace_parity = (
        [t for _, t in sorted(
            (r.req_id, r.generated) for r in engine.scheduler.finished)]
        == [direct_tokens[k] for k in sorted(direct_tokens)]
    )
    n_trace_events = len(engine.tracer)
    engine.tracer = None
    # request-log ↔ metrics parity: the wide-event lines and the
    # metrics snapshot were recorded by the SAME leg, so their counts
    # must agree exactly — one line per terminal, reasons matching the
    # finish_reasons counters, token totals matching, every line
    # carrying a trace id and an SLO verdict
    engine.request_log.flush(10.0)
    log_lines = read_request_log(rl_path)
    engine.request_log.close()
    engine.request_log = None
    shutil.rmtree(rl_dir, ignore_errors=True)
    from collections import Counter as _Counter

    traced_snap = traced
    log_reasons = dict(_Counter(ln["reason"] for ln in log_lines))
    request_log_parity = (
        len(log_lines) == traced_snap["finished"] + traced_snap["aborted"]
        and log_reasons == traced_snap["finish_reasons"]
        and sum(ln["new_tokens"] for ln in log_lines)
        == traced_snap["total_generated_tokens"]
        and all(ln.get("trace") for ln in log_lines)
        and all("slo" in ln for ln in log_lines)
    )
    _phase(name, "traced_done", t0, events=n_trace_events,
           log_lines=len(log_lines))
    t_p99 = traced.get("ttft_s_p99", float("nan"))
    trace_tok_delta = round(
        direct["throughput_tok_s"] - traced["throughput_tok_s"], 1)
    trace_p99_delta = round(t_p99 - d_p99, 4)
    # generous bounds — this guards against a broken hot path (tracing
    # turning ticks into seconds), not against scheduler jitter
    trace_overhead_small = (
        traced["throughput_tok_s"] >= 0.7 * direct["throughput_tok_s"]
        and (t_p99 - d_p99) < max(0.25, d_p99)
    )
    return {
        "config": name,
        "ok": (direct["finished"] == spec["requests"]
               and len(http_ok) == spec["requests"] and parity
               and traced["finished"] == spec["requests"]
               and trace_parity and trace_overhead_small
               and request_log_parity),
        "requests": spec["requests"],
        "rate_rps": spec["rate"],
        "slots": spec["slots"],
        "pool_blocks": num_blocks,
        "block_size": bs,
        "token_parity_http_vs_direct": parity,
        "ttft_s_p50_direct": round(d_p50, 4),
        "ttft_s_p99_direct": round(d_p99, 4),
        "ttft_s_p50_http": round(h_p50, 4),
        "ttft_s_p99_http": round(h_p99, 4),
        # the headline: what the HTTP layer costs a request's TTFT
        "http_ttft_overhead_s_p50": round(h_p50 - d_p50, 4),
        "http_ttft_overhead_s_p99": round(h_p99 - d_p99, 4),
        "throughput_tok_s_direct": round(direct["throughput_tok_s"], 1),
        "throughput_tok_s_http": round(http_snap["throughput_tok_s"], 1),
        "metrics_scrape_ok": "llm_serve_requests_finished_total" in prom,
        # the traced leg: what request-lifecycle tracing costs
        "throughput_tok_s_traced": round(traced["throughput_tok_s"], 1),
        "ttft_s_p99_traced": round(t_p99, 4),
        "trace_overhead_tok_s": trace_tok_delta,
        "trace_overhead_ttft_p99_s": trace_p99_delta,
        "trace_overhead_small": trace_overhead_small,
        "trace_events": n_trace_events,
        "trace_token_parity": trace_parity,
        # SLO goodput accounting (the slo_gate.py observables — the
        # HTTP leg is the headline; per-leg values alongside)
        "slo_ttft_s": slo_policy.ttft_s,
        "slo_tpot_s": slo_policy.tpot_s,
        "slo_attainment": round(http_snap.get("slo_attainment",
                                              float("nan")), 4),
        "goodput_tok_s": round(http_snap.get("goodput_tok_s", 0.0), 1),
        "slo_burn_rate_5m": round(
            http_snap.get("slo_burn_rate_5m", 0.0), 3),
        "slo_burn_rate_1h": round(
            http_snap.get("slo_burn_rate_1h", 0.0), 3),
        "slo_attainment_direct": round(direct.get("slo_attainment",
                                                  float("nan")), 4),
        "goodput_tok_s_direct": round(direct.get("goodput_tok_s", 0.0), 1),
        "slo_attainment_traced": round(traced.get("slo_attainment",
                                                  float("nan")), 4),
        "goodput_tok_s_traced": round(traced.get("goodput_tok_s", 0.0), 1),
        # canonical request log (traced leg)
        "request_log_lines": len(log_lines),
        "request_log_parity": request_log_parity,
        "compile_counts": engine.compile_counts(),
    }


def run_serve_chaos_config(name: str) -> dict:
    """Supervised recovery under fault injection: the SAME Poisson trace
    through the HTTP server twice — a clean leg, then a chaos leg with a
    seeded fault schedule (tick-thread crash + paged dispatch fault) and
    ``max_restarts=3`` supervision.  Reports recovery latency, restart
    count, p99 TTFT degradation vs clean, and token parity (recovered
    streams must be token-identical — the teacher-forced replay
    contract).  The clean leg is also the "chaos disabled = unchanged
    numbers" reference for the injection points' zero-overhead claim."""
    import jax.numpy as jnp
    import numpy as np

    from llm_np_cp_tpu.ops.pallas.support import (
        kernel_error,
        paged_kernel_name,
    )
    from llm_np_cp_tpu.ops.sampling import Sampler
    from llm_np_cp_tpu.serve import FaultInjector, ServeEngine, poisson_trace
    from llm_np_cp_tpu.serve.engine import pool_geometry

    t0 = time.perf_counter()
    spec = SERVE_CHAOS_CONFIGS[name]
    config, params = _build_model(spec["model"], tag=name, t0=t0)
    _phase(name, "params_built", t0)

    bs = spec["block_size"]
    chunk = min(bs * 2, 256)
    _, num_blocks, max_seq_len = pool_geometry(
        spec["prompt_len"], spec["max_tokens"], spec["slots"], bs,
        prefill_chunk=chunk,
    )
    # paged when the probe passes: the chaos 'decode' fault then
    # exercises the runtime gather fallback; on gather it exercises a
    # second supervised restart instead — both are recovery paths
    impl = "paged" if kernel_error(paged_kernel_name(False)) is None \
        else "xla"
    rng = np.random.default_rng(13)
    trace = poisson_trace(
        rng, spec["requests"], rate_rps=spec["rate"],
        prompt_len_range=(max(spec["prompt_len"] // 4, 1),
                          spec["prompt_len"]),
        max_new_tokens=spec["max_tokens"], vocab_size=config.vocab_size,
        seed_base=13,
    )
    _phase(name, "trace_built", t0)

    def build_engine(injector):
        engine = ServeEngine(
            params, config,
            sampler=Sampler(kind="greedy"),
            max_slots=spec["slots"],
            num_blocks=num_blocks,
            block_size=bs,
            max_seq_len=max_seq_len,
            prefill_chunk=chunk,
            cache_dtype=jnp.bfloat16,
            decode_attn_impl=impl,
            fault_injector=injector,
        )
        engine.warmup([int(t["prompt"].size) for t in trace],
                      max_new_tokens=spec["max_tokens"])
        return engine

    def run_leg(engine, tag):
        results, stats, _ = _run_http_trace_leg(
            engine, spec["model"], trace,
            client_timeout=TIMEOUTS.get(name, DEFAULT_TIMEOUT) / 3,
            retries=4, backoff_s=0.1,
            server_kwargs=dict(
                tick_deadline=spec.get("tick_deadline"),
                max_restarts=3,
                restart_backoff_s=spec.get("backoff", 0.2),
            ),
        )
        _phase(name, f"{tag}_done", t0, restarts=stats["restarts"])
        ok = [r for r in results if r["status"] == 200]
        ttft = [r["ttft_s"] for r in ok if r["ttft_s"]]
        return results, ok, ttft, stats

    clean_results, clean_ok, clean_ttft, clean_stats = run_leg(
        build_engine(None), "clean")
    clean_tokens = [r["token_ids"] for r in clean_results]

    injector = FaultInjector(spec["chaos"], seed=13)
    chaos_results, chaos_ok, chaos_ttft, chaos_stats = run_leg(
        build_engine(injector), "chaos")
    parity = [r["token_ids"] for r in chaos_results] == clean_tokens

    c50, c99 = _client_pct(clean_ttft, 50), _client_pct(clean_ttft, 99)
    x50, x99 = _client_pct(chaos_ttft, 50), _client_pct(chaos_ttft, 99)
    recov = chaos_stats["recovery_latency_s"]
    return {
        "config": name,
        "ok": (len(clean_ok) == spec["requests"]
               and len(chaos_ok) == spec["requests"]
               and parity and chaos_stats["restarts"] >= 1),
        "requests": spec["requests"],
        "rate_rps": spec["rate"],
        "slots": spec["slots"],
        "pool_blocks": num_blocks,
        "block_size": bs,
        "attn_impl": impl,
        "chaos_spec": spec["chaos"],
        # every request completed despite the schedule, token-identically
        "token_parity_chaos_vs_clean": parity,
        "restarts": chaos_stats["restarts"],
        "faults_injected": injector.snapshot(),
        "client_retries_total": sum(
            r.get("retries", 0) for r in chaos_results
        ),
        # the headline pair: what an engine death costs
        "recovery_latency_s": recov,
        "recovery_latency_s_max": max(recov) if recov else None,
        "ttft_s_p50_clean": round(c50, 4),
        "ttft_s_p99_clean": round(c99, 4),
        "ttft_s_p50_chaos": round(x50, 4),
        "ttft_s_p99_chaos": round(x99, 4),
        "chaos_ttft_p99_degradation_s": round(x99 - c99, 4),
        "decode_impl_final": chaos_stats["decode_impl_final"],
        # restart must not recompile: decode stays at its one program
        "compile_counts": chaos_stats["compile_counts"],
        "compile_counts_clean": clean_stats["compile_counts"],
    }


def _spawn_serve_proc(spec, tmp, tag, *, port=0, journal=None,
                      journal_sync=None, chaos=None, timeout=600.0):
    """Spawn tools/serve_proc.py (deterministic random-weight model, so
    a restarted process serves the identical model) and wait for its
    port file → ``(proc, host, port)``."""
    pf = os.path.join(tmp, f"port_{tag}")
    cmd = [
        sys.executable, os.path.join(REPO, "tools", "serve_proc.py"),
        "--model", spec["model"], "--port", str(port), "--port-file", pf,
        "--slots", str(spec["slots"]),
        "--block-size", str(spec["block_size"]),
        "--prompt-len", str(spec["prompt_len"]),
        "--max-tokens", str(spec["max_tokens"]),
    ]
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        cmd += ["--platform", plat]
    if journal:
        cmd += ["--journal", journal]
    if journal_sync:
        cmd += ["--journal-sync", journal_sync]
    if chaos:
        cmd += ["--chaos", chaos]
    log_path = os.path.join(tmp, f"log_{tag}")
    proc = subprocess.Popen(cmd, stdout=open(log_path, "w"),
                            stderr=subprocess.STDOUT, cwd=REPO)
    deadline = time.time() + timeout
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"serve_proc {tag} died at startup: "
                + open(log_path).read()[-1500:])
        if os.path.exists(pf):
            host, port_s = open(pf).read().split()
            return proc, host, int(port_s)
        time.sleep(0.1)
    proc.kill()
    raise RuntimeError(f"serve_proc {tag} never wrote its port file")


def run_serve_restart_config(name: str) -> dict:
    """kill -9 durability: REAL server subprocesses, one Poisson trace,
    four legs — plain (no journal), journaled (the overhead leg: the
    client tok/s delta + the writer thread's fsync p99 IS the journal's
    cost), journaled with ``--journal-sync admission`` (the strict
    mode's cost: one synchronous admission fsync before each stream
    starts), and a kill leg (chaos ``proc_kill`` SIGKILLs the server
    mid-decode; the parent respawns it on the same port + journal and
    every client resumes its stream via Last-Event-ID).  Token parity
    across ALL legs is the teacher-forced replay contract applied to
    process death."""
    import asyncio
    import re as _re
    import signal as _signal
    import tempfile

    import numpy as np

    from llm_np_cp_tpu.config import LLAMA_3_2_1B, tiny_config
    from llm_np_cp_tpu.serve import poisson_trace, scan_journal
    from llm_np_cp_tpu.serve.http.client import (
        astream_completion,
        http_get,
    )

    t0 = time.perf_counter()
    spec = SERVE_RESTART_CONFIGS[name]
    config = {"llama1b": LLAMA_3_2_1B,
              "tiny": tiny_config("llama")}[spec["model"]]
    rng = np.random.default_rng(13)
    trace = poisson_trace(
        rng, spec["requests"], rate_rps=spec["rate"],
        prompt_len_range=(max(spec["prompt_len"] // 4, 1),
                          spec["prompt_len"]),
        max_new_tokens=spec["max_tokens"], vocab_size=config.vocab_size,
        seed_base=13,
    )
    client_timeout = TIMEOUTS.get(name, DEFAULT_TIMEOUT) / 4

    def drive(host, port, *, retries):
        async def leg():
            async def one(item):
                await asyncio.sleep(item["arrival_s"])
                return await astream_completion(
                    host, port,
                    {"model": spec["model"],
                     "prompt": [int(t) for t in item["prompt"]],
                     "max_tokens": item["max_new_tokens"],
                     "seed": item.get("seed", 0)},
                    timeout=client_timeout, retries=retries,
                    backoff_s=0.3, max_backoff_s=2.0,
                )
            t_leg = time.perf_counter()
            results = await asyncio.gather(
                *(one(item) for item in trace))
            return results, time.perf_counter() - t_leg
        return asyncio.run(leg())

    def leg_stats(results, wall):
        ok = [r for r in results if r["status"] == 200]
        ttft = [r["ttft_s"] for r in ok if r["ttft_s"]]
        toks = sum(len(r["token_ids"]) for r in ok)
        return {
            "completed": len(ok),
            "client_tok_s": round(toks / wall, 1) if wall > 0 else 0.0,
            "ttft_s_p50": round(_client_pct(ttft, 50), 4),
            "ttft_s_p99": round(_client_pct(ttft, 99), 4),
        }

    tmp = tempfile.mkdtemp(prefix="serve_restart_")

    def scrape(host, port, pattern):
        _, raw = http_get(host, port, "/metrics")
        m = _re.search(pattern, raw.decode(), _re.M)
        return float(m.group(1)) if m else None

    # -- leg 1: plain (no journal) — the baseline every delta reads from
    proc, host, port = _spawn_serve_proc(spec, tmp, "plain")
    try:
        plain_results, plain_wall = drive(host, port, retries=2)
    finally:
        proc.send_signal(_signal.SIGTERM)
        proc.wait(timeout=90)
    plain_tokens = [r["token_ids"] for r in plain_results]
    _phase(name, "plain_done", t0)

    # -- leg 2: journaled — same trace; the delta is the journal's cost
    j_overhead = os.path.join(tmp, "overhead.journal")
    proc, host, port = _spawn_serve_proc(
        spec, tmp, "journaled", journal=j_overhead)
    try:
        jr_results, jr_wall = drive(host, port, retries=2)
        fsync_p99 = scrape(host, port,
                           r"^llm_serve_journal_fsync_p99_s (\S+)")
        records = scrape(host, port,
                         r"^llm_serve_journal_records_total (\S+)")
    finally:
        proc.send_signal(_signal.SIGTERM)
        proc.wait(timeout=90)
    journaled_parity = [r["token_ids"] for r in jr_results] == plain_tokens
    _phase(name, "journaled_done", t0)

    # -- leg 2b: strict-durability journal (--journal-sync admission —
    # every admission record fsyncs BEFORE its stream starts, closing
    # the async-fsync admission-loss window); the delta vs the async
    # journaled leg is what the strict mode costs
    j_sync = os.path.join(tmp, "sync.journal")
    proc, host, port = _spawn_serve_proc(
        spec, tmp, "journaled_sync", journal=j_sync,
        journal_sync="admission")
    try:
        js_results, js_wall = drive(host, port, retries=2)
        sync_fsync_p99 = scrape(host, port,
                                r"^llm_serve_journal_fsync_p99_s (\S+)")
    finally:
        proc.send_signal(_signal.SIGTERM)
        proc.wait(timeout=90)
    sync_parity = [r["token_ids"] for r in js_results] == plain_tokens
    _phase(name, "journaled_sync_done", t0)

    # -- leg 3: kill -9 mid-decode, respawn on the same port + journal,
    # clients resume via Last-Event-ID
    j_kill = os.path.join(tmp, "kill.journal")
    proc1, host, port = _spawn_serve_proc(
        spec, tmp, "kill", journal=j_kill,
        chaos=f"proc_kill@{spec['kill_tick']}")
    killed_at: dict = {}
    respawned: dict = {}

    def respawn_when_dead():
        proc1.wait()
        killed_at["t"] = time.perf_counter()
        p2, h2, pt2 = _spawn_serve_proc(
            spec, tmp, "restart", port=port, journal=j_kill)
        respawned["proc"] = p2

    import threading

    watcher = threading.Thread(target=respawn_when_dead, daemon=True)
    watcher.start()
    try:
        try:
            kill_results, kill_wall = drive(host, port, retries=12)
        finally:
            watcher.join(timeout=client_timeout)
            proc2 = respawned.get("proc")
        if proc2 is None:
            raise RuntimeError("restart server never came up")
        journal_replayed = scrape(
            host, port, r"^llm_serve_journal_replayed_total (\S+)")
        journal_resumed = scrape(
            host, port, r"^llm_serve_journal_resumed_total (\S+)")
        proc2.send_signal(_signal.SIGTERM)
        proc2.wait(timeout=90)
    finally:
        # never leak a warm model server past the child, whatever
        # failed above (proc_kill not firing, client timeouts, ...)
        for p in (proc1, respawned.get("proc")):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=30)
    _phase(name, "kill_done", t0, restarts=1)

    kill_parity = [r["token_ids"] for r in kill_results] == plain_tokens
    resumed = [r for r in kill_results if r.get("resumed")]
    resume_lat = sorted(r["resume_latency_s"] for r in resumed
                        if r.get("resume_latency_s"))
    live, _, epoch = scan_journal(j_kill)
    plain_stats = leg_stats(plain_results, plain_wall)
    jr_stats = leg_stats(jr_results, jr_wall)
    js_stats = leg_stats(js_results, js_wall)
    overhead_tok_s = round(
        plain_stats["client_tok_s"] - jr_stats["client_tok_s"], 1)
    # generous: this guards a broken hot path (fsync on the tick
    # thread), not scheduler jitter on a loaded host
    overhead_ok = (
        jr_stats["client_tok_s"] >= 0.5 * plain_stats["client_tok_s"]
    )
    # the strict mode pays one synchronous fsync per ADMISSION (not per
    # token), so its throughput floor is looser but still a floor: a
    # broken implementation fsyncing per tick/token would crater it
    sync_overhead_ok = (
        js_stats["client_tok_s"] >= 0.3 * plain_stats["client_tok_s"]
    )
    n = spec["requests"]
    return {
        "config": name,
        "ok": (plain_stats["completed"] == n
               and jr_stats["completed"] == n
               and js_stats["completed"] == n
               and len([r for r in kill_results if r["status"] == 200]) == n
               and journaled_parity and kill_parity and sync_parity
               and bool(resumed) and overhead_ok and sync_overhead_ok
               and proc1.returncode == -_signal.SIGKILL
               and live == {}),
        "requests": n,
        "rate_rps": spec["rate"],
        "kill_tick": spec["kill_tick"],
        # journal overhead (the journaled-vs-plain pair)
        "token_parity_journaled_vs_plain": journaled_parity,
        "client_tok_s_plain": plain_stats["client_tok_s"],
        "client_tok_s_journaled": jr_stats["client_tok_s"],
        "journal_overhead_tok_s": overhead_tok_s,
        "journal_overhead_ok": overhead_ok,
        "journal_fsync_p99_s": fsync_p99,
        "journal_records": records,
        "ttft_s_p99_plain": plain_stats["ttft_s_p99"],
        "ttft_s_p99_journaled": jr_stats["ttft_s_p99"],
        # strict admission-fsync mode (--journal-sync admission)
        "token_parity_sync_vs_plain": sync_parity,
        "client_tok_s_journaled_sync": js_stats["client_tok_s"],
        "sync_admission_overhead_tok_s": round(
            jr_stats["client_tok_s"] - js_stats["client_tok_s"], 1),
        "sync_admission_overhead_ok": sync_overhead_ok,
        "ttft_s_p99_journaled_sync": js_stats["ttft_s_p99"],
        "journal_fsync_p99_s_sync": sync_fsync_p99,
        # the kill -9 headline
        "token_parity_across_kill": kill_parity,
        "streams_resumed": len(resumed),
        "restart_to_first_resumed_token_s": (
            round(resume_lat[0], 3) if resume_lat else None),
        "resume_latency_s_max": (
            round(resume_lat[-1], 3) if resume_lat else None),
        "journal_replayed_total": journal_replayed,
        "journal_resumed_total": journal_resumed,
        "journal_epoch_final": epoch,
        "drain_left_unterminated": len(live),
    }


def run_serve_rolling_config(name: str) -> dict:
    """Zero-downtime rolling upgrade: ONE Poisson trace over a
    direct-mode 3-replica fleet, replayed twice on identical arrivals —
    steady (no roll) vs rolling (a full replica-by-replica weight swap
    triggered mid-trace).  The swap drains each replica's in-flight
    streams to peers (teacher-forced — token parity across the roll is
    the drain contract), rebuilds it on the "new" checkpoint via
    clone_fresh (same params object here: the zero-compile same-shape
    case, pinned), and rejoins routing.  ``ttft_p99_degradation`` and
    ``dropped_streams`` are what ``tools/slo_gate.py
    --max-p99-ttft-degradation`` gates in CI."""
    import jax.numpy as jnp
    import numpy as np

    from llm_np_cp_tpu.ops.sampling import Sampler
    from llm_np_cp_tpu.serve import (
        LifecycleController,
        ReplicaSet,
        ServeEngine,
        SLOPolicy,
        SLOTracker,
        poisson_trace,
    )
    from llm_np_cp_tpu.serve.engine import pool_geometry
    from llm_np_cp_tpu.serve.trace import replay_arrivals

    t0 = time.perf_counter()
    spec = SERVE_ROLLING_CONFIGS[name]
    config, params = _build_model(spec["model"], tag=name, t0=t0)
    _phase(name, "params_built", t0)
    bs = spec["block_size"]
    chunk = min(bs * 2, 256)
    _, num_blocks, max_seq_len = pool_geometry(
        spec["prompt_len"], spec["max_tokens"], spec["slots"], bs,
        prefill_chunk=chunk,
    )
    rng = np.random.default_rng(29)
    trace = poisson_trace(
        rng, spec["requests"], rate_rps=spec["rate"],
        prompt_len_range=(max(spec["prompt_len"] // 4, 1),
                          spec["prompt_len"]),
        max_new_tokens=spec["max_tokens"], vocab_size=config.vocab_size,
        seed_base=29,
    )
    lens = [int(t["prompt"].size) for t in trace]
    _phase(name, "trace_built", t0)

    def build_fleet() -> ReplicaSet:
        engines = []
        for _ in range(spec["replicas"]):
            e = ServeEngine(
                params, config,
                sampler=Sampler(kind="greedy"),
                max_slots=spec["slots"],
                num_blocks=num_blocks,
                block_size=bs,
                max_seq_len=max_seq_len,
                prefill_chunk=chunk,
                cache_dtype=jnp.bfloat16,
                mixed_step="auto",
            )
            e.warmup(lens, max_new_tokens=spec["max_tokens"])
            e.metrics.slo = SLOTracker(
                SLOPolicy(ttft_s=2.5, tpot_s=2.5), clock=e.clock,
            )
            engines.append(e)
        return ReplicaSet(engines)

    def leg_stats(snap) -> dict:
        return {
            "ok": snap["finished"] == spec["requests"],
            "finished": snap["finished"],
            "throughput_tok_s": round(snap["throughput_tok_s"], 1),
            "ttft_s_p50": round(snap.get("ttft_s_p50", float("nan")), 4),
            "ttft_s_p99": round(snap.get("ttft_s_p99", float("nan")), 4),
            "slo_attainment": snap.get("slo_attainment", float("nan")),
            "goodput_tok_s": round(snap.get("goodput_tok_s", 0.0), 1),
            "slo_burn_rate_5m": snap.get("slo_burn_rate_5m", 0.0),
            "router_routed": snap["router_routed"],
            "router_spilled": snap["router_spilled"],
        }

    # -- leg 1: steady (no roll) — the baseline every delta reads from
    steady_fleet = build_fleet()
    _phase(name, "warmed_steady", t0)
    snap_s = steady_fleet.replay_trace(trace)
    steady_tokens = [list(r.generated) for r in steady_fleet.finished]
    steady = leg_stats(snap_s)
    del steady_fleet  # free its pools before the measured rolling leg
    _phase(name, "steady_done", t0)

    # -- leg 2: rolling — same arrivals, a full fleet roll mid-trace
    fleet = build_fleet()
    controller = LifecycleController(fleet)
    _phase(name, "warmed_rolling", t0)
    rolled: dict = {}

    def on_tick(i: int) -> None:
        if i == spec["roll_after_ticks"] and not rolled:
            rolled.update(controller.rolling_upgrade(
                lambda: params, version=1, steps_between=1,
            ))

    # process-global counter, not engines[0]'s cache sizes: a compile
    # on a not-yet-rolled peer (or on a callable the roll then
    # discards) must count too
    from tools.compile_counter import CompileCounter

    with CompileCounter().watch() as roll_counter:
        snap_r = replay_arrivals(fleet, trace, fleet.snapshot,
                                 on_tick=on_tick)
    _phase(name, "rolling_done", t0, ticks=snap_r["ticks"])
    rolling_tokens = [list(r.generated) for r in fleet.finished]
    rolling = leg_stats(snap_r)
    compiles_added = roll_counter.count
    parity = rolling_tokens == steady_tokens
    dropped = spec["requests"] - snap_r["finished"]
    deg = (
        rolling["ttft_s_p99"] / steady["ttft_s_p99"]
        if steady["ttft_s_p99"] else float("nan")
    )
    lifecycle = {}
    for e in fleet.engines:
        for k, v in e.metrics.snapshot().get(
                "lifecycle_actions", {}).items():
            lifecycle[k] = lifecycle.get(k, 0) + v
    versions = snap_r["weights_versions"]
    return {
        "config": name,
        "ok": (steady["ok"] and rolling["ok"] and parity
               and bool(rolled) and dropped == 0
               and compiles_added == 0
               and all(v == 1 for v in versions)),
        "requests": spec["requests"],
        "rate_rps": spec["rate"],
        "replicas": spec["replicas"],
        "roll_after_ticks": spec["roll_after_ticks"],
        "rolled": rolled.get("rolled"),
        "drained_streams": rolled.get("drained"),
        "dropped_streams": dropped,
        "token_parity_across_roll": parity,
        # the headline pair slo_gate consumes
        "ttft_p99_degradation": round(deg, 3),
        "compiles_added_by_roll": compiles_added,
        "compile_counts": dict(fleet.engines[0].compile_counts()),
        "weights_versions": versions,
        "lifecycle_actions": lifecycle,
        "legs": {"steady": steady, "rolling": rolling},
    }


def run_spec_config(name: str) -> dict:
    import numpy as np

    from llm_np_cp_tpu.ops.sampling import Sampler
    from llm_np_cp_tpu.speculative import SpeculativeGenerator

    t_start = time.perf_counter()
    spec = SPEC_CONFIGS[name]
    config, params = _build_model(spec["model"], tag=name, t0=t_start)
    _phase(name, "params_built", t_start)
    # draft selection: default int8 self-draft; "int4" = int4 self-draft
    # (¼ the weight stream); "truncN_int4" = layer-skip draft (first N
    # layers of the target, int4 — speculative.truncated_draft)
    draft = spec.get("draft")
    kwargs = {}
    if draft == "int4":
        from llm_np_cp_tpu.quant import quantize_params

        kwargs["draft_params"] = quantize_params(params, bits=4)
    elif draft and draft.startswith("trunc"):
        from llm_np_cp_tpu.speculative import truncated_draft

        n_layers = int(draft.removeprefix("trunc").split("_")[0])
        bits = 4 if draft.endswith("int4") else None
        dp, dc = truncated_draft(params, config, n_layers, bits=bits)
        kwargs.update(draft_params=dp, draft_config=dc)
    gen = SpeculativeGenerator(
        params, config, gamma=spec["gamma"], sampler=Sampler(kind="greedy"),
        **kwargs,
    )
    batch, prompt_len, decode_tokens = spec["batch"], spec["prompt_len"], spec["decode_tokens"]
    rng = np.random.default_rng(0)

    def one(prompt_host, tag):
        res = gen.generate(prompt_host, decode_tokens)
        _phase(name, f"{tag}:done", t_start)
        return {
            "rate": res.decode_tokens_per_s,
            "acc": res.acceptance_rate,
            "chain": int(res.tokens.sum()),
        }

    _, runs = _chained_reps(
        one, rng.integers(0, config.vocab_size, (batch, prompt_len)),
        config.vocab_size,
    )
    rates = [r["rate"] for r in runs]
    acc = [r["acc"] for r in runs]
    return {
        "config": name,
        "ok": True,
        "decode_tok_s_chip": round(float(np.median(rates)), 1),
        "per_seq_tok_s": round(float(np.median(rates)) / batch, 1),
        "acceptance_rate": round(float(np.median(acc)), 3),
        "gamma": spec["gamma"],
        "draft": spec.get("draft", "int8_self"),
        "batch": batch,
        "prompt_len": prompt_len,
        "decode_tokens": decode_tokens,
    }


def run_warm() -> dict:
    """AOT-compile every decode/prefill config's programs from ABSTRACT
    shapes (jax.eval_shape params — no weight init, no transfer, no
    execution) to populate the persistent compilation cache.  One warm
    pass makes every subsequent measured run (including the driver's)
    hit warm compiles — the r2 evidence says cold compile is what burns
    the per-config budget: the one config with cache entries (bs=1)
    finished, the cold ones (bs=8/32) timed out.
    """
    import jax
    import jax.numpy as jnp

    from llm_np_cp_tpu.cache import KVCache, align_capacity
    from llm_np_cp_tpu.config import GEMMA_2_2B, LLAMA_3_2_1B, LLAMA_3_2_3B, tiny_config
    from llm_np_cp_tpu.generate import make_decode_loop_fn, make_prefill_fn
    from llm_np_cp_tpu.models.transformer import init_params
    from llm_np_cp_tpu.ops.sampling import Sampler

    t0 = time.perf_counter()
    configs = {
        "llama1b": LLAMA_3_2_1B, "llama3b": LLAMA_3_2_3B,
        "gemma2_2b": GEMMA_2_2B, "tiny": tiny_config("llama"),
    }
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    done, failed = [], []
    # PRIORITY order: a partial warm (timeout) still covers the headline.
    # Spec/ragged configs build their programs inside Generator classes
    # and aren't abstractly warmable here; they pay their own compiles.
    # BENCH_WARM_LIMIT=N (parent sets it under a tight deadline) warms
    # only the first N priority configs so measurement starts sooner —
    # later configs pay their own compile out of their own timeout.
    warm_limit = int(os.environ.get("BENCH_WARM_LIMIT", "0")) or None
    warmable = [
        n for n in PRIORITY
        if n not in SPEC_CONFIGS and n not in EXTRA_CHILDREN
        and n not in RAGGED_CONFIGS and n not in SERVE_CONFIGS
        and n not in SERVE_HTTP_CONFIGS and n not in SERVE_CHAOS_CONFIGS
        and n not in SERVE_MIXED_CONFIGS and n not in SERVE_SPEC_CONFIGS
        and n not in SERVE_SHARDED_CONFIGS
        and n not in SERVE_RESTART_CONFIGS
        and n not in SERVE_ROLLING_CONFIGS
        and n not in SERVE_TIER_CONFIGS
        and n not in SERVE_TENANT_CONFIGS
    ]
    for name in warmable[:warm_limit]:
        spec = {**DECODE_CONFIGS, **PREFILL_CONFIGS}[name]
        config = configs[spec["model"]]

        def _abstract_params(cfg=config, quant=spec.get("quant", False)):
            params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.bfloat16)
            if quant:
                from llm_np_cp_tpu.quant import quantize_params

                params = quantize_params(
                    params, bits=4 if str(quant).startswith("int4") else 8,
                    act_quant=str(quant).endswith("_a8"),
                )
            return params

        params = jax.eval_shape(_abstract_params)
        sampler = Sampler(kind=spec.get("sampler", "greedy"))
        batch = spec.get("batch", 1)
        prompt_len = spec["prompt_len"]
        decode_tokens = spec.get("decode_tokens")
        # keep in lockstep with _measure_decode's capacity sizing
        max_seq = align_capacity(prompt_len + (decode_tokens or 0) + 8)
        cdt = jnp.int8 if spec.get("cache_dtype") == "int8" else jnp.bfloat16
        cache = jax.eval_shape(
            lambda c=config, b=batch, m=max_seq, dt=cdt: KVCache.init(
                c, b, m, dtype=dt
            )
        )
        ids = jax.ShapeDtypeStruct((batch, prompt_len), jnp.int32)
        # per-config env (e.g. LLMTPU_SCAN_UNROLL) is read at TRACE time,
        # so it must be live while lowering or this warms the wrong
        # program and the measured child compiles cold
        saved_env = {
            k: os.environ.get(k) for k in (spec.get("env") or {})
        }
        os.environ.update(spec.get("env") or {})
        try:
            chunk = spec.get("chunk")
            if chunk:
                # chunked prefill = one chunk-wide program; warm the SAME
                # jitted step the measured path dispatches (its exposed
                # chunk_step — logits-only, donated cache), not a
                # make_prefill_fn lowered at the chunk shape, which is a
                # different program and misses the cache (ADVICE r3 #2)
                from llm_np_cp_tpu.generate import make_chunked_prefill_fn

                ids = jax.ShapeDtypeStruct((batch, chunk), jnp.int32)
                chunked = make_chunked_prefill_fn(
                    config, sampler, chunk_size=chunk,
                    attn_impl=spec.get("attn_impl", "xla"),
                )
                chunked.chunk_step.lower(params, ids, cache).compile()
            else:
                prefill = make_prefill_fn(
                    config, sampler, attn_impl=spec.get("attn_impl", "xla")
                )
                prefill.lower(params, ids, cache, key).compile()
            _phase("warm", f"{name}:prefill", t0)
            if decode_tokens:
                loop = make_decode_loop_fn(
                    config, sampler, attn_impl=spec.get("decode_attn", "xla")
                )
                tok = jax.ShapeDtypeStruct((batch,), jnp.int32)
                loop.lower(params, tok, cache, key, decode_tokens).compile()
                # the half-length dispatch of the marginal-rate measurement
                loop.lower(
                    params, tok, cache, key, _half_len(decode_tokens)
                ).compile()
                _phase("warm", f"{name}:decode_loop", t0)
            done.append(name)
        except Exception as e:  # record and keep warming the rest
            failed.append({"config": name, "error": repr(e)[:300]})
            _phase("warm", f"{name}:FAILED", t0)
        finally:
            for k, v in saved_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    # Ragged configs dispatch Generator-owned programs: the SAME factories
    # with ragged (attn_mask, pad_offsets) operands and n-1 step loops.
    # Lowering identical HLO here hits the shared XLA compilation cache,
    # so the measured child's 600 s isn't spent on the [8, 4096] prefill
    # compile.  Skipped under BENCH_WARM_LIMIT (tight deadline).
    for name in [] if warm_limit else [n for n in PRIORITY if n in RAGGED_CONFIGS]:
        spec = RAGGED_CONFIGS[name]
        config = configs[spec["model"]]
        lens = spec.get("lens", RAGGED_LENS)
        n_full = spec.get("decode", RAGGED_DECODE)
        b, s = len(lens), max(lens)
        cap = align_capacity(s + n_full)
        try:
            params = jax.eval_shape(
                lambda cfg=config: init_params(
                    jax.random.PRNGKey(0), cfg, dtype=jnp.bfloat16
                )
            )
            cache = jax.eval_shape(
                lambda cfg=config, m=cap: KVCache.init(cfg, b, m, dtype=jnp.bfloat16)
            )
            sampler = Sampler(kind="greedy")
            ids = jax.ShapeDtypeStruct((b, s), jnp.int32)
            mask = jax.ShapeDtypeStruct((b, s), jnp.bool_)
            pads = jax.ShapeDtypeStruct((b,), jnp.int32)
            prefill = make_prefill_fn(config, sampler)
            prefill.lower(params, ids, cache, key, mask, pads).compile()
            _phase("warm", f"{name}:prefill", t0)
            loop = make_decode_loop_fn(config, sampler, attn_impl=spec["attn"])
            tok = jax.ShapeDtypeStruct((b,), jnp.int32)
            for n_steps in (n_full - 1, max(n_full // 2, 1) - 1):
                if n_steps > 0:
                    loop.lower(params, tok, cache, key, n_steps, pads).compile()
            _phase("warm", f"{name}:decode_loop", t0)
            done.append(name)
        except Exception as e:
            failed.append({"config": name, "error": repr(e)[:300]})
            _phase("warm", f"{name}:FAILED", t0)

    return {
        "config": "warm",
        "ok": not failed,
        "warmed": done,
        "failed": failed,
        "total_s": round(time.perf_counter() - t0, 1),
    }


def run_decomp() -> dict:
    """Locate the int8 roofline gap (VERDICT r4 weak #4 / task 6).

    int8_bs8 achieved 47.5% of HBM roofline vs bf16's 63% — the absolute
    per-step times imply a fixed ~1.9 ms/step that doesn't shrink with
    the weight stream.  This child separates the two directly: the decode
    step is timed at FULL and HALF layer depth (the truncated model is a
    prefix of the full one — speculative.truncated_draft), so

        per_layer_ms = (t_full − t_half) / (L − L/2)
        fixed_ms     = t_full − per_layer_ms · L

    plus the lm_head matmul timed alone.  If per_layer_ms tracks the
    weight stream at roofline, the gap is the fixed part (head, sampling,
    cache update, dispatch) — that's what to attack; if not, the quant
    einsum itself is the blocker.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from llm_np_cp_tpu.generate import make_decode_loop_fn, make_prefill_fn
    from llm_np_cp_tpu.models.transformer import final_logits
    from llm_np_cp_tpu.ops.sampling import Sampler
    from llm_np_cp_tpu.quant import quantize_params
    from llm_np_cp_tpu.speculative import truncated_draft

    t0 = time.perf_counter()
    batch = int(os.environ.get("DECOMP_BATCH", "8"))
    prompt_len, decode_tokens = 128, 128
    model = os.environ.get("DECOMP_MODEL", "llama1b")
    config, params = _build_model(model, tag="decomp", t0=t0)
    sampler = Sampler(kind="greedy")
    out = {"config": "decomp", "ok": True, "model": model, "batch": batch}
    full_l = config.num_hidden_layers
    half_l = max(full_l // 2, 1)

    for mode in ("bf16", "int8", "int8_a8"):
        p = (
            params if mode == "bf16"
            else quantize_params(params, act_quant=mode == "int8_a8")
        )
        rates: dict[int, tuple[float, str]] = {}
        for n_layers in (full_l, half_l):
            pl_, cl = (
                (p, config) if n_layers == full_l
                else truncated_draft(p, config, n_layers)
            )
            prefill = make_prefill_fn(cl, sampler)
            loop = make_decode_loop_fn(cl, sampler)
            _, rate, _, marginal = _measure_decode(
                f"decomp_{mode}_L{n_layers}", cl, pl_, prefill, loop,
                batch, prompt_len, decode_tokens, reps=2, t_start=t0,
            )
            # marginal (transport-cancelled) when available: decomposition
            # needs on-chip step time, not tunnel RTT
            rates[n_layers] = (
                (marginal, "marginal") if marginal is not None else (rate, "e2e")
            )
        step_full_ms = 1000.0 * batch / rates[full_l][0]
        step_half_ms = 1000.0 * batch / rates[half_l][0]
        out[mode] = {
            "step_ms": round(step_full_ms, 3),
            "step_half_ms": round(step_half_ms, 3),
            "layers": [full_l, half_l],
            "rate_sources": [rates[full_l][1], rates[half_l][1]],
        }
        # the fixed-vs-per-layer split is only meaningful when BOTH depths
        # are transport-cancelled — mixing an on-chip number with an
        # RTT-inclusive one would put the transport into fixed_ms, the
        # very thing the decomposition isolates
        if full_l > half_l and rates[full_l][1] == rates[half_l][1] == "marginal":
            per_layer_ms = (step_full_ms - step_half_ms) / (full_l - half_l)
            out[mode].update(
                per_layer_ms=round(per_layer_ms, 4),
                fixed_ms=round(step_full_ms - per_layer_ms * full_l, 3),
            )
        else:
            out[mode]["decomposition"] = (
                "skipped: marginal rate unavailable at one or both depths"
                if full_l > half_l
                else "skipped: single-layer model has no depth contrast"
            )

    # lm_head alone, via the same two-length marginal trick the decode
    # measurement uses (a single dispatch is ~tunnel-RTT no matter how
    # small): fused loops of 8 vs 4 head matmuls, serialized by a data
    # dependence so XLA can't hoist the matmul, marginal = Δt/4.
    def _head_loop(n):
        def body(i, carry):
            logits = final_logits(params, carry, config, last_only=True)
            nudge = jnp.tanh(jnp.mean(logits) * 1e-3) * 1e-3
            return carry * (1.0 + nudge).astype(carry.dtype)

        return jax.jit(
            lambda x0: jnp.sum(jax.lax.fori_loop(0, n, body, x0))
        )

    head8, head4 = _head_loop(8), _head_loop(4)

    def one_head(seed, tag):
        x0 = jnp.full(
            (batch, 1, config.hidden_size), 1.0 + (seed % 7) / 7.0, jnp.bfloat16
        )
        t1 = time.perf_counter()
        np.asarray(head8(x0))
        t2 = time.perf_counter()
        np.asarray(head4(x0))
        t3 = time.perf_counter()
        _phase("decomp", f"{tag}:head_done", t0)
        return {"d8": t2 - t1, "d4": t3 - t2, "chain": seed + 1}

    _, runs = _chained_reps(one_head, 1, 10**9)
    out["lm_head_ms"] = round(
        1000.0 * float(np.median([r["d8"] - r["d4"] for r in runs])) / 4, 3
    )
    out["total_s"] = round(time.perf_counter() - t0, 1)
    return out


def run_kernels() -> dict:
    """Mosaic compile probe for every Pallas kernel on the live backend
    (VERDICT r3 task 2): tiny-shape compile+run each, record ok/error.
    The same probes back Generator's runtime downgrade-to-XLA gate
    (ops/pallas/support.py); this child makes the verdict a bench
    artifact."""
    import jax

    from llm_np_cp_tpu.ops.pallas import support

    t0 = time.perf_counter()
    out = {"config": "kernels", "backend": jax.default_backend()}
    failed = []
    for kernel in ("softmax", "flash_attention", "decode_attention",
                   "decode_attention_int8"):
        err = support.kernel_error(kernel)
        out[kernel] = "ok" if err is None else f"FAIL: {err[:300]}"
        if err is not None:
            failed.append(kernel)
    out["ok"] = not failed
    out["total_s"] = round(time.perf_counter() - t0, 1)
    return out


def run_quality() -> dict:
    """Quantization quality evidence (VERDICT r3 task 4): greedy
    divergence step + teacher-forced logit error per quant mode on the
    tiny fixture.  Deterministic and backend-independent — the parent
    runs it on CPU so it lands even when the TPU tunnel is down."""
    import jax
    import jax.numpy as jnp

    from llm_np_cp_tpu.config import tiny_config
    from llm_np_cp_tpu.models.transformer import init_params
    from llm_np_cp_tpu.utils.quality import MODES, quant_quality

    t0 = time.perf_counter()
    cfg = tiny_config("llama")
    params = init_params(jax.random.PRNGKey(7), cfg, dtype=jnp.float32)
    out = {"config": "quality", "ok": True, "fixture": "tiny_llama_seed7"}
    for mode in MODES:
        _phase("quality", mode, t0)
        out[mode] = {
            k: v for k, v in quant_quality(cfg, params, mode, steps=128).items()
            if k not in ("mode",)
        }
    out["total_s"] = round(time.perf_counter() - t0, 1)
    return out


def run_probe() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    t0 = time.perf_counter()
    x = jnp.ones((256, 256), jnp.bfloat16)
    s = float(np.asarray(x @ x).sum())
    return {
        "ok": True,
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "matmul_sum": s,
        "probe_s": round(time.perf_counter() - t0, 2),
    }


def child_main(mode: str) -> None:
    _child_jax()
    if mode == "probe":
        out = run_probe()
    elif mode == "warm":
        out = run_warm()
    elif mode == "kernels":
        out = run_kernels()
    elif mode == "decomp":
        out = run_decomp()
    elif mode == "quality":
        out = run_quality()
    elif mode in DECODE_CONFIGS:
        out = run_decode_config(mode)
    elif mode in PREFILL_CONFIGS:
        out = run_prefill_config(mode)
    elif mode in SPEC_CONFIGS:
        out = run_spec_config(mode)
    elif mode in RAGGED_CONFIGS:
        out = run_ragged_config(mode)
    elif mode in SERVE_CONFIGS:
        out = run_serve_config(mode)
    elif mode in SERVE_MIXED_CONFIGS:
        out = run_serve_mixed_config(mode)
    elif mode in SERVE_TIER_CONFIGS:
        out = run_serve_tier_config(mode)
    elif mode in SERVE_SPEC_CONFIGS:
        out = run_serve_spec_config(mode)
    elif mode in SERVE_HTTP_CONFIGS:
        out = run_serve_http_config(mode)
    elif mode in SERVE_CHAOS_CONFIGS:
        out = run_serve_chaos_config(mode)
    elif mode in SERVE_RESTART_CONFIGS:
        out = run_serve_restart_config(mode)
    elif mode in SERVE_ROLLING_CONFIGS:
        out = run_serve_rolling_config(mode)
    elif mode in SERVE_SHARDED_CONFIGS:
        out = run_serve_sharded_config(mode)
    elif mode in SERVE_TENANT_CONFIGS:
        out = run_serve_tenant_config(mode)
    else:
        raise SystemExit(f"unknown config {mode!r}")
    print(json.dumps(out), flush=True)


# ----------------------------------------------------------------------
# Parent-process orchestration
# ----------------------------------------------------------------------

def _spawn(mode: str, timeout: float, env: dict | None = None) -> dict:
    """Run `python bench.py --run mode` with a hard timeout; parse the last
    JSON line of its stdout.  Never raises.  On timeout, the child's
    partial stderr (recovered from TimeoutExpired) yields the last
    ``bench-phase`` breadcrumbs — where the budget actually went."""
    cmd = [sys.executable, os.path.abspath(__file__), "--run", mode]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout, cwd=REPO,
            env={**os.environ, **(env or {})},
        )
    except subprocess.TimeoutExpired as e:
        err = e.stderr or b""
        if isinstance(err, bytes):
            err = err.decode(errors="replace")
        phases = [l for l in err.splitlines() if l.startswith("bench-phase")]
        return {
            "config": mode,
            "ok": False,
            "error": f"timeout after {round(timeout)}s",
            "diagnosis": _diagnose_timeout(phases, timeout),
            "last_phases": phases[-4:],
        }
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-8:]
    return {
        "config": mode,
        "ok": False,
        "error": f"rc={proc.returncode}, no JSON line",
        "tail": "\n".join(tail)[-800:],
    }


def _diagnose_timeout(phases: list[str], timeout: float) -> str:
    """One-line explanation of WHERE a timed-out child spent its budget,
    from its bench-phase breadcrumbs (VERDICT r2 weak #2: the bs=8 burn
    was undiagnosable from artifacts)."""
    if not phases:
        return (
            f"no phase reached in {round(timeout)}s — hung in backend init / "
            "params transfer (tunnel?)"
        )
    try:
        last = json.loads(phases[-1].removeprefix("bench-phase "))
    except json.JSONDecodeError:
        return "unparseable phase log"
    name, t = last.get("phase", "?"), last.get("t", "?")
    if name == "params_init_start":
        nxt = "params materialization (device init / transfer, not compile)"
    elif name == "params_built":
        nxt = "prefill compile"
    elif name.startswith("warmup:prefill"):
        nxt = "decode-loop compile"
    elif name.startswith("warmup") or name == "compiled":
        nxt = "first measured rep"
    elif name.startswith("rep"):
        nxt = "a later measured rep (execution, not compile)"
    else:
        nxt = "the next phase"
    return f"reached {name!r} at t={t}s, then burned the rest in {nxt}"


def _load_prior_capture() -> dict | None:
    """Latest in-repo live-capture artifact (a tunnel-up window earlier in
    the round, saved by the builder as BENCH_TPU_LIVE_*.json).  Surfaced
    in ``detail`` ONLY — the top-level value/vs_baseline stay 0.0 for a
    run that measured nothing; those fields are this run's measurement
    contract.  Trimmed to the headline fields (no nested detail)."""
    def _round_no(path: str) -> int:
        # numeric round suffix, not mtime (git checkouts flatten mtimes)
        # and not lexicographic (r10 would sort before r4)
        m = re.search(r"_r(\d+)\.json$", path)
        return int(m.group(1)) if m else -1

    files = sorted(
        glob.glob(os.path.join(REPO, "BENCH_TPU_LIVE_*.json")), key=_round_no
    )
    for path in reversed(files):
        try:
            with open(path) as f:
                prior = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if prior.get("value"):
            return {
                "file": os.path.basename(path),
                "value": prior["value"],
                "vs_baseline": prior.get("vs_baseline"),
                "headline_definition": prior.get("detail", {}).get(
                    "headline_definition"
                ),
            }
    return None


def _emit_summary(detail: dict, probe: dict, error: str | None) -> None:
    bs8 = detail.get("llama1b_bs8", {})
    bs1 = detail.get("llama1b_bs1", {})
    # Headline: bs=8 aggregate; fall back to whatever decode config finished.
    value = bs8.get("decode_tok_s_chip")
    headline = "llama1b_bs8_aggregate"
    if value is None:
        for name, r in detail.items():
            if r.get("ok") and "decode_tok_s_chip" in r:
                value, headline = r["decode_tok_s_chip"], f"{name}_aggregate"
                break
    prior = None
    if value is None and not probe.get("ok"):
        # this run measured nothing because the tunnel was down: value
        # stays 0.0 (the numeric fields are THIS run's measurement), but
        # the round's saved live capture rides along in detail so the
        # artifact still points at the real numbers
        prior = _load_prior_capture()
        if prior is not None:
            headline = (
                "NO MEASUREMENT THIS RUN (TPU unreachable) — see "
                f"detail.prior_capture ({prior['file']}, "
                f"{prior['value']} tok/s/chip earlier this round)"
            )
    result = {
        "metric": "decode_tokens_per_sec_per_chip",
        "value": value if value is not None else 0.0,
        "unit": "tokens/s/chip",
        "vs_baseline": round((value or 0.0) / NORTH_STAR_TOK_S, 3),
        "detail": {
            "headline_definition": (
                f"{headline}: aggregate decode tokens/s on one chip "
                f"(north star {NORTH_STAR_TOK_S:.0f} tok/s/chip; the strict "
                "bs=1 per-seq reading is vs_baseline_bs1_per_seq)"
            ),
            "vs_baseline_bs1_per_seq": round(
                bs1.get("per_seq_tok_s", 0.0) / NORTH_STAR_TOK_S, 3
            ),
            "hbm_roofline_gb_s": HBM_GB_S,
            "probe": probe,
            **({"prior_capture": prior} if prior is not None else {}),
            **detail,
        },
    }
    if error:
        result["error"] = error
    print(json.dumps(result), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--run", help="(internal) run one config in this process")
    ap.add_argument("--configs", nargs="*", help="subset of configs to run")
    args = ap.parse_args()
    if args.run:
        child_main(args.run)
        return

    t_start = time.time()
    deadline = _deadline_s()
    detail: dict[str, dict] = {}

    # Opportunistic probing (VERDICT r3 task 1): the tunnel flaps — r3
    # burned a 12 h session because the probe gave up 6 minutes into a
    # 25-minute budget.  Keep probing every ~60 s across the ENTIRE
    # budget (minus a reserve for the CPU-side quality child) until the
    # chip answers; every attempt is logged so a dead-all-session tunnel
    # still yields an artifact proving the coverage.
    probe_log: list[dict] = []
    reserve_s = 300.0  # keep room to still run the CPU quality child
    # (5 quality modes measured ~180 s on CPU; headroom for slow hosts)
    while True:
        attempt_start = time.time()
        remaining = deadline - (attempt_start - t_start)
        # always make at least one attempt, even under a tiny deadline
        budget = min(PROBE_TIMEOUT, max(remaining - reserve_s, 60.0))
        probe = _spawn("probe", budget)
        probe_log.append({
            "t": round(attempt_start - t_start, 1),
            "ok": bool(probe.get("ok")),
            **({} if probe.get("ok") else {"error": str(probe.get("error"))[:200]}),
        })
        if probe.get("ok"):
            break
        print(
            f"bench: probe failed ({probe.get('error')}) at "
            f"t={round(time.time() - t_start)}s; re-probing until "
            f"deadline {round(deadline)}s",
            file=sys.stderr, flush=True,
        )
        # keep the artifact honest mid-retry: a driver kill during the
        # sleep must still leave an error-carrying summary
        _emit_summary(
            detail, {**probe, "probe_log": probe_log},
            error=f"TPU backend unreachable so far: {probe.get('error')}",
        )
        if deadline - (time.time() - t_start) <= reserve_s + 70:
            break
        time.sleep(max(0.0, 60.0 - (time.time() - attempt_start)))
    probe["probe_log"] = probe_log

    if not probe.get("ok"):
        # TPU never answered: still produce the backend-independent
        # quality evidence on CPU, then emit the probe-coverage artifact
        # (clipped to the deadline, same as the success path).
        remaining = deadline - (time.time() - t_start)
        if remaining > 60:
            detail["quality"] = _spawn(
                "quality", min(reserve_s, remaining), env={"BENCH_PLATFORM": "cpu"}
            )
        _emit_summary(
            detail, probe,
            error=f"TPU backend unreachable: {probe.get('error')}",
        )
        return

    names = args.configs or list(PRIORITY)
    if not args.configs:
        # AOT-warm the compilation cache first (abstract shapes, no
        # execution): one pass amortizes every config's compile.  Capped
        # so a pathologically slow remote-compile service can't eat the
        # run; a timeout here is recorded but configs still proceed
        # (each re-compiles what warm didn't reach, as before).
        remaining = deadline - (time.time() - t_start)
        # cap covers ~2 programs per decode config (full + half loop);
        # under a tight deadline (e.g. the driver's 1500 s default) warm
        # only the top few priority configs so measurement starts sooner
        warm_env = {"BENCH_WARM_LIMIT": "4"} if remaining < 2400 else None
        warm = _spawn(
            "warm", min(540.0, max(remaining / 3, 60.0)), env=warm_env
        )
        detail["warm"] = warm
        print(json.dumps(warm), file=sys.stderr, flush=True)
        # Mosaic verdict per Pallas kernel — cheap (tiny shapes, warm
        # cache) and the round's key hardware evidence
        detail["kernels"] = _spawn("kernels", 300.0)  # ~45 s/cold Mosaic compile
        print(json.dumps(detail["kernels"]), file=sys.stderr, flush=True)
        _emit_summary(detail, probe, error=_failed_error(detail))
    for name in names:
        remaining = deadline - (time.time() - t_start)
        if remaining < MIN_CONFIG_BUDGET_S:
            detail[name] = {
                "config": name, "ok": False,
                "error": f"skipped: {round(remaining)}s left of "
                         f"BENCH_DEADLINE_S={round(deadline)}",
            }
            print(json.dumps(detail[name]), file=sys.stderr, flush=True)
            continue
        budget = min(TIMEOUTS.get(name, DEFAULT_TIMEOUT), remaining - 10)
        spec_env = {
            **DECODE_CONFIGS, **PREFILL_CONFIGS, **SPEC_CONFIGS,
            **RAGGED_CONFIGS, **SERVE_CONFIGS, **SERVE_MIXED_CONFIGS,
            **SERVE_HTTP_CONFIGS,
            **SERVE_CHAOS_CONFIGS, **SERVE_SHARDED_CONFIGS,
            **SERVE_RESTART_CONFIGS,
        }.get(name, {}).get("env")
        res = _spawn(name, budget, env=spec_env)
        detail[name] = res
        print(json.dumps(res), file=sys.stderr, flush=True)
        # Re-emit the FULL summary after every config (last stdout line
        # wins) so an outer kill at any moment leaves a parseable artifact.
        _emit_summary(detail, probe, error=_failed_error(detail))

    if not args.configs:
        # Quantization quality evidence — CPU child (deterministic tiny
        # fixture), so it never competes with the TPU for budget; clipped
        # to the deadline the module docstring promises to honor
        remaining = deadline - (time.time() - t_start)
        if remaining > 60:
            detail["quality"] = _spawn(
                "quality", min(360.0, remaining), env={"BENCH_PLATFORM": "cpu"}
            )
            print(json.dumps(detail["quality"]), file=sys.stderr, flush=True)

    # Final emit covers the nothing-ran / everything-skipped path too.
    _emit_summary(detail, probe, error=_failed_error(detail))


def _failed_error(detail: dict) -> str | None:
    # "warm" is advisory (cache priming): its failure alone doesn't
    # flag the run
    failed = [
        n for n, r in detail.items() if not r.get("ok") and n != "warm"
    ]
    return f"configs failed: {failed}" if failed else None


if __name__ == "__main__":
    main()
