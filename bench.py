"""Decode-throughput benchmark (BASELINE.md metric: decode tokens/sec/chip).

Runs the flagship Llama-3.2-1B architecture (random bf16 weights — no
checkpoint downloads in this environment; decode throughput is
weight-value-independent) with the fused device-side decode loop:
prefill seq=128, then one jitted lax.scan of decode steps.

Headline = aggregate decode tokens/sec/chip at batch=8 (the north-star
1,000 tok/s/chip target is unreachable at bs=1 by the HBM roofline:
1.24B bf16 params = 2.47 GB read per step ÷ ~819 GB/s ≈ 331 steps/s
ceiling; batching amortizes the weight stream — BASELINE config 3 uses
bs=8).  bs=1 and bs=32 rates plus TTFT are in "detail".

Measurement notes (tunneled TPU): the transport dedupes repeated
executions with identical live inputs and ``block_until_ready`` is not a
reliable fence, so every timed iteration feeds FRESH inputs (chained to
the previous iteration's output host-side) and forces a real D2H
materialization with ``np.asarray`` before reading the clock.

Prints ONE JSON line:
  {"metric": "decode_tokens_per_sec_per_chip", "value": N,
   "unit": "tokens/s/chip", "vs_baseline": N/1000}
vs_baseline is against the BASELINE.json north-star target of 1,000
decode tokens/sec/chip (the reference publishes no numbers of its own —
SURVEY §6).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _measure(config, params, prefill, loop, batch, prompt_len, decode_tokens, reps=3):
    """Median TTFT + aggregate decode rate over ``reps`` fresh-input runs."""
    from llm_np_cp_tpu.cache import KVCache

    key = jax.random.PRNGKey(0)
    max_seq = prompt_len + decode_tokens + 8
    rng = np.random.default_rng(batch)
    carry = rng.integers(0, config.vocab_size, (batch, prompt_len))

    def one(prompt_host):
        cache = KVCache.init(config, batch, max_seq, dtype=jnp.bfloat16)
        t0 = time.perf_counter()
        tok0, cache, _ = prefill(params, jnp.asarray(prompt_host, jnp.int32), cache, key)
        np.asarray(tok0)  # force real D2H — block_until_ready is not a fence here
        t1 = time.perf_counter()
        toks, cache = loop(params, tok0, cache, key, decode_tokens)
        toks_host = np.asarray(toks)
        t2 = time.perf_counter()
        return t1 - t0, t2 - t1, toks_host

    _, _, toks_host = one(carry)  # warmup: compile both programs
    ttfts, rates = [], []
    for i in range(reps):
        # chain inputs through the previous output so the transport cannot
        # serve a deduped result for a repeated (executable, args) pair
        carry = (carry + int(toks_host.sum()) + i + 1) % config.vocab_size
        ttft, dec, toks_host = one(carry)
        ttfts.append(ttft)
        rates.append(batch * decode_tokens / dec)
    return float(np.median(ttfts)), float(np.median(rates))


def main() -> None:
    from llm_np_cp_tpu.config import LLAMA_3_2_1B
    from llm_np_cp_tpu.generate import make_decode_loop_fn, make_prefill_fn
    from llm_np_cp_tpu.models.transformer import init_params
    from llm_np_cp_tpu.ops.sampling import Sampler

    config = LLAMA_3_2_1B
    prompt_len = 128
    decode_tokens = 256

    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.bfloat16)
    sampler = Sampler(kind="greedy")
    prefill = make_prefill_fn(config, sampler)
    loop = make_decode_loop_fn(config, sampler)

    detail = {}
    for batch in (1, 8, 32):
        ttft, rate = _measure(
            config, params, prefill, loop, batch, prompt_len, decode_tokens
        )
        detail[f"bs{batch}"] = {
            "decode_tok_s_chip": round(rate, 1),
            "per_seq_tok_s": round(rate / batch, 1),
            "ttft_s_p50": round(ttft, 4),
        }

    # int8 weight-only quantization (quant.py): halves the per-step HBM
    # weight stream — reported separately since numerics differ from bf16.
    from llm_np_cp_tpu.quant import quantize_params

    qparams = quantize_params(params)
    for batch in (1, 8):
        ttft, rate = _measure(
            config, qparams, prefill, loop, batch, prompt_len, decode_tokens
        )
        detail[f"int8_bs{batch}"] = {
            "decode_tok_s_chip": round(rate, 1),
            "per_seq_tok_s": round(rate / batch, 1),
            "ttft_s_p50": round(ttft, 4),
        }

    rate = detail["bs8"]["decode_tok_s_chip"]
    result = {
        "metric": "decode_tokens_per_sec_per_chip",
        "value": rate,
        "unit": "tokens/s/chip",
        "vs_baseline": round(rate / 1000.0, 3),
        "detail": {
            "model": "Llama-3.2-1B (random bf16 weights)",
            "prompt_len": prompt_len,
            "decode_tokens": decode_tokens,
            "headline_batch": 8,
            **detail,
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
