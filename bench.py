"""Decode-throughput benchmark (BASELINE.md metric: decode tokens/sec/chip).

Runs the flagship Llama-3.2-1B architecture (random bf16 weights — no
checkpoint downloads in this environment; decode throughput is
weight-value-independent) with the fused device-side decode loop:
prefill seq=128, then one jitted lax.scan of decode steps, bs=1
(BASELINE config 1 shape).

Prints ONE JSON line:
  {"metric": "decode_tokens_per_sec_per_chip", "value": N,
   "unit": "tokens/s/chip", "vs_baseline": N/1000}
vs_baseline is against the BASELINE.json north-star target of 1,000
decode tokens/sec/chip (the reference publishes no numbers of its own —
SURVEY §6).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    from llm_np_cp_tpu.cache import KVCache
    from llm_np_cp_tpu.config import LLAMA_3_2_1B
    from llm_np_cp_tpu.generate import make_decode_loop_fn, make_prefill_fn
    from llm_np_cp_tpu.models.transformer import init_params
    from llm_np_cp_tpu.ops.sampling import Sampler

    config = LLAMA_3_2_1B
    prompt_len = 128
    decode_tokens = 256
    max_seq = prompt_len + decode_tokens + 8

    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.bfloat16)
    sampler = Sampler(kind="greedy")
    prefill = make_prefill_fn(config, sampler)
    loop = make_decode_loop_fn(config, sampler)

    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, config.vocab_size, (1, prompt_len)),
        jnp.int32,
    )
    key = jax.random.PRNGKey(0)

    def run():
        cache = KVCache.init(config, 1, max_seq, dtype=jnp.bfloat16)
        t0 = time.perf_counter()
        tok0, cache, _ = prefill(params, prompt, cache, key)
        tok0.block_until_ready()
        t1 = time.perf_counter()
        toks, cache = loop(params, tok0, cache, key, decode_tokens)
        toks.block_until_ready()
        t2 = time.perf_counter()
        return t1 - t0, t2 - t1

    run()  # warmup: compile both programs
    ttfts, rates = [], []
    for _ in range(3):
        ttft, dec = run()
        ttfts.append(ttft)
        rates.append(decode_tokens / dec)

    rate = float(np.median(rates))
    result = {
        "metric": "decode_tokens_per_sec_per_chip",
        "value": round(rate, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(rate / 1000.0, 3),
        "detail": {
            "model": "Llama-3.2-1B (random bf16 weights)",
            "prompt_len": prompt_len,
            "decode_tokens": decode_tokens,
            "batch": 1,
            "ttft_s_p50": round(float(np.median(ttfts)), 4),
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
