"""Merge newly captured bench rows into BENCH_TPU_LIVE_r4.json.

Usage: python .merge_live.py /tmp/bench_retry_r4.out [/tmp/kernels_r4.out]
Takes the LAST parseable summary line of each input; config rows with
ok=true replace/add into the live artifact's detail; headline value is
recomputed from llama1b_bs8 if present. Scratch tool for the r4 session,
not part of the framework.
"""

import json
import sys

LIVE = "BENCH_TPU_LIVE_r4.json"


def last_json(path):
    out = None
    with open(path) as f:
        for line in f:
            try:
                out = json.loads(line)
            except json.JSONDecodeError:
                continue
    return out


def main():
    with open(LIVE) as f:
        live = json.load(f)
    merged = []
    for path in sys.argv[1:]:
        new = last_json(path)
        if new is None:
            print(f"{path}: no parseable JSON line, skipped")
            continue
        if "detail" in new:  # a summary line: merge its ok config rows
            for name, row in new["detail"].items():
                if isinstance(row, dict) and row.get("ok"):
                    live["detail"][name] = row
                    merged.append(name)
        elif new.get("config") == "kernels":  # a raw kernels child line
            live["detail"]["kernels"] = new
            merged.append("kernels")
    bs8 = live["detail"].get("llama1b_bs8", {})
    if bs8.get("decode_tok_s_chip"):
        live["value"] = bs8["decode_tok_s_chip"]
        live["vs_baseline"] = round(live["value"] / 1000.0, 3)
    with open(LIVE, "w") as f:
        json.dump(live, f)
        f.write("\n")
    print("merged:", merged)
    print("headline:", live["value"])


if __name__ == "__main__":
    main()
