"""Training step: causal-LM loss + optimizer update, mesh-sharded.

The reference is inference-only (``loss`` is always ``None``,
llama3.2_model.py:809).  The framework closes that gap with a minimal but
real training path — cross-entropy over shifted targets, ``jax.grad``
through the same ``models.transformer.forward`` used for inference, optax
updates, and the full thing jit-compiled over a device mesh (DP on batch,
TP on weights) so the multi-chip story covers training too.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax

from llm_np_cp_tpu.config import ModelConfig
from llm_np_cp_tpu.models.transformer import forward

Params = dict[str, Any]


def causal_lm_loss(
    params: Params,
    batch: jnp.ndarray,
    config: ModelConfig,
    *,
    loss_mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Mean next-token cross-entropy.  batch: [B, S] int32; positions
    t < S-1 predict t+1.  loss_mask: optional [B, S-1] weighting.
    MoE configs add ``router_aux_loss_coef ×`` the load-balancing loss."""
    inputs = batch[:, :-1]
    targets = batch[:, 1:]
    if config.is_moe:
        logits, _, aux = forward(params, inputs, config, output_router_losses=True)
    else:
        logits, _ = forward(params, inputs, config)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if loss_mask is not None:
        loss = jnp.sum(nll * loss_mask) / jnp.maximum(jnp.sum(loss_mask), 1.0)
    else:
        loss = jnp.mean(nll)
    if config.is_moe:
        loss = loss + config.router_aux_loss_coef * aux["moe_aux_loss"]
    return loss


def make_train_step(config: ModelConfig, optimizer: optax.GradientTransformation):
    """Returns jitted ``step(params, opt_state, batch) → (params, opt_state,
    loss)``.  Shard params/batch before calling; GSPMD partitions the
    backward pass and gradient psums over the mesh automatically."""

    @jax.jit
    def step(params: Params, opt_state, batch: jnp.ndarray):
        loss, grads = jax.value_and_grad(causal_lm_loss)(params, batch, config)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def default_optimizer(lr: float = 1e-4) -> optax.GradientTransformation:
    return optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(lr))
