"""Training step: causal-LM loss + optimizer update, mesh-sharded.

The reference is inference-only (``loss`` is always ``None``,
llama3.2_model.py:809).  The framework closes that gap with a minimal but
real training path — cross-entropy over shifted targets, ``jax.grad``
through the same ``models.transformer.forward`` used for inference, optax
updates, and the full thing jit-compiled over a device mesh (DP on batch,
TP on weights) so the multi-chip story covers training too.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax

from llm_np_cp_tpu.config import ModelConfig
from llm_np_cp_tpu.models.transformer import forward

Params = dict[str, Any]


def causal_lm_loss(
    params: Params,
    batch: jnp.ndarray,
    config: ModelConfig,
    *,
    loss_mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Mean next-token cross-entropy.  batch: [B, S] int32; positions
    t < S-1 predict t+1.  loss_mask: optional [B, S-1] weighting.
    MoE configs add ``router_aux_loss_coef ×`` the load-balancing loss."""
    inputs = batch[:, :-1]
    targets = batch[:, 1:]
    if config.is_moe:
        logits, _, aux = forward(params, inputs, config, output_router_losses=True)
    else:
        logits, _ = forward(params, inputs, config)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if loss_mask is not None:
        loss = jnp.sum(nll * loss_mask) / jnp.maximum(jnp.sum(loss_mask), 1.0)
    else:
        loss = jnp.mean(nll)
    if config.is_moe:
        loss = loss + config.router_aux_loss_coef * aux["moe_aux_loss"]
    return loss


def make_train_step(config: ModelConfig, optimizer: optax.GradientTransformation):
    """Returns jitted ``step(params, opt_state, batch) → (params, opt_state,
    loss)``.  Shard params/batch before calling; GSPMD partitions the
    backward pass and gradient psums over the mesh automatically."""

    @jax.jit
    def step(params: Params, opt_state, batch: jnp.ndarray):
        loss, grads = jax.value_and_grad(causal_lm_loss)(params, batch, config)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def default_optimizer(lr: float = 1e-4) -> optax.GradientTransformation:
    return optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(lr))


# ----------------------------------------------------------------------
# CLI: the user entrypoint for every training-side mesh axis
# ----------------------------------------------------------------------

def build_parser():
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m llm_np_cp_tpu.train",
        description="Mesh-sharded causal-LM training (DP/TP/PP/EP). The "
        "reference is inference-only; this is the training entrypoint the "
        "dryrun exercises, exposed (SURVEY §5 checkpoint/resume row).",
    )
    p.add_argument("--model", default="tiny",
                   help="preset (tiny, tiny_moe, llama1b, llama3b, gemma2_2b "
                        "— random init) or an HF checkpoint dir/repo id")
    p.add_argument("--mesh", default="1,1,1",
                   help="named axes data=2,pipe=2,model=2 (any of data/seq/"
                        "model/pipe/expert) or positional data,seq,model")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--microbatches", type=int, default=2,
                   help="GPipe microbatches per step (pipe>1 only)")
    p.add_argument("--dtype", choices=["bf16", "f32"], default="f32",
                   help="parameter dtype (f32 default: optimizer math)")
    p.add_argument("--data", default=None,
                   help="UTF-8 text file tokenized with the model tokenizer "
                        "(checkpoint models only); default: synthetic tokens")
    p.add_argument("--layers", type=int, default=None,
                   help="override the preset's layer count (e.g. to make it "
                        "divisible by pipe)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--checkpoint-dir", default=None,
                   help="save an orbax checkpoint here after training")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                   help="force a jax platform via jax.config (env vars are "
                        "too late where the site pre-imports jax)")
    p.add_argument("--virtual-devices", type=int, default=None, metavar="N",
                   help="with --platform cpu: N virtual devices to test "
                        "multi-chip meshes on one host")
    return p


def _resolve_model(args):
    from llm_np_cp_tpu.config import (
        GEMMA_2_2B, LLAMA_3_2_1B, LLAMA_3_2_3B, tiny_config,
    )
    from llm_np_cp_tpu.models.transformer import init_params

    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    tiny_kw = dict(num_hidden_layers=args.layers) if args.layers else {}
    presets = {
        "tiny": lambda: tiny_config("llama", **tiny_kw),
        "tiny_moe": lambda: tiny_config(
            "llama", num_local_experts=4, num_experts_per_tok=2, **tiny_kw
        ),
        "llama1b": lambda: LLAMA_3_2_1B,
        "llama3b": lambda: LLAMA_3_2_3B,
        "gemma2_2b": lambda: GEMMA_2_2B,
    }
    if args.model in presets:
        if args.layers and args.model not in ("tiny", "tiny_moe"):
            raise SystemExit("--layers applies to the tiny presets only")
        config = presets[args.model]()
        params = init_params(jax.random.PRNGKey(args.seed), config, dtype=dtype)
        return None, params, config
    if args.layers:
        raise SystemExit("--layers applies to the tiny presets only")
    from llm_np_cp_tpu.utils.loading import load_model

    return load_model(args.model, dtype=dtype)


def _batches(args, tokenizer, vocab_size):
    """Yield [batch, seq_len] int32 arrays forever."""
    import numpy as np

    if args.data:
        if tokenizer is None:
            raise SystemExit("--data needs a checkpoint model (tokenizer)")
        text = open(args.data, encoding="utf-8").read()
        ids = np.asarray(tokenizer(text)["input_ids"], dtype=np.int32)
        need = args.batch * args.seq_len
        if ids.size < need:
            ids = np.tile(ids, need // ids.size + 1)
        off = 0
        while True:
            if off + need > ids.size:
                off = 0
            yield ids[off:off + need].reshape(args.batch, args.seq_len)
            off += need
    else:
        # synthetic mode: a small FIXED corpus cycled forever (not fresh
        # noise per step), so a smoke run shows the loss actually falling
        # as the model memorizes it
        rng = np.random.default_rng(args.seed)
        corpus = [
            rng.integers(0, vocab_size, (args.batch, args.seq_len), dtype=np.int32)
            for _ in range(2)
        ]
        i = 0
        while True:
            yield corpus[i % len(corpus)]
            i += 1


def run(argv: list[str] | None = None) -> list[float]:
    """Train for --steps steps; returns the per-step losses (also printed)."""
    import contextlib
    import sys
    import time

    from llm_np_cp_tpu.parallel.sharding import (
        make_mesh, parse_mesh_spec, shard_params,
    )

    args = build_parser().parse_args(argv)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    if args.virtual_devices:
        jax.config.update("jax_num_cpu_devices", args.virtual_devices)
    plan = parse_mesh_spec(args.mesh)
    tokenizer, params, config = _resolve_model(args)

    mesh = None
    if plan.num_devices > 1:
        plan.validate(config)
        if args.batch % max(plan.data, 1):
            raise SystemExit(
                f"--batch {args.batch} not divisible by data={plan.data}"
            )
        mesh = make_mesh(plan)
        params = shard_params(params, config, plan, mesh)
    if plan.pipe > 1 and args.batch % args.microbatches:
        raise SystemExit(
            f"--batch {args.batch} not divisible by "
            f"--microbatches {args.microbatches}"
        )

    opt = default_optimizer(args.lr)
    opt_state = opt.init(params)
    if plan.pipe > 1:
        from llm_np_cp_tpu.parallel.pipeline import make_pp_train_step

        step = make_pp_train_step(
            config, opt, plan, mesh, num_microbatches=args.microbatches
        )
    else:
        step = make_train_step(config, opt)

    ctx = jax.set_mesh(mesh) if mesh is not None else contextlib.nullcontext()
    losses: list[float] = []
    toks = args.batch * (args.seq_len - 1)
    with ctx:
        gen = _batches(args, tokenizer, config.vocab_size)
        for i in range(args.steps):
            t0 = time.perf_counter()
            params, opt_state, loss = step(
                params, opt_state, jnp.asarray(next(gen))
            )
            loss = float(loss)  # blocks: step wall-clock is real
            dt = time.perf_counter() - t0
            losses.append(loss)
            print(
                f"step {i:4d}  loss {loss:.4f}  {toks / dt:,.0f} tok/s"
                + ("  (compile)" if i == 0 else ""),
                file=sys.stderr,
            )
    if args.checkpoint_dir:
        from llm_np_cp_tpu.utils.checkpoint import save_checkpoint

        save_checkpoint(
            args.checkpoint_dir,
            {"params": params, "opt_state": opt_state, "step": args.steps},
        )
        print(f"saved checkpoint to {args.checkpoint_dir}", file=sys.stderr)
    return losses


if __name__ == "__main__":
    run()
