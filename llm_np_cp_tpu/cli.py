"""Command-line entry: the reference's ``__main__`` surface, grown up.

The reference hard-codes everything (model name in ``__main__``,
llama3.2_model.py:1101-1109; ``config.use_cache = True`` by mutation;
no argparse anywhere — SURVEY §5 config row).  Per the BASELINE north star,
the entrypoint scripts keep the reference's names (``llama3.2_model.py``,
``gemma2_model.py``, ``llama3.2_model_numpy.py`` at the repo root are thin
shims over this module) and accept ``--backend={tpu,numpy}``:

- ``tpu``: the JAX path — jitted prefill + fused/streamed decode, optional
  mesh sharding (``--mesh data,seq,model``), bf16 default.
- ``numpy``: the fp32 NumPy oracle backend (the reference's
  llama3.2_model_numpy.py role).
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Any

import numpy as np


def build_parser(default_model: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="TPU-native LLM inference (llm_np_cp capability surface)",
        epilog="subcommands (dispatched before this parser, each with its "
        "own flags): serve-bench — replay a Poisson trace through the "
        "continuous-batching ServeEngine (serve-bench --help); serve — "
        "the OpenAI-compatible streaming HTTP front-end over the same "
        "engine (serve --help)",
    )
    p.add_argument("--model", default=default_model,
                   help="HF repo id or local checkpoint dir")
    p.add_argument("--backend", choices=["tpu", "numpy"], default="tpu")
    p.add_argument("--prompt", default="Once upon a time")
    p.add_argument("--batch-size", type=int, default=0, metavar="N",
                   help="with --prompts-file: run the workload in ragged "
                        "batches of N (longest-first grouping; 0 = one "
                        "batch of everything)")
    p.add_argument("--prompts-file", default=None, metavar="PATH",
                   help="batch mode: one prompt per line, generated together "
                        "as a ragged batch (left-padded, per-row positions "
                        "exact); prints one completion per line. The "
                        "reference's generate is strictly bs=1 "
                        "(llama3.2_model.py:865-902)")
    p.add_argument("--max-tokens", type=int, default=200)
    p.add_argument("--sampler", choices=["min_p", "greedy", "cdf", "top_k", "top_p"],
                   default="min_p")
    p.add_argument("--p-base", type=float, default=0.1, help="min-p threshold")
    p.add_argument("--top-k", type=int, default=50,
                   help="k for --sampler top_k")
    p.add_argument("--top-p", type=float, default=0.9,
                   help="nucleus mass for --sampler top_p")
    p.add_argument("--temperature", type=float, default=1.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--dtype", choices=["bf16", "f32"], default="bf16")
    p.add_argument("--cache-dtype", choices=["auto", "bf16", "f32", "int8"],
                   default="auto",
                   help="KV-cache storage dtype (auto = follow --dtype); "
                        "int8 stores per-token-per-head absmax-quantized "
                        "K/V, halving cache HBM traffic for long contexts")
    p.add_argument("--quantize",
                   choices=["none", "int8", "int8_a8", "int4", "int4_a8"],
                   default="none",
                   help="quantization: int8 (weight-only) halves decode HBM "
                        "traffic, int4 packs projections two-per-byte "
                        "(embed stays int8); the _a8 variants add dynamic "
                        "activation quant (all-integer MXU einsums; "
                        "lossier, opt-in); composes with --mesh sharding")
    p.add_argument("--mesh", default="1,1,1",
                   help="data,seq,model parallel degrees (e.g. 1,1,8 for TP=8)")
    p.add_argument("--max-seq-len", type=int, default=None,
                   help="KV cache capacity (default: prompt + max tokens)")
    p.add_argument("--no-cache", action="store_true",
                   help="cache-less full-recompute mode (reference parity)")
    p.add_argument("--no-stream", action="store_true",
                   help="fused decode (fastest) instead of token streaming")
    p.add_argument("--attn-impl", choices=["xla", "flash", "ring"], default=None,
                   help="prefill attention: xla (default), flash (Pallas "
                        "blockwise kernel), ring (sequence-parallel ring "
                        "attention; needs --mesh with seq>1)")
    p.add_argument("--flash-prefill", action="store_true",
                   help=argparse.SUPPRESS)  # deprecated alias: --attn-impl flash
    p.add_argument("--prefill-chunk", type=int, default=None, metavar="N",
                   help="prefill the prompt in N-token chunks (bounds compile "
                        "cost for long prompts; one compiled program reused "
                        "per chunk)")
    p.add_argument("--decode-attn", choices=["xla", "pallas"], default="xla",
                   help="decode-step attention: xla (default) or the fused "
                        "Pallas kernel over the cache slab")
    p.add_argument("--speculative", type=int, default=0, metavar="GAMMA",
                   help="speculative decoding: GAMMA draft proposals per "
                        "round (exact target distribution regardless of "
                        "draft; tpu backend, implies --no-stream)")
    p.add_argument("--draft", default="int8", metavar="KIND",
                   help="draft model for --speculative: int8 (default) or "
                        "int4 self-quantization, or truncN / truncN_int4 — "
                        "a layer-skip draft from the target's first N "
                        "layers (e.g. trunc8_int4)")
    p.add_argument("--early-stop", action="store_true",
                   help="fused decode exits once every row has hit EOS "
                        "(lax.while_loop) instead of running the full "
                        "token budget; needs a tokenizer EOS")
    p.add_argument("--metrics", action="store_true",
                   help="print tokens/sec and TTFT after generation")
    return p


def _add_serve_engine_flags(p: argparse.ArgumentParser,
                            default_model: str) -> None:
    """Engine flags shared by the ``serve-bench`` (trace replay) and
    ``serve`` (HTTP front-end) subcommands — ONE definition so the HTTP
    server can always be pointed at exactly the configuration a bench
    measured."""
    p.add_argument("--model", default=default_model)
    p.add_argument("--prompt-len", type=int, default=64, metavar="MAX",
                   help="serve-bench: prompt lengths are uniform in "
                   "[MAX//4, MAX]; serve: the longest prompt the pool is "
                   "sized to admit")
    p.add_argument("--max-tokens", type=int, default=32,
                   help="decode budget per request (serve: the cap and "
                   "default for the request's max_tokens field)")
    p.add_argument("--slots", type=int, default=4,
                   help="decode slots (packed batch width)")
    p.add_argument("--block-size", type=int, default=64,
                   help="KV pool block size in cache slots (multiple of 8)")
    p.add_argument("--num-blocks", type=int, default=0,
                   help="KV pool blocks; 0 sizes the pool so every slot "
                   "can hold a worst-case request plus one spare block")
    p.add_argument("--cache-dtype", choices=["bf16", "f32", "int8"],
                   default="bf16")
    p.add_argument("--attn-impl", choices=["gather", "paged", "auto"],
                   default="gather",
                   help="decode K/V access: 'gather' materializes the "
                   "active batch's cache view through the block tables "
                   "(the XLA path), 'paged' runs the block-table-native "
                   "Pallas kernel with ZERO gather (requires the Mosaic "
                   "compile probe to pass), 'auto' picks paged when the "
                   "probe passes and falls back to gather")
    p.add_argument("--prefix-cache", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="share fully-filled prompt-prefix blocks across "
                   "requests (refcounted; hits skip those prefill chunks). "
                   "Cache entries are reclaimed LRU under pool pressure, "
                   "so give --num-blocks headroom beyond the worst-case "
                   "default for entries to survive between twin prompts")
    p.add_argument("--kv-tier", choices=["off", "host"], default="off",
                   help="tiered KV prefix cache (serve/host_tier.py): "
                   "'host' spills LRU-reclaimed prefix blocks to a "
                   "pinned host-RAM pool (keyed by the same chained "
                   "content hash the prefix cache uses) and restores "
                   "them at admission via async device_put staged off "
                   "the tick thread — a capacity miss costs one "
                   "host→device copy instead of a full re-prefill.  "
                   "Restore-vs-recompute is a MEASURED breakeven "
                   "(startup device_put probe + live prefill rates); "
                   "below it the span re-prefills.  One tier is shared "
                   "across all replicas, so drains/re-homes ship blocks "
                   "replica-to-replica through it.  Requires "
                   "--prefix-cache")
    p.add_argument("--kv-host-tier-gb", type=float, default=4.0,
                   metavar="G",
                   help="host-RAM budget for --kv-tier host, GiB "
                   "(LRU eviction past it; the tier is a cache, so "
                   "dropping is always safe)")
    p.add_argument("--decode-attn", choices=["xla", "pallas"], default="xla",
                   help="attention kernel for the GATHERED decode step "
                   "(pallas is gated: it silently downgrades off-TPU); "
                   "ignored under --attn-impl paged")
    p.add_argument("--mixed-step", choices=["auto", "on", "off"],
                   default="auto",
                   help="unified ragged prefill+decode tick: ONE device "
                   "dispatch per tick runs a mixed batch of prefill "
                   "chunk slices and decode rows against the paged pool "
                   "(ragged_paged_attention), with prefill K/V written "
                   "straight into pool blocks and decode co-scheduled "
                   "under --tick-token-budget.  'auto' (default) takes "
                   "the unified tick when the ragged kernel's Mosaic "
                   "probe passes and falls back to the phase-split tick "
                   "otherwise; 'on' forces it (XLA ragged fallback if "
                   "the kernel is rejected); 'off' is the phase-split "
                   "engine (--attn-impl/--decode-attn then select its "
                   "decode path)")
    p.add_argument("--sample-epilogue", choices=["auto", "on", "off"],
                   default="auto",
                   help="fused sampling epilogue (tick-tail fusion): "
                   "the step's final-norm → lm_head → sample chain runs "
                   "as ONE Pallas kernel over vocab tiles, so the "
                   "[rows, V] logits never materialize in HBM.  'auto' "
                   "(default) fuses when the sample_epilogue probe "
                   "passes AND the draw is bit-identical to the XLA "
                   "tail (greedy sampler, float/int8 head); 'on' warns "
                   "when it cannot fuse; 'off' forces the XLA "
                   "final_logits+sampler tail (the parity oracle).  The "
                   "banner reports the resolution as epilogue=fused|xla")
    p.add_argument("--tick-token-budget", type=int, default=0, metavar="N",
                   help="unified tick only: token budget per tick — "
                   "decode rows are budgeted first (never starved), "
                   "remaining tokens go to prefill chunk slices, so a "
                   "long prefill spreads over ticks instead of stalling "
                   "the decode batch.  Must be >= --slots; larger = "
                   "faster TTFT, smaller = steadier decode cadence.  "
                   "0 = slots + 2*prefill_chunk")
    p.add_argument("--speculative-serve", action="store_true",
                   help="speculative decoding inside the unified tick: "
                   "per-request host-side prompt-lookup drafts verified "
                   "as ragged q-slices in the SAME one dispatch per "
                   "tick, accepted with the deterministic (seed, "
                   "content-pos) sampling keys — streams stay "
                   "token-identical to plain decode, each accepted "
                   "draft is a free token per HBM sweep.  Requests opt "
                   "in per-submit ('\"speculative\": true' on "
                   "/v1/completions; serve-bench marks its whole "
                   "trace).  Requires the unified tick (--mixed-step "
                   "auto/on); per-request fallback to plain decode "
                   "when rolling acceptance collapses")
    p.add_argument("--spec-k", type=int, default=4, metavar="N",
                   help="max draft tokens proposed per speculating "
                   "request per tick (the verify slice is <= N+1 wide); "
                   "only read under --speculative-serve")
    p.add_argument("--mesh", default="", metavar="SPEC",
                   help="shard EACH engine over a tensor-parallel mesh "
                   "slice: model=N (parallel/sharding.py syntax; serve "
                   "meshes are TP-only — params column/row-sharded, pool "
                   "KV slabs kv-head-partitioned, block tables "
                   "replicated).  Default: single chip")
    p.add_argument("--replicas", type=int, default=1, metavar="N",
                   help="data-parallel engine replicas behind one "
                   "front-end with prefix-affinity routing "
                   "(serve/replica.py); composes with --mesh — each "
                   "replica gets its own mesh slice, so N replicas x "
                   "TP degree devices are required")
    p.add_argument("--spill-queue-depth", type=int, default=4, metavar="D",
                   help="router spill threshold: a request leaves its "
                   "prefix-affine replica when that replica's queue is "
                   ">= D deep and a less-loaded replica exists "
                   "(0 = never spill)")
    p.add_argument("--sampler", choices=["greedy", "min_p", "top_k", "top_p",
                                         "cdf"], default="greedy")
    p.add_argument("--dtype", choices=["bf16", "f32"], default="bf16")
    p.add_argument("--chaos-spec", default=None, metavar="SPEC",
                   help="fault-injection schedule (serve/faults.py): "
                   "events 'site@N[:COUNT][=ARG]' (deterministic) or "
                   "'site%%P[=ARG]' (seeded probability) joined by ';' — "
                   "sites: decode, prefill, tick_crash, tick_hang, "
                   "ckpt_read, http_429, http_reset, proc_kill, "
                   "journal_write, journal_fsync, host_sync, "
                   "upgrade_ckpt.  Default: the "
                   "LLMTPU_CHAOS_SPEC env var, else chaos off (injection "
                   "points are zero-overhead no-ops)")
    p.add_argument("--chaos-seed", type=int, default=0,
                   help="seed for probabilistic chaos events (a fixed "
                   "seed replays the identical fault schedule)")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write the request-lifecycle + tick-phase "
                   "timeline as Chrome/Perfetto trace-event JSON to "
                   "PATH on exit (open at ui.perfetto.dev; summarize "
                   "with tools/summarize_trace.py).  Default: tracing "
                   "off — every hook is a zero-overhead no-op")
    p.add_argument("--trace-ring", type=int, default=0, metavar="N",
                   help="keep only the newest N trace events in memory "
                   "(bounded for long-running servers; served live at "
                   "GET /debug/trace).  0 = unbounded when --trace-out "
                   "is set, else tracing off")
    p.add_argument("--slo-ttft", type=float, default=0.0, metavar="S",
                   help="SLO target: time to first token, seconds.  With"
                   " --slo-tpot this turns on goodput accounting — "
                   "slo_attainment, goodput_tok_s and 5m/1h error-budget"
                   " burn rates on /metrics plus GET /debug/slo.  "
                   "0 = no TTFT target")
    p.add_argument("--slo-tpot", type=float, default=0.0, metavar="S",
                   help="SLO target: time per output token (steady "
                   "decode cadence), seconds.  0 = no TPOT target")
    p.add_argument("--slo-target", type=float, default=0.99, metavar="F",
                   help="attainment objective the burn rate reads its "
                   "error budget from (0.99 = 1%% of requests may miss)")
    p.add_argument("--request-log", default=None, metavar="PATH",
                   help="canonical request log: ONE structured JSON "
                   "line per terminal request (trace id, route+spills, "
                   "prefix blocks hit, restarts/replays/drains "
                   "survived, per-phase latency breakdown, finish "
                   "reason, SLO verdict), written off the tick thread. "
                   "Default: off (hooks are zero-overhead no-ops)")
    p.add_argument("--tick-sentinel", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="tick anomaly sentinel: rolling per-phase EWMA "
                   "baselines over the tick-phase slices; an outlier "
                   "tick emits a trace instant naming the guilty phase "
                   "and bumps llm_serve_anomaly_ticks_total{phase=}.  "
                   "Implies host tracing (the sentinel rides the "
                   "tracer's phase timestamps)")
    p.add_argument("--sentinel-threshold", type=float, default=8.0,
                   metavar="K",
                   help="sentinel sensitivity: a phase is an outlier "
                   "past baseline + K deviations")
    p.add_argument("--auto-actions", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="closed-loop sentinel/SLO auto-actions "
                   "(serve/lifecycle.ActionPolicy): a persistent "
                   "host_sync regression (named by --tick-sentinel) "
                   "sheds prefill budget in the unified tick's planner; "
                   "an SLO error-budget burn rate past "
                   "--shed-burn-threshold flips admission to 503-first "
                   "load shedding with a burn-scaled Retry-After.  Both "
                   "actions are reversible (they release when the "
                   "signal clears), rate-limited, and counted as "
                   "llm_serve_lifecycle_actions_total{action=}.  "
                   "Default: off (no policy is constructed)")
    p.add_argument("--shed-burn-threshold", type=float, default=2.0,
                   metavar="B",
                   help="auto-actions: start 503-first load shedding "
                   "when the 5m SLO burn rate exceeds B (release at "
                   "B/2; needs --slo-ttft/--slo-tpot for burn to be "
                   "measured)")
    p.add_argument("--tenants", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="multi-tenant accounting (serve/tenants.py): "
                   "requests carry an X-Tenant-Id header (or a "
                   "\"tenant\" body field; absent = \"default\"), and "
                   "every observability surface becomes tenant-scoped — "
                   "per-tenant request/token/device-cost totals and SLO "
                   "burn as tenant-labeled series on /metrics, "
                   "GET /debug/tenants JSON, the tenant on journal "
                   "records, request-log lines and trace spans.  "
                   "Default: off (hooks are zero-overhead no-ops)")
    p.add_argument("--tenant-fairness",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="fair-share admission (implies --tenants): each "
                   "tick's prefill budget fills "
                   "smallest-running-cost-share-first across tenants "
                   "(within a tenant, oldest-first; running decodes are "
                   "never starved).  Single-tenant traffic is "
                   "byte-identical to fairness off")
    p.add_argument("--tenant-max-inflight", type=int, default=0,
                   metavar="N",
                   help="per-tenant in-flight cap (implies --tenants): "
                   "a tenant with N live requests gets 429 + "
                   "Retry-After on the next, counted as "
                   "llm_serve_tenant_throttled_total{tenant=}.  "
                   "0 = uncapped")
    p.add_argument("--max-tenant-series", type=int, default=20,
                   metavar="K",
                   help="Prometheus cardinality bound for tenant-"
                   "labeled series: the top K tenants by attributed "
                   "cost keep their own label, the rest roll up into "
                   "tenant=\"other\" (/debug/tenants always shows all)")
    p.add_argument("--roofline", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="device roofline telemetry "
                   "(serve/telemetry.py): an analytic per-tick "
                   "byte/FLOP model combined with the measured "
                   "dispatch wall yields achieved GB/s, utilization "
                   "vs --hbm-gbps and an MFU estimate — per-tick "
                   "gauges/histograms on /metrics, tick args in the "
                   "trace plane, a roofline_deficit sentinel signal, "
                   "and per-request cost attribution in the request "
                   "log.  Default: off (hooks are zero-overhead "
                   "no-ops)")
    p.add_argument("--hbm-gbps", type=float, default=819.0, metavar="G",
                   help="the HBM roofline --roofline grades "
                   "utilization against, GB/s (819 = the ROADMAP's "
                   "reference chip)")
    p.add_argument("--otlp-endpoint", default=None, metavar="URL",
                   help="ship the trace plane's spans to an "
                   "OTLP/HTTP JSON collector (e.g. "
                   "http://collector:4318/v1/traces), batched off the "
                   "serving threads, drop-and-count on collector "
                   "failure (serve/otel.py).  Implies host tracing.  "
                   "Default: no export")
    p.add_argument("--jax-profile", default=None, metavar="DIR",
                   help="capture a jax.profiler device trace into DIR "
                   "for the run; the serve dispatch phases are wrapped "
                   "in TraceAnnotation scopes, so the device profile "
                   "lines up against the host timeline from --trace-out. "
                   "Implies host tracing (the annotation scopes only "
                   "exist while a recorder is attached); give "
                   "--trace-ring/--trace-out to control the recorder, "
                   "else a bounded default ring is used")


def build_serve_parser(default_model: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="serve-bench",
        description="Replay a synthetic Poisson arrival trace through the "
        "continuous-batching ServeEngine and report TTFT/throughput "
        "percentiles (llm_np_cp_tpu/serve/)",
    )
    _add_serve_engine_flags(p, default_model)
    p.add_argument("--requests", type=int, default=16,
                   help="number of synthetic requests in the trace")
    p.add_argument("--rate", type=float, default=8.0, metavar="RPS",
                   help="mean Poisson arrival rate, requests/second")
    p.add_argument("--distinct-prompts", type=int, default=0, metavar="N",
                   help="draw only N distinct prompts and cycle requests "
                   "through them (0 = every prompt distinct) — the "
                   "shared-prefix workload shape --prefix-cache hits on")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--realtime", action="store_true",
                   help="sleep until each arrival instead of the virtual "
                   "clock (live serving simulation)")
    p.add_argument("--json", action="store_true",
                   help="also print the full metrics snapshot as one JSON "
                   "line")
    return p


def build_http_serve_parser(default_model: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="serve",
        description="Serve the model over HTTP: OpenAI-compatible "
        "POST /v1/completions (SSE streaming), GET /healthz, and a "
        "Prometheus GET /metrics (llm_np_cp_tpu/serve/http/).  Aborts "
        "requests on client disconnect or deadline, returns 429 when the "
        "queue cap is hit, and drains gracefully on SIGTERM",
    )
    _add_serve_engine_flags(p, default_model)
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (0.0.0.0 to accept remote clients)")
    p.add_argument("--port", type=int, default=8000,
                   help="TCP port; 0 picks an ephemeral port")
    p.add_argument("--max-queue", type=int, default=64,
                   help="queue-depth cap: submits past it get HTTP 429 "
                   "with Retry-After (0 = unbounded)")
    p.add_argument("--request-timeout", type=float, default=0.0,
                   metavar="S",
                   help="per-request deadline in seconds; past it the "
                   "request is aborted with finish_reason='aborted' "
                   "(0 = none; a request's own timeout_s can only lower "
                   "it)")
    p.add_argument("--drain-timeout", type=float, default=30.0, metavar="S",
                   help="SIGTERM drain: wait this long for in-flight "
                   "requests before aborting stragglers")
    p.add_argument("--tick-deadline", type=float, default=0.0, metavar="S",
                   help="watchdog: declare the engine HUNG when no tick "
                   "heartbeat lands within S seconds and hand it to the "
                   "supervisor (0 = no watchdog; crashes are still "
                   "supervised)")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="supervised restart INTENSITY budget: engine "
                   "deaths within a --restart-window span (bounded "
                   "exponential backoff; in-flight requests are replayed "
                   "token-identically) before the server goes terminally "
                   "503.  Isolated, fully-recovered blips outside the "
                   "window do not consume the budget.  0 restores "
                   "crash-equals-outage behavior")
    p.add_argument("--restart-window", type=float, default=300.0,
                   metavar="S",
                   help="the sliding window (seconds) --max-restarts "
                   "counts engine deaths in; a crash LOOP exhausts the "
                   "budget, a blip a day does not")
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="durable request journal (serve/journal.py): "
                   "admissions, per-tick delivery watermarks, and "
                   "terminals are CRC-framed and fsync'd to PATH off "
                   "the tick thread; on start, unterminated requests "
                   "found in PATH are replayed token-identically "
                   "(teacher-forced) and clients resume dropped SSE "
                   "streams via Last-Event-ID — so a kill -9 or rolling "
                   "restart loses no stream.  With --replicas N each "
                   "replica journals to PATH.<i>.  Default: no journal "
                   "(hooks are zero-overhead no-ops)")
    p.add_argument("--journal-compact-bytes", type=int,
                   default=4 << 20, metavar="N",
                   help="rewrite the journal as a live-set snapshot "
                   "whenever N appended bytes accumulate (bounds file "
                   "growth; replay-equivalent by construction)")
    p.add_argument("--journal-sync", choices=["async", "admission"],
                   default="async",
                   help="journal durability mode: 'async' (default) "
                   "fsyncs off the tick thread — an admission accepted "
                   "in the sub-tick window before a kill -9 can be "
                   "lost (clients retry, so this is usually fine); "
                   "'admission' fsyncs each admission record "
                   "SYNCHRONOUSLY before the stream starts, closing "
                   "that window at the cost of one fsync of admission "
                   "latency (measured in serve_restart_poisson)")
    p.add_argument("--port-file", default=None, metavar="PATH",
                   help="write 'host port' to PATH once listening "
                   "(readiness for scripts and tests)")
    p.add_argument("--exit-after-s", type=float, default=None,
                   help=argparse.SUPPRESS)  # test hook: timed drain
    return p


def _validate_pool_flags(args) -> None:
    """Cheap argument checks that must fire BEFORE the (potentially
    multi-minute) model load."""
    if args.block_size < 8 or args.block_size % 8:
        raise SystemExit(
            f"--block-size must be a multiple of 8, got {args.block_size}"
        )
    if getattr(args, "trace_ring", 0) < 0:
        raise SystemExit(
            f"--trace-ring must be >= 0, got {args.trace_ring}"
        )
    budget = getattr(args, "tick_token_budget", 0)
    if budget < 0 or (budget and budget < args.slots):
        raise SystemExit(
            f"--tick-token-budget must be 0 (auto) or >= --slots "
            f"({args.slots}) so decode rows are never starved, got "
            f"{budget}"
        )
    if getattr(args, "speculative_serve", False):
        if getattr(args, "mixed_step", "off") == "off":
            raise SystemExit(
                "--speculative-serve rides the unified tick's batched "
                "verifier; it cannot run with --mixed-step off"
            )
        if getattr(args, "spec_k", 4) < 1:
            raise SystemExit(
                f"--spec-k must be >= 1, got {args.spec_k}"
            )
    for flag in ("slo_ttft", "slo_tpot"):
        if getattr(args, flag, 0.0) < 0:
            raise SystemExit(
                f"--{flag.replace('_', '-')} must be >= 0 "
                f"(0 = no target), got {getattr(args, flag)}"
            )
    target = getattr(args, "slo_target", 0.99)
    if not (0.0 < target < 1.0):
        raise SystemExit(
            f"--slo-target must be in (0, 1), got {target}"
        )
    if getattr(args, "shed_burn_threshold", 2.0) <= 0:
        raise SystemExit(
            f"--shed-burn-threshold must be > 0, got "
            f"{args.shed_burn_threshold}"
        )
    if getattr(args, "hbm_gbps", 819.0) <= 0:
        raise SystemExit(
            f"--hbm-gbps must be > 0, got {args.hbm_gbps}"
        )


def _resolve_serve_mesh(args, prog: str):
    """--mesh/--replicas → (MeshPlan | None, replica device slices).

    Validates BEFORE the model load: serve meshes are TP-only, and
    ``replicas × tp`` devices must exist.  Returns one device slice per
    replica (None entries = default placement on a single chip)."""
    import jax

    from llm_np_cp_tpu.parallel.sharding import parse_mesh_spec

    replicas = args.replicas
    if replicas < 1:
        raise SystemExit(f"--replicas must be >= 1, got {replicas}")
    if args.spill_queue_depth < 0:
        raise SystemExit(
            f"--spill-queue-depth must be >= 0, got {args.spill_queue_depth}"
        )
    plan = None
    if args.mesh:
        plan = parse_mesh_spec(args.mesh)
        for axis in ("data", "seq", "pipe", "expert"):
            if getattr(plan, axis) != 1:
                raise SystemExit(
                    f"--mesh {args.mesh!r}: serve meshes are "
                    f"tensor-parallel only (model=N); {axis}="
                    f"{getattr(plan, axis)} is not a serve axis — use "
                    "--replicas for data parallelism"
                )
        if plan.model == 1:
            plan = None
    per = plan.num_devices if plan is not None else 1
    need = per * replicas
    devices = jax.devices()
    if plan is not None or replicas > 1:
        if need > len(devices):
            raise SystemExit(
                f"{prog}: --mesh/--replicas need {need} devices "
                f"({replicas} replicas x {per}), have {len(devices)}"
            )
    if plan is None:
        if replicas == 1:
            return None, [None]
        # DP without TP: each replica still gets ITS OWN chip — a
        # one-device placement mesh (model=1) pins that replica's
        # params + pool there, so N replicas really occupy N devices
        # instead of piling onto the default one
        from llm_np_cp_tpu.parallel.sharding import MeshPlan

        plan = MeshPlan()
    return plan, [devices[i * per:(i + 1) * per] for i in range(replicas)]


def _chaos_injector(args):
    """Resolve --chaos-spec (or LLMTPU_CHAOS_SPEC) into a FaultInjector —
    or None, the zero-overhead default.  Called BEFORE the model load so
    the ckpt_read site covers checkpoint IO, and installed globally for
    the engine-less injection points.  Malformed specs fail here, before
    any multi-minute load."""
    import os

    from llm_np_cp_tpu.serve.faults import FaultInjector, install

    spec = args.chaos_spec
    if spec is None:
        spec = os.environ.get("LLMTPU_CHAOS_SPEC", "")
    try:
        injector = FaultInjector.from_spec(spec, seed=args.chaos_seed)
    except ValueError as e:
        raise SystemExit(f"--chaos-spec: {e}") from None
    if injector is not None:
        install(injector)
        print(f"[chaos] fault injection ACTIVE: {spec!r} "
              f"(seed {args.chaos_seed})")
    return injector


def _build_serve_engine(args, params, config, *, prog: str,
                        tokenizer=None, max_queue: int | None = None,
                        fault_injector=None, mesh_plan=None,
                        mesh_devices=None, shared_tracer=None,
                        journal=None, shared_request_log=None,
                        shared_host_tier=None, quiet=False):
    """The shared engine build for both serve subcommands: validate the
    pool flags, resolve --attn-impl against the Mosaic probe (an EXPLICIT
    paged request must fail with an actionable message when the kernel
    does not compile — not a Pallas traceback at first dispatch, and not
    a silent downgrade, which is what auto is for), size the pool, build.
    """
    import jax.numpy as jnp

    from llm_np_cp_tpu.ops.sampling import Sampler
    from llm_np_cp_tpu.serve import ServeEngine
    from llm_np_cp_tpu.serve.engine import pool_geometry

    _validate_pool_flags(args)  # re-checked for non-CLI callers
    cache_dtype = {
        "bf16": jnp.bfloat16, "f32": jnp.float32, "int8": jnp.int8,
    }[args.cache_dtype]
    gather_impl = "flash_decode" if args.decode_attn == "pallas" else "xla"
    if args.attn_impl in ("paged", "auto"):
        from llm_np_cp_tpu.ops.pallas.support import (
            kernel_error,
            paged_kernel_name,
        )

        paged_kernel = paged_kernel_name(args.cache_dtype == "int8")
        err = kernel_error(paged_kernel)
        if err is None:
            decode_attn_impl = "paged"
        elif args.attn_impl == "auto":
            print(f"[{prog}] --attn-impl auto: paged kernel "
                  f"unavailable ({err}); using the gather path")
            decode_attn_impl = gather_impl
        else:
            raise SystemExit(
                f"--attn-impl paged: the {paged_kernel} kernel does not "
                f"compile on this backend ({err}); use --attn-impl "
                "gather, or auto to fall back automatically"
            )
    else:
        decode_attn_impl = gather_impl

    # tracing on iff requested (--trace-out / --trace-ring / implied by
    # --jax-profile — the TraceAnnotation scopes that correlate the
    # device profile only exist while a recorder is attached): the
    # recorder's absence IS the off switch — every engine/HTTP hook is
    # a single is-None check when it is None
    tracer = shared_tracer
    jax_profile = getattr(args, "jax_profile", None)
    sentinel_on = getattr(args, "tick_sentinel", False)
    otlp_endpoint = getattr(args, "otlp_endpoint", None)
    if tracer is None and (args.trace_out or args.trace_ring
                           or jax_profile or sentinel_on
                           or otlp_endpoint):
        from llm_np_cp_tpu.serve.tracing import TraceRecorder

        ring = args.trace_ring or None
        if ring is None and not args.trace_out:
            # --jax-profile / --tick-sentinel / --otlp-endpoint alone:
            # the recorder exists for its annotation scopes / phase
            # timestamps / span feed — keep its memory bounded
            ring = 100_000
        tracer = TraceRecorder(ring=ring)
        implied = (jax_profile or sentinel_on or otlp_endpoint) \
            and not (args.trace_out or args.trace_ring)
        print(f"[{prog}] tracing ACTIVE (ring={ring or 'unbounded'}"
              + (f", dump to {args.trace_out}" if args.trace_out else "")
              + (", implied by --jax-profile/--tick-sentinel/"
                 "--otlp-endpoint" if implied else "")
              + ")")
    if otlp_endpoint and tracer is not None and tracer.otel is None:
        # one exporter per PROCESS, shared by every replica through the
        # shared recorder (replica engines arrive with shared_tracer
        # already carrying it)
        from llm_np_cp_tpu.serve.otel import OtlpExporter

        OtlpExporter(
            otlp_endpoint, resource_attrs={"llm.model": args.model},
        ).attach(tracer)
        print(f"[{prog}] OTLP export ACTIVE: {otlp_endpoint} "
              "(spans batched off-thread, dropped+counted on "
              "collector failure)")
    sentinel = None
    if sentinel_on:
        from llm_np_cp_tpu.serve.slo import TickSentinel

        sentinel = TickSentinel(
            threshold=getattr(args, "sentinel_threshold", 8.0))
        if not quiet:
            print(f"[{prog}] tick sentinel ACTIVE "
                  f"(threshold {sentinel.threshold:g} deviations)")
    actions = None
    if getattr(args, "auto_actions", False):
        from llm_np_cp_tpu.serve.lifecycle import ActionPolicy

        # one policy PER ENGINE (verdict state is tick-thread-owned);
        # each replica's _build_serve_engine call constructs its own
        actions = ActionPolicy(
            burn_threshold=getattr(args, "shed_burn_threshold", 2.0),
        )
        if not quiet:
            slo_on = bool(getattr(args, "slo_ttft", 0.0)
                          or getattr(args, "slo_tpot", 0.0))
            print(f"[{prog}] auto-actions ACTIVE: shed prefill on "
                  "persistent host_sync anomalies"
                  + ("" if sentinel_on else
                     " (needs --tick-sentinel to observe)")
                  + ", 503-first shedding past burn "
                  f"{actions.burn_threshold:g}"
                  + ("" if slo_on else
                     " (needs --slo-ttft/--slo-tpot to measure burn)"))
    telemetry = None
    if getattr(args, "roofline", False):
        from llm_np_cp_tpu.serve.telemetry import TelemetryModel

        telemetry = TelemetryModel(
            config, params, hbm_gbps=getattr(args, "hbm_gbps", 819.0),
        )
        if not quiet:
            print(f"[{prog}] roofline telemetry ACTIVE: grading "
                  f"dispatches against {telemetry.hbm_gbps:g} GB/s "
                  "(achieved GB/s + MFU on /metrics, per-request cost "
                  "attribution in the request log)")
    slo_ttft = getattr(args, "slo_ttft", 0.0) or None
    slo_tpot = getattr(args, "slo_tpot", 0.0) or None
    slo_policy = None
    if slo_ttft or slo_tpot:
        from llm_np_cp_tpu.serve.slo import SLOPolicy

        slo_policy = SLOPolicy(
            ttft_s=slo_ttft, tpot_s=slo_tpot,
            target=getattr(args, "slo_target", 0.99),
        )
    tenants = None
    tenant_fairness = getattr(args, "tenant_fairness", False)
    tenant_cap = getattr(args, "tenant_max_inflight", 0)
    if tenant_cap < 0:
        raise SystemExit(
            f"--tenant-max-inflight must be >= 0, got {tenant_cap}")
    if getattr(args, "tenants", False) or tenant_fairness or tenant_cap:
        max_series = getattr(args, "max_tenant_series", 20)
        if max_series < 1:
            raise SystemExit(
                f"--max-tenant-series must be >= 1, got {max_series}")
        from llm_np_cp_tpu.serve.tenants import TenantLedger

        # one ledger PER ENGINE (R3: lock-grouped shared state, like
        # metrics); replica builds clone their own via
        # _fresh_replica_engine, and the scrape/debug layers aggregate
        tenants = TenantLedger(
            fairness=tenant_fairness,
            max_inflight=tenant_cap or None,
            max_series=max_series,
            policy=slo_policy,
        )
        if not quiet:
            print(f"[{prog}] tenant accounting ACTIVE: "
                  f"fairness={'on' if tenant_fairness else 'off'}, "
                  f"max-inflight={tenant_cap or 'uncapped'}, "
                  f"top-{max_series} tenants labeled on /metrics "
                  "(X-Tenant-Id header names the tenant; "
                  "GET /debug/tenants for the full breakdown)")
    host_tier = shared_host_tier
    if host_tier is None and getattr(args, "kv_tier", "off") == "host":
        if not args.prefix_cache:
            raise SystemExit(
                "--kv-tier host requires --prefix-cache: the tier is "
                "keyed by the prefix cache's chained content hashes"
            )
        gb = getattr(args, "kv_host_tier_gb", 4.0)
        if gb <= 0:
            raise SystemExit(
                f"--kv-host-tier-gb must be > 0, got {gb:g}"
            )
        from llm_np_cp_tpu.serve.host_tier import HostTier

        # ONE tier per process, shared by every replica (replica builds
        # arrive with shared_host_tier already set) — that sharing IS
        # the fleet block-shipping path: a drain/re-home spills through
        # it and the destination replica restores from it
        host_tier = HostTier(int(gb * 2**30))
        if not quiet:
            print(f"[{prog}] KV host tier ACTIVE: {gb:g} GiB host pool "
                  "(evicted prefix blocks spill instead of dropping; "
                  "admissions restore above the measured breakeven; "
                  "shared fleet-wide for drain/re-home block shipping)")
    request_log = shared_request_log
    rl_path = getattr(args, "request_log", None)
    if request_log is None and rl_path:
        from llm_np_cp_tpu.serve.request_log import RequestLog

        request_log = RequestLog(rl_path)
        print(f"[{prog}] request log ACTIVE: {rl_path} "
              "(one JSON line per terminal)")

    # same chunking as bench.run_serve_config, so the README's CLI line
    # compiles the same prefill programs as the recorded bench numbers
    chunk = min(args.block_size * 2, 256)
    _, sized_blocks, max_seq_len = pool_geometry(
        args.prompt_len, args.max_tokens, args.slots, args.block_size,
        prefill_chunk=chunk,
    )
    num_blocks = args.num_blocks or sized_blocks
    engine = ServeEngine(
        params, config,
        sampler=Sampler(kind=args.sampler),
        max_slots=args.slots,
        num_blocks=num_blocks,
        block_size=args.block_size,
        max_seq_len=max_seq_len,
        prefill_chunk=chunk,
        cache_dtype=cache_dtype,
        decode_attn_impl=decode_attn_impl,
        enable_prefix_cache=args.prefix_cache,
        max_queue=max_queue,
        tokenizer=tokenizer,
        fault_injector=fault_injector,
        tracer=tracer,
        mixed_step=getattr(args, "mixed_step", "off"),
        sample_epilogue=getattr(args, "sample_epilogue", "auto"),
        tick_token_budget=getattr(args, "tick_token_budget", 0) or None,
        mesh_plan=mesh_plan,
        mesh_devices=mesh_devices,
        journal=journal,
        request_log=request_log,
        sentinel=sentinel,
        actions=actions,
        telemetry=telemetry,
        host_tier=host_tier,
        tenants=tenants,
        spec_k=(
            getattr(args, "spec_k", 4)
            if getattr(args, "speculative_serve", False) else 0
        ),
    )
    if slo_policy is not None:
        from llm_np_cp_tpu.serve.slo import SLOTracker

        engine.metrics.slo = SLOTracker(slo_policy, clock=engine.clock)
        if not quiet:
            print(f"[{prog}] SLO accounting ACTIVE: "
                  f"ttft<={slo_ttft or '-'}s tpot<={slo_tpot or '-'}s "
                  f"target {getattr(args, 'slo_target', 0.99):g} "
                  "(goodput/burn on /metrics, GET /debug/slo)")
    if quiet:
        return engine, num_blocks
    if engine.mesh is not None:
        print(f"[{prog}] mesh ACTIVE: {engine.mesh_desc}")
    if engine.mixed:
        print(f"[{prog}] unified tick ACTIVE: one mixed dispatch/tick, "
              f"budget {engine.tick_token_budget} tokens "
              f"(ragged attention: {engine.ragged_attn_impl}, "
              f"epilogue={'fused' if engine.epilogue_impl == 'fused' else 'xla'})")
    elif getattr(args, "mixed_step", "off") == "auto":
        print(f"[{prog}] --mixed-step auto: ragged kernel unavailable; "
              "using the phase-split tick "
              f"(epilogue={'fused' if engine.epilogue_impl == 'fused' else 'xla'})")
    if engine.spec_k:
        print(f"[{prog}] speculative serving ACTIVE: k={engine.spec_k} "
              "draft tokens/tick, prompt-lookup drafts verified in the "
              "mixed dispatch (per-request opt-in: "
              '"speculative": true)')
    elif getattr(args, "speculative_serve", False):
        print(f"[{prog}] --speculative-serve requested but the unified "
              "tick is unavailable; serving plain decode")
    return engine, num_blocks


def _jax_profile_ctx(args):
    """--jax-profile DIR → a jax.profiler trace context (device timeline
    correlatable with the host trace via the TraceAnnotation scopes), or
    a no-op context."""
    import contextlib

    if not getattr(args, "jax_profile", None):
        return contextlib.nullcontext()
    from llm_np_cp_tpu.utils.profiling import trace as jax_trace

    return jax_trace(args.jax_profile)


def _close_otel(tracer, prog: str) -> None:
    """Final flush of the OTLP exporter (if one rode the recorder):
    everything offered is attempted against the collector once before
    exit, then the ship/drop tally is printed."""
    otel = getattr(tracer, "otel", None)
    if otel is None:
        return
    otel.flush(10.0)
    otel.close()
    st = otel.stats()
    print(f"[{prog}] OTLP export: {st['spans']} spans shipped in "
          f"{st['batches']} batches, {st['dropped']} dropped "
          f"({st['export_errors']} collector errors)")


def _dump_trace(tracer, args, prog: str) -> None:
    # takes the RECORDER, not the engine: a supervised restart mutes the
    # dead engine's tracer attribute, but the recorder object (shared by
    # every rebuilt engine) holds the full timeline
    if args.trace_out and tracer is not None:
        n = tracer.dump(args.trace_out)
        print(f"[{prog}] wrote {n} trace events to {args.trace_out}"
              + (f" ({tracer.dropped} dropped by the ring)"
                 if tracer.dropped else ""))


def _run_serve_bench(argv: list[str], default_model: str) -> str:
    import json as _json

    from llm_np_cp_tpu.serve import poisson_trace

    args = build_serve_parser(default_model).parse_args(argv)
    _validate_pool_flags(args)
    if args.distinct_prompts < 0:
        raise SystemExit(
            f"--distinct-prompts must be >= 0 (0 = every prompt distinct), "
            f"got {args.distinct_prompts}"
        )
    plan, dev_slices = _resolve_serve_mesh(args, "serve-bench")
    injector = _chaos_injector(args)
    _tok, params, config = _load(args)
    engine, num_blocks = _build_serve_engine(
        args, params, config, prog="serve-bench", fault_injector=injector,
        mesh_plan=plan, mesh_devices=dev_slices[0],
    )
    replica_set = None
    if args.replicas > 1:
        from llm_np_cp_tpu.serve import ReplicaSet

        peers = [
            _build_serve_engine(
                args, params, config, prog="serve-bench",
                fault_injector=injector, mesh_plan=plan,
                mesh_devices=dev_slices[i], shared_tracer=engine.tracer,
                shared_request_log=engine.request_log,
                shared_host_tier=engine.host_tier,
                quiet=True,
            )[0]
            for i in range(1, args.replicas)
        ]
        replica_set = ReplicaSet(
            [engine] + peers,
            spill_queue_depth=args.spill_queue_depth or None,
        )
        print(f"[serve-bench] replicas ACTIVE: {args.replicas} engines, "
              "prefix-affinity routing")
    rng = np.random.default_rng(args.seed)
    trace = poisson_trace(
        rng, args.requests, rate_rps=args.rate,
        prompt_len_range=(max(args.prompt_len // 4, 1), args.prompt_len),
        max_new_tokens=args.max_tokens, vocab_size=config.vocab_size,
        seed_base=args.seed,
        distinct_prompts=args.distinct_prompts or None,
    )
    if engine.spec_k:
        # serve-bench's whole trace opts in (the HTTP surface is where
        # per-request opt-in lives); tokens are identical either way
        for item in trace:
            item["speculative"] = True
    # compile outside the measured span (steady-state numbers only)
    lens = [int(t["prompt"].size) for t in trace]
    if replica_set is not None:
        for e in replica_set.engines:
            e.warmup(lens, max_new_tokens=args.max_tokens)
    else:
        engine.warmup(lens, max_new_tokens=args.max_tokens)
    with _jax_profile_ctx(args):
        snap = (replica_set or engine).replay_trace(
            trace, realtime=args.realtime
        )
    _dump_trace(engine.tracer, args, "serve-bench")
    _close_otel(engine.tracer, "serve-bench")
    tick = (
        f"mixed:{engine.ragged_attn_impl}"
        f"(budget={engine.tick_token_budget})"
        if engine.mixed else "split"
    ) + f",epilogue={engine.epilogue_impl}"
    topo = engine.mesh_desc or "single chip"
    if args.replicas > 1:
        if topo.startswith("pinned to"):
            # DP without TP: each replica owns one device; replica 0's
            # own desc would misread as the whole fleet's placement
            topo = f"{args.replicas} replicas x (1 device each)"
        else:
            topo = f"{args.replicas} replicas x ({topo})"
    out = (
        f"[serve-bench] {args.requests} requests @ {args.rate} req/s, "
        f"slots={args.slots}, pool={num_blocks}x{args.block_size} "
        f"({args.cache_dtype}), attn={engine.decode_attn_impl}, "
        f"tick={tick}, topo={topo}, "
        f"prefix_cache={'on' if args.prefix_cache else 'off'}, "
        f"kv_tier={args.kv_tier}\n"
    )
    if replica_set is not None:
        out += (
            f"fleet: {snap['finished']} finished, "
            f"{snap['throughput_tok_s']:.1f} tok/s, ttft p99 "
            f"{snap.get('ttft_s_p99', float('nan')):.3f}s, router "
            f"{snap['router_routed']} routed / "
            f"{snap['router_spilled']} spilled\n"
            + "\n".join(
                f"-- replica {i} --\n{e.metrics.format()}"
                for i, e in enumerate(replica_set.engines)
            )
        )
    else:
        out += engine.metrics.format()
    if "goodput_tok_s" in snap:
        att = snap.get("slo_attainment")
        out += (
            f"\nslo: attainment "
            f"{att if att is None else format(att, '.3f')}, "
            f"goodput {snap['goodput_tok_s']:.1f} tok/s, burn "
            f"5m {snap.get('slo_burn_rate_5m', 0.0):.2f} / "
            f"1h {snap.get('slo_burn_rate_1h', 0.0):.2f}"
        )
    print(out)
    if engine.request_log is not None:
        engine.request_log.close()
        print(f"[serve-bench] wrote "
              f"{engine.request_log.stats()['records']} request-log "
              f"lines to {args.request_log}")
    if args.json:
        print(_json.dumps(snap))
    return out


def _run_http_serve(argv: list[str], default_model: str) -> str:
    from llm_np_cp_tpu.serve.http import serve_forever

    args = build_http_serve_parser(default_model).parse_args(argv)
    _validate_pool_flags(args)
    if args.max_queue < 0:
        raise SystemExit(f"--max-queue must be >= 0, got {args.max_queue}")
    if args.request_timeout < 0:
        raise SystemExit(
            f"--request-timeout must be >= 0, got {args.request_timeout}"
        )
    if args.tick_deadline < 0:
        raise SystemExit(
            f"--tick-deadline must be >= 0, got {args.tick_deadline}"
        )
    if args.max_restarts < 0:
        raise SystemExit(
            f"--max-restarts must be >= 0, got {args.max_restarts}"
        )
    plan, dev_slices = _resolve_serve_mesh(args, "serve")
    injector = _chaos_injector(args)
    # per-replica durable journal segments, opened (and replayed for
    # unterminated requests) BEFORE the model load is visible to
    # clients; a malformed path fails fast here
    journals: list = [None] * args.replicas
    if args.journal:
        from llm_np_cp_tpu.serve.journal import RequestJournal

        paths = (
            [args.journal] if args.replicas == 1
            else [f"{args.journal}.{i}" for i in range(args.replicas)]
        )
        journals = [
            RequestJournal(p, fault_injector=injector,
                           compact_bytes=args.journal_compact_bytes,
                           sync_admissions=args.journal_sync == "admission")
            for p in paths
        ]
        replays = [j.stats()["replayed"] for j in journals]
        print(f"[serve] journal ACTIVE: {args.journal} "
              f"(epoch {journals[0].epoch}, sync={args.journal_sync}, "
              f"{sum(replays)} unterminated to replay)")
    tok, params, config = _load(args)
    engine, num_blocks = _build_serve_engine(
        args, params, config, prog="serve", tokenizer=tok,
        max_queue=args.max_queue or None, fault_injector=injector,
        mesh_plan=plan, mesh_devices=dev_slices[0], journal=journals[0],
    )
    engines = [engine] + [
        _build_serve_engine(
            args, params, config, prog="serve", tokenizer=tok,
            max_queue=args.max_queue or None, fault_injector=injector,
            mesh_plan=plan, mesh_devices=dev_slices[i],
            shared_tracer=engine.tracer, journal=journals[i],
            shared_request_log=engine.request_log,
            shared_host_tier=engine.host_tier, quiet=True,
        )[0]
        for i in range(1, args.replicas)
    ]
    runner = None
    if args.replicas > 1:
        from llm_np_cp_tpu.serve import ReplicaRunner

        runner = ReplicaRunner(
            engines,
            request_timeout=args.request_timeout or None,
            tick_deadline=args.tick_deadline or None,
            max_restarts=args.max_restarts,
            restart_window_s=args.restart_window,
            spill_queue_depth=args.spill_queue_depth or None,
        )
    # hold the recorder here: a supervised restart rebinds the runner's
    # engine and mutes the dead one's tracer attribute
    tracer = engine.tracer
    # warm the phase programs BEFORE accepting traffic: the first real
    # request must not pay a multi-second model compile in its TTFT
    for e in engines:
        e.warmup([args.prompt_len], max_new_tokens=args.max_tokens)
    topo = engine.mesh_desc or "single chip"
    if args.replicas > 1:
        if topo.startswith("pinned to"):
            # DP without TP: each replica owns one device; replica 0's
            # own desc would misread as the whole fleet's placement
            topo = f"{args.replicas} replicas x (1 device each)"
        else:
            topo = f"{args.replicas} replicas x ({topo})"
    banner = (
        f"[serve] model={args.model} slots={args.slots} "
        f"pool={num_blocks}x{args.block_size} ({args.cache_dtype}), "
        f"attn={engine.decode_attn_impl}, "
        f"epilogue={engine.epilogue_impl}, topo={topo}, "
        f"prefix_cache={'on' if args.prefix_cache else 'off'}, "
        f"kv_tier={args.kv_tier}, "
        f"max_queue={args.max_queue or 'unbounded'}, "
        f"supervision={'off' if not args.max_restarts else f'{args.max_restarts} restarts'}, "
        f"journal={'on' if args.journal else 'off'}"
    )
    print(banner)

    def on_started(server) -> None:
        print(f"[serve] listening on http://{server.host}:{server.port} "
              f"(POST /v1/completions, GET /healthz, GET /metrics)")

    def upgrade_loader(body: dict):
        # POST /admin/upgrade: reload a checkpoint (the body may name a
        # different --model) and hand the params to the rolling swap.
        # Geometry must match — the pool/steps are shaped by config,
        # and a mismatched checkpoint must abort the roll, not corrupt
        # the fleet
        ns = argparse.Namespace(**vars(args))
        if body.get("model"):
            ns.model = str(body["model"])
        print(f"[serve] admin upgrade: loading checkpoint {ns.model}")
        _, new_params, new_config = _load(ns)
        if new_config != config:
            raise ValueError(
                f"upgrade checkpoint {ns.model} has a different model "
                "geometry than the serving config; rolling upgrades "
                "swap weights, not architectures"
            )
        return new_params

    with _jax_profile_ctx(args):
        serve_forever(
            engine,
            model_id=args.model,
            tokenizer=tok,
            host=args.host,
            port=args.port,
            request_timeout=args.request_timeout or None,
            drain_timeout=args.drain_timeout,
            default_max_tokens=args.max_tokens,
            max_tokens_cap=args.max_tokens,
            tick_deadline=args.tick_deadline or None,
            max_restarts=args.max_restarts,
            restart_window_s=args.restart_window,
            port_file=args.port_file,
            exit_after_s=args.exit_after_s,
            on_started=on_started,
            runner=runner,
            upgrade_loader=upgrade_loader,
        )
    _dump_trace(tracer, args, "serve")
    _close_otel(tracer, "serve")
    if engine.request_log is not None:
        engine.request_log.close()
    print("[serve] drained, bye")
    return banner


def run(argv: list[str] | None = None, default_model: str = "meta-llama/Llama-3.2-1B") -> str:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "serve-bench":
        return _run_serve_bench(argv[1:], default_model)
    if argv and argv[0] == "serve":
        return _run_http_serve(argv[1:], default_model)
    args = build_parser(default_model).parse_args(argv)
    _validate_draft(args)
    if args.batch_size < 0:
        raise SystemExit(f"--batch-size must be >= 0, got {args.batch_size}")
    if args.prompts_file and args.backend == "numpy":
        raise SystemExit(
            "--prompts-file batches through the tpu backend; the numpy "
            "oracle is single-prompt"
        )
    # --prompts-file composes with --prefill-chunk: ragged chunks slice
    # the pad mask per chunk and the cache bitmap persists validity
    # (generate.make_chunked_prefill_fn ragged_step)
    if args.prompts_file and (args.attn_impl in ("flash", "ring") or args.flash_prefill):
        raise SystemExit(
            "--prompts-file uses ragged pad masks, which the flash/ring "
            "prefill kernels do not consume; use the default --attn-impl xla"
        )
    if args.backend == "numpy":
        if args.quantize != "none":
            raise SystemExit("--quantize applies to the tpu backend only "
                             "(the numpy oracle is fp32 by definition)")
        return _run_numpy(args)
    return _run_tpu(args)


def _parse_draft(kind: str) -> tuple[int | None, bool]:
    """--draft KIND → (trunc_layers | None, int4).  Raises SystemExit on
    malformed kinds — called at parse time, before any model load."""
    import re

    if kind == "int8":
        return None, False
    if kind == "int4":
        return None, True
    m = re.fullmatch(r"trunc(\d+)(_int4)?", kind)
    if m is None or int(m.group(1)) < 1:
        raise SystemExit(
            f"--draft must be int8, int4, truncN or truncN_int4; got {kind!r}"
        )
    return int(m.group(1)), bool(m.group(2))


def _validate_draft(args) -> None:
    """Fail fast on bad --draft combinations, before the model loads."""
    trunc_layers, int4 = _parse_draft(args.draft)
    if args.draft != "int8" and args.speculative == 0:
        raise SystemExit("--draft requires --speculative GAMMA")
    if int4 and args.quantize != "none":
        # re-quantizing already-quantized dict leaves is undefined; the
        # int8 self-draft (reuse-the-target guard) and plain truncN
        # (slices quantized leaves fine) both compose with --quantize
        raise SystemExit(
            f"--draft {args.draft} requires an unquantized target; with "
            f"--quantize {args.quantize}, use --draft int8 or truncN"
        )


def _draft_kwargs(kind: str, params: Any, config: Any) -> dict[str, Any]:
    """--draft KIND → SpeculativeGenerator draft kwargs.

    int8 is the class default (empty kwargs); int4 quantizes the target's
    projections to 4 bits; truncN[_int4] takes the target's first N
    layers (speculative.truncated_draft), optionally int4-quantized.
    Combination validity was checked at parse time (_validate_draft).
    """
    trunc_layers, int4 = _parse_draft(kind)
    if trunc_layers is not None:
        from llm_np_cp_tpu.speculative import truncated_draft

        dp, dc = truncated_draft(
            params, config, trunc_layers, bits=4 if int4 else None
        )
        return {"draft_params": dp, "draft_config": dc}
    if int4:
        from llm_np_cp_tpu.quant import quantize_params

        return {"draft_params": quantize_params(params, bits=4)}
    return {}


def _load(args) -> tuple[Any, Any, Any]:
    import jax.numpy as jnp

    from llm_np_cp_tpu.utils.loading import load_model

    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    return load_model(args.model, dtype=dtype)


def _run_numpy(args) -> str:
    """The reference's NumPy path: fp32 oracle forward, Python decode loop."""
    import jax

    from llm_np_cp_tpu.backends.numpy_ref import NpKVCache, forward_np

    tok, params, config = _load(args)
    params_np = jax.tree.map(lambda x: np.asarray(x, np.float32), params)
    rng = np.random.default_rng(args.seed)

    ids = tok(args.prompt, return_tensors="np")["input_ids"].astype(np.int32)
    prompt_len = ids.shape[1]
    cache = None if args.no_cache else NpKVCache()
    all_ids = list(ids[0])
    emitted = ""
    t0 = time.perf_counter()
    ttft = None
    for i in range(args.max_tokens):
        logits, cache = forward_np(params_np, ids, config, cache)
        nxt = _sample_np(logits[0, -1], args, rng)
        if ttft is None:
            ttft = time.perf_counter() - t0
        all_ids.append(nxt)
        text = tok.decode(all_ids[prompt_len:], skip_special_tokens=True)
        if not text.endswith("�"):
            delta, emitted = text[len(emitted):], text
            print(delta, end="", flush=True)
        if nxt == getattr(tok, "eos_token_id", None):
            break
        if args.no_cache:
            ids = np.asarray([all_ids], dtype=np.int32)
        else:
            ids = np.asarray([[nxt]], dtype=np.int32)
    # final flush: emit any delta held back by the mid-multibyte guard
    text = tok.decode(all_ids[prompt_len:], skip_special_tokens=True)
    if text != emitted:
        print(text[len(emitted):], end="", flush=True)
        emitted = text
    print()
    if args.metrics:
        dt = time.perf_counter() - t0
        n = len(all_ids) - prompt_len
        print(f"[numpy] {n} tokens in {dt:.2f}s "
              f"({n / dt:.2f} tok/s, ttft {ttft:.2f}s)", file=sys.stderr)
    return emitted


def _sample_np(logits: np.ndarray, args, rng: np.random.Generator) -> int:
    """NumPy samplers mirroring ops.sampling semantics (all five kinds)."""
    logits = logits.astype(np.float64)
    if args.sampler == "greedy":
        return int(np.argmax(logits))
    logits = logits / args.temperature
    p = np.exp(logits - logits.max())
    p /= p.sum()
    if args.sampler == "min_p":
        keep = p >= p.max() * args.p_base
    elif args.sampler == "top_k":
        kth = np.sort(p)[-min(max(args.top_k, 1), p.size)]
        keep = p >= kth
    elif args.sampler == "top_p":
        order = np.argsort(p)[::-1]
        csum = np.cumsum(p[order])
        keep_sorted = (csum - p[order]) < args.top_p
        keep_sorted[0] = True  # top token always survives (p<=0 → greedy)
        keep = np.zeros_like(p, dtype=bool)
        keep[order[keep_sorted]] = True
    else:  # cdf: plain draw from the full distribution
        keep = np.ones_like(p, dtype=bool)
    p = np.where(keep, p, 0.0)
    p /= p.sum()
    return int(rng.choice(len(p), p=p))


def _run_tpu(args) -> str:
    import jax
    import jax.numpy as jnp

    from llm_np_cp_tpu.generate import Generator
    from llm_np_cp_tpu.ops.sampling import Sampler
    from llm_np_cp_tpu.parallel.sharding import (
        make_mesh, parse_mesh_spec, shard_params,
    )

    plan = parse_mesh_spec(args.mesh)
    if plan.pipe > 1 or plan.expert > 1:
        raise SystemExit(
            "pipe/expert parallelism are training-side axes "
            "(python -m llm_np_cp_tpu.train); inference meshes use "
            "data/seq/model"
        )
    seq = plan.seq

    tok, params, config = _load(args)

    if args.quantize != "none":
        from llm_np_cp_tpu.quant import quantize_params

        params = quantize_params(
            params, bits=4 if args.quantize.startswith("int4") else 8,
            act_quant=args.quantize.endswith("_a8"),
        )
    mesh = None
    if plan.num_devices > 1:
        plan.validate(config)
        mesh = make_mesh(plan)
        params = shard_params(params, config, plan, mesh)

    if args.speculative > 0 and (
        args.attn_impl or args.flash_prefill or args.decode_attn != "xla"
    ):
        raise SystemExit(
            "--speculative uses its own fused draft/verify pipeline; "
            "--attn-impl/--flash-prefill/--decode-attn do not apply to it"
        )
    if args.speculative > 0 and (args.batch_size or args.early_stop):
        # these flags were silently ignored on the speculative branch
        # (ADVICE r5); reject loudly like the kernel flags above
        raise SystemExit(
            "--speculative does not implement --batch-size grouping or "
            "--early-stop (its verify loop has its own stopping rule); "
            "drop those flags or drop --speculative"
        )
    attn_impl = args.attn_impl or ("flash" if args.flash_prefill else "xla")
    if attn_impl == "ring" and (mesh is None or seq <= 1):
        raise SystemExit(
            "--attn-impl ring needs a sequence-parallel mesh: pass "
            "--mesh data,seq,model with seq>1 (ring attention shards the "
            "prompt over the mesh's 'seq' axis)"
        )

    sampler = Sampler(
        kind=args.sampler, temperature=args.temperature, p_base=args.p_base,
        top_k=args.top_k, top_p=args.top_p,
    )
    eos = getattr(tok, "eos_token_id", None)
    cache_dtype = {
        "auto": jnp.bfloat16 if args.dtype == "bf16" else jnp.float32,
        "bf16": jnp.bfloat16,
        "f32": jnp.float32,
        "int8": jnp.int8,
    }[args.cache_dtype]

    import contextlib

    ctx = jax.set_mesh(mesh) if mesh is not None else contextlib.nullcontext()

    # one definition of prompts-file parsing for BOTH pipelines below
    batch_prompt_ids = None
    if args.prompts_file:
        with open(args.prompts_file) as f:
            prompts = [line.rstrip("\n") for line in f if line.strip()]
        if not prompts:
            raise SystemExit(f"--prompts-file {args.prompts_file}: no prompts")
        batch_prompt_ids = [
            tok(p, return_tensors="np")["input_ids"][0].astype(np.int32)
            for p in prompts
        ]

    if args.speculative > 0:
        from llm_np_cp_tpu.speculative import SpeculativeGenerator

        # Under the mesh context from construction on: the draft derives
        # from the (possibly sharded) params, and every spec jit must see
        # the same mesh as the target model's (VERDICT r2 weak #5: this
        # branch used to run before jax.set_mesh entirely).
        with ctx:
            spec = SpeculativeGenerator(
                params, config, gamma=args.speculative, sampler=sampler,
                cache_dtype=cache_dtype, prefill_chunk=args.prefill_chunk,
                **_draft_kwargs(args.draft, params, config),
            )
            stops = (eos,) if eos is not None else ()
            if batch_prompt_ids is not None:
                res = spec.generate_ragged(
                    batch_prompt_ids, args.max_tokens,
                    max_seq_len=args.max_seq_len, seed=args.seed,
                    stop_tokens=stops,
                )
                texts = [
                    tok.decode(row, skip_special_tokens=True)
                    for row in np.asarray(res.tokens)
                ]
                for text in texts:
                    print(text)
                if args.metrics:
                    print(
                        f"[tpu] speculative ragged batch of {len(texts)} "
                        f"γ={args.speculative}: {res.decode_tokens_per_s:.1f} "
                        f"tok/s aggregate, accept {res.acceptance_rate:.2f}, "
                        f"{res.tokens_per_round:.2f} tok/round, "
                        f"ttft {res.ttft_s:.3f}s",
                        file=sys.stderr,
                    )
                return "\n".join(texts)
            prompt_ids = tok(args.prompt, return_tensors="np")["input_ids"][0]
            res = spec.generate(
                prompt_ids, args.max_tokens, seed=args.seed,
                stop_tokens=stops,
            )
        text = tok.decode(res.tokens, skip_special_tokens=True)
        print(text)
        if args.metrics:
            print(
                f"[tpu] speculative γ={args.speculative}: "
                f"{res.num_generated} tokens, {res.decode_tokens_per_s:.1f} "
                f"tok/s, accept {res.acceptance_rate:.2f}, "
                f"{res.tokens_per_round:.2f} tok/round, ttft {res.ttft_s:.3f}s",
                file=sys.stderr,
            )
        return text
    if args.early_stop and eos is None:
        raise SystemExit("--early-stop needs a tokenizer with an EOS token")
    gen = Generator(
        params, config,
        sampler=sampler,
        stop_tokens=(eos,) if eos is not None else (),
        cache_dtype=cache_dtype,
        prefill_attn_impl=attn_impl,
        prefill_chunk=args.prefill_chunk,
        decode_attn_impl="flash_decode" if args.decode_attn == "pallas" else "xla",
        early_stop=args.early_stop,
    )

    if batch_prompt_ids is not None:
        n_batches = 1
        with ctx:
            if args.batch_size and args.batch_size < len(batch_prompt_ids):
                # dynamic batching: ragged batches of N, longest-first
                results = gen.generate_many(
                    batch_prompt_ids, args.max_tokens,
                    batch_size=args.batch_size,
                    max_seq_len=args.max_seq_len, seed=args.seed,
                )
                rows = [np.asarray(r.tokens)[0] for r in results]
                # each result carries ITS batch's rate; time-to-first-
                # output is the first EXECUTED batch's ttft — the one
                # holding the longest prompt (longest-first grouping)
                row_rates = [r.decode_tokens_per_s for r in results]
                longest = max(
                    range(len(batch_prompt_ids)),
                    key=lambda i: len(batch_prompt_ids[i]),
                )
                ttft = results[longest].ttft_s
                rate = float(np.mean(row_rates))
                row_steps = [r.steps for r in results]
                n_batches = -(-len(rows) // args.batch_size)
            else:
                res = gen.generate_ragged(
                    batch_prompt_ids, args.max_tokens,
                    max_seq_len=args.max_seq_len, seed=args.seed,
                )
                rows = list(np.asarray(res.tokens))
                ttft, rate = res.ttft_s, res.decode_tokens_per_s
                row_rates = [rate] * len(rows)
                row_steps = [res.steps] * len(rows)
        texts, row_counts = [], []
        for row in rows:
            if eos is not None and (row == eos).any():
                row = row[: int(np.argmax(row == eos))]
            row_counts.append(len(row))
            texts.append(tok.decode(row, skip_special_tokens=True))
        for text in texts:
            print(text)
        if args.metrics:
            # each row scales ITS batch's per-sequence step rate by the
            # kept fraction (a row that hit EOS early still paid the
            # loop).  The denominator is steps EXECUTED + the prefill
            # token — with early_stop the loop may exit before the
            # budget, and the old budget-based denominator overstated
            # per-row rates (ADVICE r5).
            per_row = [
                f"{c}tok@{r * c / max(s + 1, 1):.1f}tok/s"
                for c, r, s in zip(row_counts, row_rates, row_steps)
            ]
            print(
                f"[tpu] ragged batch of {len(texts)}"
                + (f" in {n_batches} batches" if n_batches > 1 else "")
                + f": ttft {ttft:.3f}s, {rate:.1f} tok/s/row decode, rows: "
                + " ".join(per_row),
                file=sys.stderr,
            )
        return "\n".join(texts)

    with ctx:
        if args.no_stream:
            prompt_ids = tok(args.prompt, return_tensors="np")["input_ids"][0]
            res = gen.generate(
                prompt_ids, args.max_tokens,
                max_seq_len=args.max_seq_len, seed=args.seed,
            )
            text = tok.decode(res.tokens[0], skip_special_tokens=True)
            print(text)
            if args.metrics:
                print(
                    f"[tpu] {res.num_generated} tokens, ttft {res.ttft_s:.3f}s, "
                    f"{res.decode_tokens_per_s:.1f} tok/s decode",
                    file=sys.stderr,
                )
            return text
        text = gen.stream_text(
            tok, args.prompt, args.max_tokens, seed=args.seed,
            echo=lambda s: print(s, end="", flush=True),
        )
        print()
        if args.metrics:
            st = gen.last_stream_stats
            print(
                f"[tpu] streamed {st['tokens']} tokens in {st['duration_s']:.2f}s "
                f"(ttft {st['ttft_s']:.3f}s)",
                file=sys.stderr,
            )
        return text


if __name__ == "__main__":
    run()
