"""NumPy oracle backend.

A clean fp32 re-derivation of the reference semantics
(llama3.2_model_numpy.py — the de-facto golden path, SURVEY §1) used as:

1. the golden oracle for the JAX path's parity tests (SURVEY §4), and
2. the ``--backend=numpy`` runtime of the reference-compatible CLIs.

Deliberate fixes vs the reference (documented, SURVEY §7 "reference bugs to
NOT copy"):
- softmax is always max-stabilized (the reference's live NumPy softmax is
  the unstable ``exp/sum``, llama3.2_model_numpy.py:915);
- the causal mask is built from positions as q_len×kv_len, so 2-token
  prompts and chunked prefill are masked correctly (vs the ``q_len > 2``
  q_len×q_len tril guard, llama3.2_model.py:471-478);
- Gemma-2 attention-logit softcapping and sliding-window layers are
  honored when the config enables them (the reference drops both,
  SURVEY §2.7).

This file intentionally shares no code with ``models/transformer.py`` — it
is an independent implementation (loops + numpy, dynamic shapes, concat-grown
cache like the reference's KVCache, llama3.2_model.py:303-332) so that
agreement between the two is meaningful evidence of correctness.
"""

from __future__ import annotations

import math
from typing import Any

import numpy as np

from llm_np_cp_tpu.config import ModelConfig


class NpKVCache:
    """Reference-style append cache: per-layer lists, concat growth
    (llama3.2_model.py:303-332)."""

    def __init__(self) -> None:
        self.key_cache: list[np.ndarray] = []
        self.value_cache: list[np.ndarray] = []

    def num_items(self) -> int:
        if not self.key_cache:
            return 0
        return self.key_cache[0].shape[1]  # [B, S, K, D]

    def update(
        self, keys: np.ndarray, values: np.ndarray, layer_idx: int
    ) -> tuple[np.ndarray, np.ndarray]:
        if len(self.key_cache) <= layer_idx:
            self.key_cache.append(keys)
            self.value_cache.append(values)
        else:
            self.key_cache[layer_idx] = np.concatenate(
                [self.key_cache[layer_idx], keys], axis=1
            )
            self.value_cache[layer_idx] = np.concatenate(
                [self.value_cache[layer_idx], values], axis=1
            )
        return self.key_cache[layer_idx], self.value_cache[layer_idx]


def _rms_norm(x: np.ndarray, w: np.ndarray, eps: float, unit_offset: bool) -> np.ndarray:
    var = np.mean(np.square(x), axis=-1, keepdims=True)
    normed = x / np.sqrt(var + eps)
    weight = w + 1.0 if unit_offset else w
    return normed * weight


def _softmax(x: np.ndarray, axis: int = -1) -> np.ndarray:
    x = x - np.max(x, axis=axis, keepdims=True)
    e = np.exp(x)
    return e / np.sum(e, axis=axis, keepdims=True)


def _silu(x: np.ndarray) -> np.ndarray:
    return x / (1.0 + np.exp(-x))


def _gelu_tanh(x: np.ndarray) -> np.ndarray:
    return 0.5 * x * (1.0 + np.tanh(math.sqrt(2.0 / math.pi) * (x + 0.044715 * x**3)))


_ACT = {"silu": _silu, "gelu_pytorch_tanh": _gelu_tanh}


def _inv_freq(config: ModelConfig) -> np.ndarray:
    d = config.head_dim
    inv_freq = 1.0 / (config.rope_theta ** (np.arange(0, d, 2, dtype=np.float64) / d))
    if config.rope_scaling_type == "llama3":
        factor = config.rope_scaling_factor
        low = config.rope_scaling_low_freq_factor
        high = config.rope_scaling_high_freq_factor
        orig = config.rope_scaling_original_max_position
        wavelen = 2.0 * math.pi / inv_freq
        smooth = (orig / wavelen - low) / (high - low)
        scaled = np.where(wavelen > orig / low, inv_freq / factor, inv_freq)
        interp = (1.0 - smooth) / factor * inv_freq + smooth * inv_freq
        medium = (wavelen <= orig / low) & (wavelen >= orig / high)
        inv_freq = np.where(medium, interp, scaled)
    return inv_freq.astype(np.float32)


def _rope(positions: np.ndarray, config: ModelConfig) -> tuple[np.ndarray, np.ndarray]:
    freqs = positions.astype(np.float32)[..., None] * _inv_freq(config)
    emb = np.concatenate([freqs, freqs], axis=-1)
    return np.cos(emb), np.sin(emb)


def _rotate_half(x: np.ndarray) -> np.ndarray:
    h = x.shape[-1] // 2
    return np.concatenate([-x[..., h:], x[..., :h]], axis=-1)


def _softcap(x: np.ndarray, cap: float) -> np.ndarray:
    return np.tanh(x / cap) * cap


def _layer(params: dict[str, Any], idx: int) -> dict[str, np.ndarray]:
    # fp32 contract: per-layer weights are cast too, not just top-level ones
    # (bf16 checkpoint params must not silently compute in bf16 here).
    return {
        k: np.asarray(v[idx], dtype=np.float32) for k, v in params["layers"].items()
    }


def forward_np(
    params: dict[str, Any],
    input_ids: np.ndarray,
    config: ModelConfig,
    cache: NpKVCache | None = None,
) -> tuple[np.ndarray, NpKVCache | None]:
    """fp32 forward. input_ids [B, S] → logits [B, S, V] float32."""
    params = {
        "embed_tokens": np.asarray(params["embed_tokens"], dtype=np.float32),
        "layers": params["layers"],
        "final_norm": np.asarray(params["final_norm"], dtype=np.float32),
        **(
            {"lm_head": np.asarray(params["lm_head"], dtype=np.float32)}
            if "lm_head" in params
            else {}
        ),
    }
    b, s = input_ids.shape
    offset = cache.num_items() if cache is not None else 0
    positions = offset + np.arange(s, dtype=np.int32)[None, :]
    positions = np.broadcast_to(positions, (b, s))

    x = params["embed_tokens"][input_ids]
    if config.scale_embeddings:
        x = x * np.float32(math.sqrt(config.hidden_size))

    cos, sin = _rope(positions, config)  # [B, S, D]
    cos_h, sin_h = cos[:, :, None, :], sin[:, :, None, :]
    act = _ACT[config.hidden_act]
    nh, nk, d = config.num_attention_heads, config.num_key_value_heads, config.head_dim
    g = nh // nk

    def _proj(h, w, name):
        # Qwen-2-style checkpoints carry projection biases; dropping them
        # silently prints wrong text (ADVICE r1 / VERDICT r2 weak #6)
        y = h @ w[f"{name}_proj"]
        bias = w.get(f"{name}_bias")
        return y + bias if bias is not None else y

    for li in range(config.num_hidden_layers):
        w = _layer(params, li)
        h = _rms_norm(x, w["ln_attn_in"], config.rms_norm_eps, config.rms_norm_unit_offset)
        q = _proj(h, w, "q").reshape(b, s, nh, d)
        k = _proj(h, w, "k").reshape(b, s, nk, d)
        v = _proj(h, w, "v").reshape(b, s, nk, d)
        q = q * cos_h + _rotate_half(q) * sin_h
        k = k * cos_h + _rotate_half(k) * sin_h

        if cache is not None:
            k_all, v_all = cache.update(k, v, li)
        else:
            k_all, v_all = k, v
        skv = k_all.shape[1]
        kv_pos = np.arange(skv, dtype=np.int32)

        # [B, S, nk, g, d] x [B, skv, nk, d] -> [B, nk, g, S, skv]
        qg = q.reshape(b, s, nk, g, d)
        scores = np.einsum("bqkgd,bskd->bkgqs", qg, k_all) * config.attn_scale
        if config.attn_logit_softcapping is not None:
            scores = _softcap(scores, config.attn_logit_softcapping)
        mask = kv_pos[None, None, :] <= positions[:, :, None]  # [B, S, skv]
        if config.layer_is_sliding(li):
            mask = mask & (positions[:, :, None] - kv_pos[None, None, :] < config.sliding_window)
        scores = np.where(mask[:, None, None, :, :], scores, np.float32(-np.inf))
        probs = _softmax(scores)
        attn = np.einsum("bkgqs,bskd->bqkgd", probs, v_all).reshape(b, s, nh * d)
        attn = _proj(attn, w, "o")
        if config.sandwich_norms:
            attn = _rms_norm(attn, w["ln_attn_out"], config.rms_norm_eps, config.rms_norm_unit_offset)
        x = x + attn

        h = _rms_norm(x, w["ln_mlp_in"], config.rms_norm_eps, config.rms_norm_unit_offset)
        mlp = _proj(act(_proj(h, w, "gate")) * _proj(h, w, "up"), w, "down")
        if config.sandwich_norms:
            mlp = _rms_norm(mlp, w["ln_mlp_out"], config.rms_norm_eps, config.rms_norm_unit_offset)
        x = x + mlp

    x = _rms_norm(x, params["final_norm"], config.rms_norm_eps, config.rms_norm_unit_offset)
    if config.tie_word_embeddings:
        logits = x @ params["embed_tokens"].T
    else:
        logits = x @ params["lm_head"]
    if config.final_logit_softcapping is not None:
        logits = _softcap(logits, config.final_logit_softcapping)
    return logits.astype(np.float32), cache


def greedy_generate_np(
    params: dict[str, Any],
    prompt_ids: np.ndarray,
    config: ModelConfig,
    max_new_tokens: int,
    use_cache: bool = True,
) -> list[int]:
    """Greedy decode loop (oracle for token-level parity tests)."""
    cache = NpKVCache() if use_cache else None
    ids = list(np.asarray(prompt_ids).reshape(-1))
    cur = np.asarray(prompt_ids).reshape(1, -1)
    out: list[int] = []
    for _ in range(max_new_tokens):
        logits, cache = forward_np(params, cur, config, cache)
        nxt = int(np.argmax(logits[0, -1]))
        out.append(nxt)
        ids.append(nxt)
        if use_cache:
            cur = np.array([[nxt]], dtype=np.int32)
        else:
            cur = np.array([ids], dtype=np.int32)
    return out
