"""Array backends.

The reference ships interchangeable NumPy (CPU) and CuPy (single-GPU)
backends as twin files (SURVEY §1); this framework's primary backend is
JAX/XLA on TPU (``llm_np_cp_tpu.models``), and ``numpy_ref`` preserves the
NumPy path — both as the ``--backend=numpy`` runtime and as the golden
oracle for the test suite (SURVEY §4: "the NumPy file is the oracle").
"""

from llm_np_cp_tpu.backends.numpy_ref import forward_np, NpKVCache

__all__ = ["forward_np", "NpKVCache"]
