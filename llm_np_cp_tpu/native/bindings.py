"""ctypes bindings for the native safetensors reader.

Zero-copy design: the shard file is mmap'd once in C++; tensors are numpy
views over the mapping (no heap copy of the file), and the threaded
``st_copy2d`` moves/transposes/casts bytes straight into the caller's
preallocated stacked buffer.
"""

from __future__ import annotations

import ctypes
import json
import os
import threading
from typing import Any

import ml_dtypes
import numpy as np

_lib = None
_lib_lock = threading.Lock()


def _dtype_code(dt: np.dtype) -> int | None:
    dt = np.dtype(dt)
    if dt == np.float32:
        return 0
    if dt == ml_dtypes.bfloat16:
        return 1
    if dt == np.float16:
        return 2
    return None


_ST_DTYPES = {"F32": np.dtype(np.float32), "BF16": np.dtype(ml_dtypes.bfloat16),
              "F16": np.dtype(np.float16), "I32": np.dtype(np.int32),
              "I64": np.dtype(np.int64), "U8": np.dtype(np.uint8),
              "BOOL": np.dtype(bool)}


def _load_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from llm_np_cp_tpu.native.build import build

        path = build()
        if path is None:
            _lib = False
            return _lib
        lib = ctypes.CDLL(str(path))
        lib.st_open.restype = ctypes.c_void_p
        lib.st_open.argtypes = [ctypes.c_char_p]
        lib.st_header.restype = ctypes.c_void_p
        lib.st_header.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
        lib.st_data.restype = ctypes.c_void_p
        lib.st_data.argtypes = [ctypes.c_void_p]
        lib.st_data_size.restype = ctypes.c_uint64
        lib.st_data_size.argtypes = [ctypes.c_void_p]
        lib.st_close.argtypes = [ctypes.c_void_p]
        lib.st_copy2d.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
        ]
        _lib = lib
        return _lib


def is_available() -> bool:
    return bool(_load_lib())


class NativeSafetensorsFile:
    """mmap-backed safetensors shard: ``keys()``, ``get_tensor(name)``
    (zero-copy view), ``copy_into(name, dest, transpose)`` (threaded)."""

    def __init__(self, path: str | os.PathLike) -> None:
        lib = _load_lib()
        if not lib:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.st_open(str(path).encode())
        if not self._h:
            raise OSError(f"cannot open safetensors file: {path}")
        hlen = ctypes.c_uint64()
        hptr = lib.st_header(self._h, ctypes.byref(hlen))
        header = ctypes.string_at(hptr, hlen.value).decode("utf-8")
        meta = json.loads(header)
        meta.pop("__metadata__", None)
        self._meta = meta
        nbytes = lib.st_data_size(self._h)
        self._data = np.ctypeslib.as_array(
            ctypes.cast(lib.st_data(self._h), ctypes.POINTER(ctypes.c_uint8)),
            shape=(nbytes,),
        )

    def keys(self) -> list[str]:
        return list(self._meta)

    def _entry(self, name: str) -> tuple[np.dtype, tuple[int, ...], int, int]:
        e = self._meta[name]
        dt = _ST_DTYPES[e["dtype"]]
        begin, end = e["data_offsets"]
        return dt, tuple(e["shape"]), begin, end

    def get_tensor(self, name: str) -> np.ndarray:
        dt, shape, begin, end = self._entry(name)
        return self._data[begin:end].view(dt).reshape(shape)

    def copy_into(
        self, name: str, dest: np.ndarray, *, transpose: bool = False,
        nthreads: int | None = None,
    ) -> None:
        """Threaded copy/transpose/cast of a (≤2-D) tensor into ``dest``."""
        dt, shape, begin, end = self._entry(name)
        src_code = _dtype_code(dt)
        dst_code = _dtype_code(dest.dtype)
        if src_code is None or dst_code is None or len(shape) > 2:
            src = self.get_tensor(name)
            dest[...] = (src.T if transpose else src).astype(dest.dtype)
            return
        rows, cols = (shape if len(shape) == 2 else (1, shape[0] if shape else 1))
        want = (cols, rows) if transpose and len(shape) == 2 else tuple(shape)
        if tuple(dest.shape) != want:
            raise ValueError(f"{name}: dest shape {dest.shape} != expected {want}")
        if not dest.flags.c_contiguous:
            raise ValueError(f"{name}: dest must be C-contiguous")
        nthreads = nthreads or min(16, os.cpu_count() or 1)
        self._lib.st_copy2d(
            self._data[begin:end].ctypes.data_as(ctypes.c_void_p), src_code,
            dest.ctypes.data_as(ctypes.c_void_p), dst_code,
            rows, cols, int(transpose and len(shape) == 2), nthreads,
        )

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._data = None
            self._lib.st_close(self._h)
            self._h = None

    def __enter__(self) -> "NativeSafetensorsFile":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass


def copy2d(
    src: np.ndarray, dest: np.ndarray, *, transpose: bool = False,
    nthreads: int | None = None,
) -> bool:
    """Threaded 2-D copy/transpose/cast between host arrays.  Returns False
    (no-op) when the native library or dtype pair is unsupported."""
    lib = _load_lib()
    sc, dc = _dtype_code(src.dtype), _dtype_code(dest.dtype)
    if not lib or sc is None or dc is None or src.ndim != 2:
        return False
    if not (src.flags.c_contiguous and dest.flags.c_contiguous):
        return False
    rows, cols = src.shape
    want = (cols, rows) if transpose else (rows, cols)
    if tuple(dest.shape) != want:
        raise ValueError(f"dest shape {dest.shape} != expected {want}")
    nthreads = nthreads or min(16, os.cpu_count() or 1)
    lib.st_copy2d(
        src.ctypes.data_as(ctypes.c_void_p), sc,
        dest.ctypes.data_as(ctypes.c_void_p), dc,
        rows, cols, int(transpose), nthreads,
    )
    return True
