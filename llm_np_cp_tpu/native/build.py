"""Build the native library with g++ (no pip/pybind11 — plain C ABI .so)."""

from __future__ import annotations

import subprocess
from pathlib import Path

SRC = Path(__file__).parent / "safetensors_reader.cc"
LIB = Path(__file__).parent / "libllmtpu_native.so"


def build(force: bool = False) -> Path | None:
    """Compile the .so if missing/stale.  Returns the path, or None if the
    toolchain is unavailable (callers fall back to pure Python)."""
    if LIB.exists() and not force and LIB.stat().st_mtime >= SRC.stat().st_mtime:
        return LIB
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        "-o", str(LIB), str(SRC), "-lpthread",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return None
    return LIB


if __name__ == "__main__":
    path = build(force=True)
    print(f"built: {path}" if path else "build failed (g++ unavailable?)")
