// Native checkpoint IO: mmap'd safetensors access + threaded tensor
// transform (transpose / dtype cast) into preallocated destination buffers.
//
// Role: the data-loading hot path of utils/loading.py.  The reference's
// loader funnels every tensor through torch on one thread
// (llama3.2_model.py:1060-1062, :1079); here the Python layer orchestrates
// and this library does the byte work: the checkpoint shard is mapped
// read-only (no heap copy of the file), and each tensor is copied /
// transposed / cast into its slot of the stacked host buffer by a pool of
// std::threads.  bf16<->f32 conversions use round-to-nearest-even.
//
// C ABI only (consumed via ctypes — no pybind11 in this environment).

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

extern "C" {

struct StFile {
  int fd = -1;
  uint8_t* base = nullptr;  // whole-file mapping
  size_t size = 0;
  uint64_t header_len = 0;  // JSON header byte length
};

// Open + mmap a .safetensors file.  Returns nullptr on failure.
StFile* st_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 8) {
    ::close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (base == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  madvise(base, st.st_size, MADV_SEQUENTIAL);
  auto* f = new StFile;
  f->fd = fd;
  f->base = static_cast<uint8_t*>(base);
  f->size = st.st_size;
  std::memcpy(&f->header_len, f->base, 8);  // little-endian u64 prefix
  if (8 + f->header_len > f->size) {  // corrupt header length
    munmap(base, st.st_size);
    ::close(fd);
    delete f;
    return nullptr;
  }
  return f;
}

const char* st_header(StFile* f, uint64_t* len) {
  *len = f->header_len;
  return reinterpret_cast<const char*>(f->base + 8);
}

// Pointer to the start of the tensor-data region (offsets in the JSON
// header are relative to this).
const uint8_t* st_data(StFile* f) { return f->base + 8 + f->header_len; }

uint64_t st_data_size(StFile* f) { return f->size - 8 - f->header_len; }

void st_close(StFile* f) {
  if (!f) return;
  munmap(f->base, f->size);
  ::close(f->fd);
  delete f;
}

// ---------------------------------------------------------------------
// dtype codes: 0 = f32, 1 = bf16, 2 = f16
// ---------------------------------------------------------------------

static inline float load_elem(const uint8_t* p, int dtype) {
  switch (dtype) {
    case 0: {
      float v;
      std::memcpy(&v, p, 4);
      return v;
    }
    case 1: {
      uint16_t h;
      std::memcpy(&h, p, 2);
      uint32_t bits = static_cast<uint32_t>(h) << 16;
      float v;
      std::memcpy(&v, &bits, 4);
      return v;
    }
    default: {  // f16
      uint16_t h;
      std::memcpy(&h, p, 2);
      uint32_t sign = (h >> 15) & 1, exp = (h >> 10) & 0x1f, man = h & 0x3ff;
      uint32_t bits;
      if (exp == 0) {
        if (man == 0) {
          bits = sign << 31;
        } else {  // subnormal
          int e = -1;
          while (!(man & 0x400)) {
            man <<= 1;
            e++;
          }
          man &= 0x3ff;
          bits = (sign << 31) | ((127 - 15 - e) << 23) | (man << 13);
        }
      } else if (exp == 0x1f) {
        bits = (sign << 31) | 0x7f800000 | (man << 13);
      } else {
        bits = (sign << 31) | ((exp - 15 + 127) << 23) | (man << 13);
      }
      float v;
      std::memcpy(&v, &bits, 4);
      return v;
    }
  }
}

static inline void store_elem(uint8_t* p, int dtype, float v) {
  switch (dtype) {
    case 0:
      std::memcpy(p, &v, 4);
      return;
    case 1: {  // f32 -> bf16, round to nearest even
      uint32_t bits;
      std::memcpy(&bits, &v, 4);
      uint32_t rounded = bits + 0x7fff + ((bits >> 16) & 1);
      uint16_t h = static_cast<uint16_t>(rounded >> 16);
      if ((bits & 0x7f800000) == 0x7f800000 && (bits & 0x007fffff))
        h = static_cast<uint16_t>((bits >> 16) | 0x0040);  // quiet NaN
      std::memcpy(p, &h, 2);
      return;
    }
    default: {  // f32 -> f16 (round to nearest even, with clamping)
      uint32_t bits;
      std::memcpy(&bits, &v, 4);
      uint32_t sign = (bits >> 31) & 1;
      int32_t exp = static_cast<int32_t>((bits >> 23) & 0xff) - 127 + 15;
      uint32_t man = bits & 0x7fffff;
      uint16_t h;
      if (exp >= 0x1f) {
        h = static_cast<uint16_t>((sign << 15) | 0x7c00 |
                                  ((bits & 0x7f800000) == 0x7f800000 && man ? 0x200 : 0));
      } else if (exp <= 0) {
        h = static_cast<uint16_t>(sign << 15);  // flush tiny to zero
      } else {
        uint32_t m10 = man >> 13;
        uint32_t rem = man & 0x1fff;
        if (rem > 0x1000 || (rem == 0x1000 && (m10 & 1))) m10++;
        h = static_cast<uint16_t>((sign << 15) | (exp << 10) | m10);
        if (m10 == 0x400) h = static_cast<uint16_t>((sign << 15) | ((exp + 1) << 10));
      }
      std::memcpy(p, &h, 2);
      return;
    }
  }
}

static inline size_t dsize(int dtype) { return dtype == 0 ? 4 : 2; }

// Copy a [rows, cols] tensor from src to dst, optionally transposing to
// [cols, rows], with dtype conversion, across nthreads.
void st_copy2d(const uint8_t* src, int src_dtype, uint8_t* dst, int dst_dtype,
               uint64_t rows, uint64_t cols, int transpose, int nthreads) {
  const size_t ss = dsize(src_dtype), ds = dsize(dst_dtype);
  if (nthreads < 1) nthreads = 1;
  const bool memcpy_ok = (src_dtype == dst_dtype) && !transpose;

  auto worker = [&](uint64_t r0, uint64_t r1) {
    if (memcpy_ok) {
      std::memcpy(dst + r0 * cols * ds, src + r0 * cols * ss,
                  (r1 - r0) * cols * ss);
      return;
    }
    for (uint64_t r = r0; r < r1; ++r) {
      const uint8_t* sp = src + r * cols * ss;
      if (!transpose) {
        uint8_t* dp = dst + r * cols * ds;
        for (uint64_t c = 0; c < cols; ++c)
          store_elem(dp + c * ds, dst_dtype, load_elem(sp + c * ss, src_dtype));
      } else {
        for (uint64_t c = 0; c < cols; ++c)
          store_elem(dst + (c * rows + r) * ds, dst_dtype,
                     load_elem(sp + c * ss, src_dtype));
      }
    }
  };

  if (nthreads == 1 || rows < 64) {
    worker(0, rows);
    return;
  }
  std::vector<std::thread> pool;
  uint64_t chunk = (rows + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    uint64_t r0 = t * chunk, r1 = std::min(rows, r0 + chunk);
    if (r0 >= r1) break;
    pool.emplace_back(worker, r0, r1);
  }
  for (auto& th : pool) th.join();
}

}  // extern "C"
