"""Native (C++) runtime components.

The reference's only native code is an inline CUDA softmax; its *runtime*
(loading, IO) is single-threaded Python/torch.  This package holds the
framework's C++ pieces, consumed through ctypes (no pybind11 in this
environment) with transparent pure-Python fallbacks:

- ``safetensors_reader.cc`` — mmap'd safetensors access + multithreaded
  tensor transpose/cast into preallocated host buffers (the checkpoint
  load hot path).

Build: ``python -m llm_np_cp_tpu.native.build`` (or lazily on first use).
"""

from llm_np_cp_tpu.native.bindings import (
    NativeSafetensorsFile,
    copy2d,
    is_available,
)

__all__ = ["NativeSafetensorsFile", "copy2d", "is_available"]
