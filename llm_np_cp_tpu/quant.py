"""Weight-only int8 quantization for decode.

Single-chip decode is HBM-bandwidth-bound: every step streams the full
weight set (bf16 Llama-3.2-1B = 2.47 GB ÷ ~819 GB/s ≈ 331 steps/s
ceiling — measured ~80% of that).  The reference has no quantization at
all; on TPU the natural lever is storing weights as int8 with
per-output-channel float scales and dequantizing *inside* the fused
matmul read: XLA folds the ``int8 → bf16`` convert and the scale multiply
into the GEMM's operand pipeline, so HBM traffic halves while the MXU
still runs bf16×bf16.

Representation: a quantized matrix is a dict in the original array's
pytree position — ``{"q": int8, "s": f32}`` (8-bit), ``{"q4": uint8
two-nibbles-per-byte packed along the contraction axis, "s": f32}``
(4-bit; see quantize_array4), or ``{"qa": int8, "s": f32}`` (8-bit
weights consumed with DYNAMIC per-token int8 activation quantization:
the einsum runs int8×int8 on the MXU's native int8 path, skipping the
per-element int8→bf16 weight convert of the ``q`` mode — W8A8) — with
``s`` broadcast along the *input* axis (consumers: ``payload()`` /
``payload_key()`` below, quant_einsum, sharding.shard_params):

- projections ``[in, out]`` → per-out-channel scale ``[out]``
- stacked layers ``[L, in, out]`` → ``[L, 1, out]``
- embedding ``[V, H]`` → per-row scale ``[V, 1]`` (the row is the output
  channel of the tied lm_head and the gather unit of the embed lookup)

Norm gammas, MoE routers, and anything 1-D stay in the float dtype —
they are noise in the byte budget and precision-critical.

Symmetric quantization: ``q = round(w / s)``, ``s = max|w| / 127`` per
channel.  No activation quantization (activations never touch HBM
between fused ops).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

# weights quantized along their contraction-input axis (per-output scales)
_QUANT_KEYS = {
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj", "lm_head",
}


_PAYLOAD_KEYS = ("q", "qa", "q4", "q4a")


def is_quantized(w: Any) -> bool:
    return (
        isinstance(w, dict)
        and any(k in w for k in _PAYLOAD_KEYS)
        and "s" in w
    )


def payload_key(w: dict) -> str:
    for k in _PAYLOAD_KEYS:
        if k in w:
            return k
    raise KeyError(f"not a quantized leaf: {list(w)}")


def payload(w: dict) -> jnp.ndarray:
    """The quantized leaf's full-width integer payload (int4 unpacked)."""
    key = payload_key(w)
    if key in ("q4", "q4a"):
        return _unpack4(w[key])
    return w[key]


def quantize_array(w: jnp.ndarray, *, axis: int) -> dict[str, jnp.ndarray]:
    """Symmetric int8 quantization of ``w`` along ``axis`` (the contraction
    axis): scales have size 1 there and the full size elsewhere is kept
    broadcastable."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=axis, keepdims=True)
    s = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s.astype(jnp.float32)}


def quantize_array4(w: jnp.ndarray, *, axis: int = -2) -> dict[str, jnp.ndarray]:
    """Symmetric int4: q ∈ [-7, 7], stored offset-binary (q+8) two values
    per uint8, packed along the CONTRACTION axis (must be ``-2`` and even
    — every projection's in-dim is).  Payload is in-dim/2 × 1 byte: a
    quarter of bf16, half of int8."""
    if axis != -2:
        raise NotImplementedError("int4 packing is along axis -2 only")
    if w.shape[-2] % 2:
        raise ValueError(f"contraction dim {w.shape[-2]} must be even for int4")
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=axis, keepdims=True)
    s = jnp.where(amax > 0, amax / 7.0, 1.0)
    q = (jnp.clip(jnp.round(w32 / s), -7, 7) + 8).astype(jnp.uint8)
    qr = q.reshape(*q.shape[:-2], q.shape[-2] // 2, 2, q.shape[-1])
    packed = qr[..., 0, :] | (qr[..., 1, :] << 4)
    return {"q4": packed, "s": s.astype(jnp.float32)}


def _unpack4_pairs(p: jnp.ndarray) -> jnp.ndarray:
    """uint8 [..., in/2, out] → int8 [..., in/2, 2, out] (n=0 low nibble).

    A single broadcast-shift-mask over the packed bytes — no stack, no
    concat, no axis merge — so the unpack stays a pure elementwise
    producer that XLA can fuse into the consuming GEMM's operand read
    (the r4 bench showed the earlier stack+reshape variant materializing
    the full unpacked tensor every decode step: int4 ran 4x SLOWER than
    bf16 at 5% roofline)."""
    shifts = jnp.asarray([0, 4], jnp.uint8).reshape(2, 1)
    q = (p[..., None, :] >> shifts) & jnp.uint8(0xF)
    return q.astype(jnp.int8) - 8


def _unpack4(p: jnp.ndarray) -> jnp.ndarray:
    """uint8 [..., in/2, out] → int8 [..., in, out] (row 2i = low nibble)."""
    u = _unpack4_pairs(p)  # [..., in/2, 2, out]
    return u.reshape(*p.shape[:-2], p.shape[-2] * 2, p.shape[-1])


def dequantize(w: Any, dtype: jnp.dtype = jnp.float32) -> jnp.ndarray:
    if not is_quantized(w):
        return w
    return (payload(w).astype(jnp.float32) * w["s"]).astype(dtype)


def quantize_params(
    params: Params, *, embed: bool = True, bits: int = 8,
    act_quant: bool = False,
) -> Params:
    """Quantize every projection matrix (and optionally the embedding /
    tied lm_head table) of a transformer param pytree in place-shape.

    ``bits=4`` packs the projections two-per-byte (quarter of bf16); the
    embedding/lm_head stay int8 — per-row int4 on the gather table costs
    visible quality for a small byte win, and the lm_head matmul is once
    per step, not per layer.

    ``act_quant=True`` marks the per-layer projections for dynamic
    activation quantization (payload key ``qa`` at bits=8, ``q4a`` at
    bits=4): quant_einsum quantizes each token's activations to int8 on
    the fly (per-row absmax) and contracts all-integer with int32
    accumulation — the MXU's native int8 path, no weight convert in the
    operand stream.  The embed / lm_head table keeps the weight-only
    ``q`` mode (it serves the gather too, and logits set output
    quality).  Quality cost is measured by utils/quality.py's
    ``int8_a8`` / ``int4_a8`` modes — activation outliers make these
    lossier than their weight-only twins; both are opt-in.

    The result drops into ``models.transformer.forward`` unchanged —
    ``_project`` / ``embed_inputs`` / ``final_logits`` detect the dict
    leaves — and into ``parallel.sharding.shard_params``, which shards the
    payload like the original weight and the scales alongside it.
    """
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    qproj = quantize_array4 if bits == 4 else quantize_array
    out = dict(params)
    layers = dict(params["layers"])
    for key in list(layers.keys()):
        if key in _QUANT_KEYS:
            # stacked [L, in, out] (dense) or [L, E, in, out] (MoE experts):
            # contraction axis is always -2
            w = qproj(layers[key], axis=-2)
            if act_quant:  # W8A8 "qa" / W4A8 "q4a": int-MXU consumption
                pk = "q" if "q" in w else "q4"
                w = {pk + "a": w.pop(pk), **w}
            layers[key] = w
    out["layers"] = layers
    if embed:
        # [V, H]: per-row scales serve both the embed gather and the tied
        # lm_head (row = vocab output channel)
        out["embed_tokens"] = quantize_array(params["embed_tokens"], axis=-1)
    if "lm_head" in params:
        # int8 even at bits=4: the lm_head matmul runs once per step (not
        # per layer) and sets output-logit quality
        out["lm_head"] = quantize_array(params["lm_head"], axis=-2)
    return out


def _align_scale(spec: str, s: jnp.ndarray) -> jnp.ndarray:
    """Reshape a keepdims scale tensor (same rank as the einsum's second
    operand, size 1 on contracted axes) so it broadcasts against the
    einsum's OUTPUT — the single place that knows the scale layout."""
    ins, out = spec.replace(" ", "").split("->")
    _, w_idx = ins.split(",")
    drop = tuple(i for i, c in enumerate(w_idx) if c not in out)
    s2 = jnp.squeeze(s, axis=drop)
    kept = [c for c in w_idx if c in out]
    s2 = jnp.transpose(s2, sorted(range(len(kept)), key=lambda i: out.index(kept[i])))
    kept_sorted = sorted(kept, key=out.index)
    return s2.reshape([
        s2.shape[kept_sorted.index(c)] if c in kept_sorted else 1 for c in out
    ])


def _align_x_scale(spec: str, sx: jnp.ndarray) -> jnp.ndarray:
    """Reshape a keepdims ACTIVATION scale (same rank as the einsum's
    first operand, size 1 on contracted axes) to broadcast against the
    einsum's output — the x-side twin of _align_scale."""
    ins, out = spec.replace(" ", "").split("->")
    x_idx, _ = ins.split(",")
    drop = tuple(i for i, c in enumerate(x_idx) if c not in out)
    s2 = jnp.squeeze(sx, axis=drop)
    kept = [c for c in x_idx if c in out]
    s2 = jnp.transpose(s2, sorted(range(len(kept)), key=lambda i: out.index(kept[i])))
    kept_sorted = sorted(kept, key=out.index)
    return s2.reshape([
        s2.shape[kept_sorted.index(c)] if c in kept_sorted else 1 for c in out
    ])


def quant_einsum(spec: str, x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """``einsum(spec, x, w)`` in f32 accumulation, accepting a plain array
    or a quantized ``{"q"|"qa"|"q4", "s"}`` dict for ``w``.  ``q``/``q4``
    matmul the (unpacked) payload in x.dtype and rescale the output;
    ``qa`` additionally quantizes the activations on the fly (dynamic
    per-row absmax) and contracts int8×int8 with int32 accumulation —
    the W8A8 path.  All weight-consuming einsums in the model go through
    this."""
    if not is_quantized(w):
        return jnp.einsum(spec, x, w, preferred_element_type=jnp.float32)
    if "qa" in w or "q4a" in w:
        # dynamic activation quant (per-row absmax over the contracted
        # axes), then an all-integer contraction on the MXU's int8 path
        ins, out = spec.replace(" ", "").split("->")
        x_idx, _ = ins.split(",")
        contracted = tuple(i for i, c in enumerate(x_idx) if c not in out)
        amax = jnp.max(
            jnp.abs(x.astype(jnp.float32)), axis=contracted, keepdims=True
        )
        sx = jnp.where(amax > 0, amax / 127.0, 1.0)
        xq = jnp.clip(jnp.round(x.astype(jnp.float32) / sx), -127, 127).astype(
            jnp.int8
        )
        if "qa" in w:
            y = jnp.einsum(spec, xq, w["qa"], preferred_element_type=jnp.int32)
        else:
            y = _einsum4(spec, xq, w["q4a"], int_accum=True)
        return (
            y.astype(jnp.float32)
            * _align_x_scale(spec, sx)
            * _align_scale(spec, w["s"])
        )
    if "q4" in w:
        y = _einsum4(spec, x, w["q4"])
    else:
        y = jnp.einsum(
            spec, x, w["q"].astype(x.dtype), preferred_element_type=jnp.float32
        )
    return y * _align_scale(spec, w["s"])


def _einsum4(
    spec: str, x: jnp.ndarray, q4: jnp.ndarray, *, int_accum: bool = False
) -> jnp.ndarray:
    """int4 einsum that contracts over (packed-pair, nibble) axes
    directly: x's contraction axis splits [in] → [in/2, 2] (a free
    adjacent-dim reshape on the ACTIVATION, which is tiny at decode) and
    the weight unpacks as [..., in/2, 2, out] via _unpack4_pairs — no
    axis-merge reshape on the weight side, keeping the whole decode
    elementwise-fusable into the GEMM operand read.

    ``int_accum=True`` (W4A8: x already int8) keeps the unpacked nibbles
    int8 and accumulates in int32 — all-integer MXU contraction."""
    acc = jnp.int32 if int_accum else jnp.float32
    ins, out = spec.replace(" ", "").split("->")
    x_idx, w_idx = ins.split(",")
    c = w_idx[-2]  # quantize_array4 packs along axis -2 only
    if x_idx[-1] != c:
        # not a last-axis contraction (no in-repo spec hits this): fall
        # back to the explicit unpack
        return jnp.einsum(
            spec, x, _unpack4(q4).astype(x.dtype),
            preferred_element_type=acc,
        )
    n = next(ch for ch in "nmzyxwutsr" if ch not in spec)
    xr = x.reshape(*x.shape[:-1], x.shape[-1] // 2, 2)
    u = _unpack4_pairs(q4).astype(x.dtype)
    pair_spec = f"{x_idx[:-1]}{c}{n},{w_idx[:-1]}{n}{w_idx[-1]}->{out}"
    return jnp.einsum(pair_spec, xr, u, preferred_element_type=acc)


def param_bytes(params: Params) -> int:
    """Total HBM bytes of a (possibly quantized) param pytree."""
    return sum(
        leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(params)
    )
