"""Quantization quality measurement: greedy divergence + logit MAE.

The framework's quantization modes (int8/int4 weight-only, int8 KV
cache) have no counterpart in the reference — these are our own claims,
so they carry their own evidence (VERDICT r3 weak #4): for each mode,
how many greedy steps match the float baseline token-for-token, and the
mean absolute logit delta under teacher forcing on the baseline's own
continuation.  Emitted with every quantized bench row and pinned by
regression floors in tests/test_quant_quality.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from llm_np_cp_tpu.config import ModelConfig
from llm_np_cp_tpu.generate import Generator
from llm_np_cp_tpu.models.transformer import forward
from llm_np_cp_tpu.ops.sampling import Sampler
from llm_np_cp_tpu.quant import quantize_params

MODES = ("int8", "int8_a8", "int4", "int4_a8", "kv_int8")


def quant_quality(
    config: ModelConfig,
    params,
    mode: str,
    *,
    steps: int = 256,
    prompt_len: int = 16,
    seed: int = 0,
    base_dtype: jnp.dtype = jnp.float32,
) -> dict:
    """Compare one quantization mode against the float baseline.

    Returns ``divergence_step`` (index of the first greedy token that
    differs; == ``steps`` when the whole continuation matches) and
    ``logit_mae``/``logit_max_abs_err`` (teacher-forced on the BASELINE
    continuation, so both models score the same prefix — a fair per-step
    comparison that doesn't compound the token drift).
    """
    if mode not in MODES:
        raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
    sampler = Sampler(kind="greedy")
    base = Generator(params, config, sampler=sampler, cache_dtype=base_dtype)
    if mode == "kv_int8":
        qparams, cache_dtype = params, jnp.int8
    else:
        qparams = quantize_params(
            params, bits=4 if mode.startswith("int4") else 8,
            act_quant=mode.endswith("_a8"),
        )
        cache_dtype = base_dtype
    quant = Generator(qparams, config, sampler=sampler, cache_dtype=cache_dtype)

    rng = np.random.default_rng(seed)
    prompt = jnp.asarray(
        rng.integers(1, config.vocab_size, (1, prompt_len)), jnp.int32
    )
    toks_b = np.asarray(base.generate(prompt, steps, seed=seed).tokens)[0]
    toks_q = np.asarray(quant.generate(prompt, steps, seed=seed).tokens)[0]
    mismatch = np.nonzero(toks_b != toks_q)[0]
    div_step = int(mismatch[0]) if mismatch.size else steps

    seq = jnp.concatenate([prompt, jnp.asarray(toks_b[None, :], jnp.int32)], axis=1)
    if mode == "kv_int8":
        # the KV cache only exists in cached decode; measure its logit
        # error on the incremental path instead: score the baseline
        # continuation step-by-step through each generator's cache.
        delta = _cached_logit_delta(base, quant, seq, steps)
    else:
        # Teacher-forced logits over prompt + baseline continuation
        # (cache-less forward: one wide pass, identical masks for both).
        logits_b, _ = forward(params, seq, config, cache=None)
        logits_q, _ = forward(qparams, seq, config, cache=None)
        delta = np.abs(
            np.asarray(logits_b, np.float32) - np.asarray(logits_q, np.float32)
        )
    return {
        "mode": mode,
        "steps": steps,
        "divergence_step": div_step,
        "diverged": bool(mismatch.size),
        "logit_mae": round(float(delta.mean()), 6),
        "logit_max_abs_err": round(float(delta.max()), 4),
    }


def _cached_logit_delta(base: Generator, quant: Generator, seq, steps: int):
    """|Δlogits| between two generators' cached forward over ``seq``.

    Runs each generator's own prefill over the full sequence (logits at
    the last position come from a cache filled by that generator's cache
    dtype), sliding a window so every step's logits are produced through
    the cache path the mode actually changes.
    """
    deltas = []
    # score at a handful of depths — O(steps) full prefills would be slow
    b, s = seq.shape
    for end in np.linspace(max(2, s - steps), s, num=8, dtype=int):
        lb = _prefill_logits(base, seq[:, :end])
        lq = _prefill_logits(quant, seq[:, :end])
        deltas.append(np.abs(lb - lq))
    return np.concatenate(deltas, axis=None)


def _prefill_logits(gen: Generator, ids) -> np.ndarray:
    cache = gen._init_cache(ids.shape[0], ids.shape[1])
    _, _, logits = gen._prefill(
        gen.params, ids, cache, jax.random.PRNGKey(0), None, None
    )
    return np.asarray(logits, np.float32)
