"""Checkpoint save / resume (SURVEY §5 checkpoint row).

The reference is load-only: it reads HF safetensors but can never write
state (no training, no optimizer — SURVEY §5: "No saving, no training").
The framework adds the missing half via Orbax: save/restore of the param
pytree plus optimizer state and step counter, sharding-aware (restores
directly onto a mesh when target shardings are provided), so multi-chip
training runs can stop and resume.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp


def save_checkpoint(path: str | Path, state: dict[str, Any]) -> None:
    """Write ``state`` (arbitrary pytree: params / opt_state / step)."""
    path = Path(path).absolute()
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state, force=True)
    ckptr.wait_until_finished()


def restore_checkpoint(
    path: str | Path, like: dict[str, Any] | None = None
) -> dict[str, Any]:
    """Restore a pytree.  ``like``: abstract target (e.g. the current state
    pytree, or ``jax.tree.map(ocp.utils.to_shape_dtype_struct, state)``)
    carrying dtype/sharding so arrays restore directly onto the mesh."""
    path = Path(path).absolute()
    ckptr = ocp.StandardCheckpointer()
    if like is not None:
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape")
            else x,
            like,
        )
        return ckptr.restore(path, abstract)
    return ckptr.restore(path)
