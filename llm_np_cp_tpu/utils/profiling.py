"""Profiling & tracing (SURVEY §5 tracing row).

The reference's entire profiling subsystem is a wall-clock ``timing``
decorator whose every application is commented out (llama3.2_model.py:12-26,
``#timing`` at :87, :179, :314).  Here the same decorator exists but is
*switchable* (env ``LLMTPU_TIMING=1`` or ``enable_timing()``), understands
async dispatch (blocks on results before stopping the clock — naive
wall-clock around a JAX call measures dispatch, not compute), and the real
tool is ``trace()``: a ``jax.profiler`` context that dumps a TensorBoard/
Perfetto trace of the XLA timeline.
"""

from __future__ import annotations

import contextlib
import functools
import os
import time
from typing import Any, Callable, Iterator

import jax

_TIMING_ENABLED = os.environ.get("LLMTPU_TIMING", "") not in ("", "0")


def enable_timing(on: bool = True) -> None:
    global _TIMING_ENABLED
    _TIMING_ENABLED = on


def timing(fn: Callable) -> Callable:
    """Per-call wall-clock printer (the reference's decorator, made real)."""

    @functools.wraps(fn)
    def wrapper(*args: Any, **kwargs: Any):
        if not _TIMING_ENABLED:
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        try:
            jax.block_until_ready(out)
        except Exception:
            pass  # non-array outputs
        dt = time.perf_counter() - t0
        print(f"[timing] {fn.__qualname__}: {dt * 1e3:.2f} ms")
        return out

    return wrapper


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/llmtpu_trace") -> Iterator[None]:
    """XLA timeline trace → TensorBoard/Perfetto (view with
    ``tensorboard --logdir`` or ui.perfetto.dev)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Stopwatch:
    """Tiny helper for step metrics: TTFT, per-phase durations, rates."""

    def __init__(self) -> None:
        self.marks: dict[str, float] = {}
        self._t0 = time.perf_counter()

    def mark(self, name: str, result: Any = None) -> float:
        if result is not None:
            jax.block_until_ready(result)
        t = time.perf_counter() - self._t0
        self.marks[name] = t
        return t

    def span(self, a: str, b: str) -> float:
        return self.marks[b] - self.marks[a]
