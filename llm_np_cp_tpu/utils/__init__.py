"""Runtime utilities: checkpoint loading, profiling, misc."""
