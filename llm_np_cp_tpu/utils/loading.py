"""HF checkpoint loading: sharded safetensors → stacked param pytree.

Reference behavior being replaced (SURVEY §2.1, §3.1):
- ``load_sharded_safetensors_via_weight_map`` (llama3.2_model.py:1033-1073)
  parses ``model.safetensors.index.json``, loads every shard into one big
  host dict of torch tensors, with a bare try/except falling back to
  single-file ``model.safetensors``;
- ``load_weights(key)`` then copies each tensor host→device one at a time
  inside every module constructor, with weight tying done by rewriting the
  key ``lm_head.weight`` → ``model.embed_tokens.weight`` (:1077-1078);
- dtype policy is inconsistent: Llama casts to fp32, Gemma keeps checkpoint
  dtype (gemma2_model.py:1137-1138).

TPU-native design:
- torch-free: safetensors' numpy framework reads bf16 via ml_dtypes;
- streaming: tensors are copied shard-by-shard directly into preallocated
  stacked host buffers ``[num_layers, ...]`` (the layout ``lax.scan``
  consumes), so peak host memory is one shard + the param set — not the
  reference's full-dict-then-model double residency (important for 9B);
- projections are transposed once to (in, out) at load;
- explicit dtype policy (bf16 default, fp32 for parity runs);
- optional ``shardings`` pytree: each stacked buffer is ``jax.device_put``
  onto its mesh sharding as soon as it completes, so a TP-sharded load
  never materializes the full model on one chip.

Weight tying: with ``tie_word_embeddings`` the checkpoint has no
``lm_head.weight`` and the forward pass reuses ``embed_tokens`` directly —
same semantics as the reference's key rewrite, zero extra memory.
"""

from __future__ import annotations

import json
import re
import time
from pathlib import Path
from typing import Any, Callable

import jax
import ml_dtypes
import numpy as np
from safetensors import safe_open

from llm_np_cp_tpu.config import ModelConfig
from llm_np_cp_tpu.models import gemma2, llama, qwen2
from llm_np_cp_tpu.models.transformer import param_shapes

_LAYER_RE = re.compile(r"^model\.layers\.(\d+)\.(.+)$")

# Transient shard-read IO (NFS blips, object-store mounts dropping a
# connection) gets a bounded retry instead of killing a multi-minute
# load; backoff doubles per attempt.  Module-level so tests can shrink
# the backoff.
SHARD_READ_RETRIES = 2
SHARD_READ_BACKOFF_S = 0.5

# These OSError subclasses are configuration mistakes, not flaky IO —
# retrying a missing file three times only delays and mislabels the
# diagnosis.
_PERMANENT_OS_ERRORS = (
    FileNotFoundError, PermissionError, IsADirectoryError,
    NotADirectoryError,
)

# Fault-injection seam: when set, called with the shard path before each
# read attempt and may raise OSError to simulate transient IO.  Wired by
# llm_np_cp_tpu.serve.faults.install() — the hook lives HERE so
# checkpoint loading never imports the serving stack (utils stays below
# serve in the layering).
SHARD_READ_HOOK: Callable[[Path], None] | None = None


def _read_shard(
    path: Path, use_native: bool, consume: Callable[[Any, bool], None],
) -> None:
    """Open one shard and run ``consume(f, native)`` over it, with a
    bounded retry on transient ``OSError`` and shard-named, actionable
    errors otherwise — a failed 9B load must say WHICH shard and tensor
    disagreed, not dump a raw safetensors traceback.

    Retrying the whole shard is safe: ``consume`` only copies tensors
    into preallocated buffers (idempotent) and ``filled`` is a set.
    """
    for attempt in range(SHARD_READ_RETRIES + 1):
        try:
            if SHARD_READ_HOOK is not None:
                SHARD_READ_HOOK(path)
            f, native = _open_shard(path, use_native)
            with f:
                consume(f, native)
            return
        except _PERMANENT_OS_ERRORS:
            raise  # the OS message already names the path
        except OSError as e:
            if attempt >= SHARD_READ_RETRIES:
                raise OSError(
                    f"{path.name}: shard read failed after "
                    f"{SHARD_READ_RETRIES + 1} attempts: {e}"
                ) from e
            time.sleep(SHARD_READ_BACKOFF_S * (2 ** attempt))
        except ValueError as e:
            # size/key mismatch — permanent; name the shard and re-raise
            raise ValueError(f"{path.name}: {e}") from e


def _key_maps(config: ModelConfig):
    family = {"gemma2": gemma2, "qwen2": qwen2}.get(config.model_type, llama)
    return family.LAYER_KEY_MAP, family.TOP_KEY_MAP


def _np_dtype(dtype) -> np.dtype:
    import jax.numpy as jnp

    return np.dtype(
        {jnp.bfloat16: ml_dtypes.bfloat16, jnp.float32: np.float32,
         jnp.float16: np.float16}.get(dtype, dtype)
    )


def shard_files(model_dir: str | Path) -> list[Path]:
    """Resolve checkpoint shards: index file first, single-file fallback
    (the reference's fallback, llama3.2_model.py:1063-1065 — kept, but
    explicit instead of a bare ``except:``)."""
    model_dir = Path(model_dir)
    index = model_dir / "model.safetensors.index.json"
    if index.exists():
        with open(index) as f:
            weight_map: dict[str, str] = json.load(f)["weight_map"]
        return [model_dir / fn for fn in sorted(set(weight_map.values()))]
    single = model_dir / "model.safetensors"
    if single.exists():
        return [single]
    raise FileNotFoundError(
        f"no model.safetensors.index.json or model.safetensors in {model_dir}"
    )


def _open_shard(path: Path, use_native: bool):
    """Returns (file, native: bool).  The native reader mmaps the shard and
    does threaded transpose/cast (llm_np_cp_tpu/native); the safetensors
    python reader is the fallback."""
    if use_native:
        try:
            from llm_np_cp_tpu.native import NativeSafetensorsFile, is_available

            if is_available():
                return NativeSafetensorsFile(path), True
        except Exception:
            pass
    return safe_open(path, framework="np"), False


def load_params(
    model_dir: str | Path,
    config: ModelConfig | None = None,
    *,
    dtype=None,
    shardings: Any = None,
    use_native: bool = True,
) -> tuple[dict[str, Any], ModelConfig]:
    """Load an HF checkpoint directory into the model's param pytree.

    dtype: target dtype (default jnp.bfloat16; pass jnp.float32 for parity).
    shardings: optional pytree of jax.sharding.Sharding matching the param
        tree; each buffer is device_put onto it as soon as it is filled.
    use_native: route tensor bytes through the C++ reader when built.
    Returns (params, config).
    """
    import jax.numpy as jnp

    model_dir = Path(model_dir)
    if config is None:
        config = ModelConfig.from_json(model_dir / "config.json")
    dtype = dtype or jnp.bfloat16
    np_dtype = _np_dtype(dtype)
    layer_map, top_map = _key_maps(config)
    shapes = param_shapes(config)

    # Preallocated stacked host buffers.
    host: dict[str, Any] = {
        "embed_tokens": np.empty(shapes["embed_tokens"], dtype=np_dtype),
        "final_norm": np.empty(shapes["final_norm"], dtype=np_dtype),
        "layers": {
            name: np.empty(shape, dtype=np_dtype)
            for name, shape in shapes["layers"].items()
        },
    }
    if "lm_head" in shapes:
        host["lm_head"] = np.empty(shapes["lm_head"], dtype=np_dtype)

    filled: set[str] = set()

    def fill(f, native: bool, key: str, dest: np.ndarray, transpose: bool) -> None:
        if native:
            try:
                f.copy_into(key, dest, transpose=transpose)
            except ValueError as e:
                raise ValueError(f"{key}: checkpoint shape mismatch: {e}") from e
            return
        value = f.get_tensor(key)
        if transpose:
            value = value.T
        if dest.shape != value.shape:
            raise ValueError(
                f"{key}: checkpoint shape {value.shape} != expected {dest.shape}"
            )
        dest[...] = value.astype(np_dtype)

    def consume(f: Any, native: bool) -> None:
        for key in f.keys():
            m = _LAYER_RE.match(key)
            if m:
                idx, suffix = int(m.group(1)), m.group(2)
                if suffix not in layer_map:
                    continue  # e.g. rotary inv_freq buffers
                name, transpose = layer_map[suffix]
                if name not in host["layers"]:
                    if name.endswith("_bias"):
                        # A bias tensor the config gated OFF is
                        # PRESENT in the checkpoint — loading would
                        # silently drop it and produce wrong logits
                        # (the round-1 silent-wrongness class)
                        raise ValueError(
                            f"{key}: checkpoint carries this bias but "
                            f"the config disables it "
                            f"(attention_bias={config.attention_bias}, "
                            f"attention_out_bias={config.attention_out_bias}, "
                            f"mlp_bias={config.mlp_bias})"
                        )
                    continue
                fill(f, native, key, host["layers"][name][idx], transpose)
                filled.add(f"layers.{name}.{idx}")
            elif key in top_map:
                name, transpose = top_map[key]
                if name == "lm_head" and config.tie_word_embeddings:
                    continue  # tied: forward reuses embed_tokens
                if name not in host:
                    continue
                fill(f, native, key, host[name], transpose)
                filled.add(name)

    for path in shard_files(model_dir):
        _read_shard(path, use_native, consume)

    _check_complete(host, filled, config)

    def place(path_: tuple, buf: np.ndarray):
        if shardings is not None:
            shard = _tree_get(shardings, path_)
            if shard is not None:
                return jax.device_put(buf, shard)
        return jax.device_put(jnp.asarray(buf))

    params: dict[str, Any] = {}
    for k, v in host.items():
        if isinstance(v, dict):
            params[k] = {k2: place((k, k2), v2) for k2, v2 in v.items()}
        else:
            params[k] = place((k,), v)
    return params, config


def _tree_get(tree: Any, path: tuple):
    node = tree
    for p in path:
        if node is None:
            return None
        node = node.get(p) if isinstance(node, dict) else None
    return node


def _check_complete(host: dict, filled: set, config: ModelConfig) -> None:
    missing: list[str] = []
    for name in host:
        if name == "layers":
            for lname in host["layers"]:
                for i in range(config.num_hidden_layers):
                    if f"layers.{lname}.{i}" not in filled:
                        missing.append(f"model.layers.{i}.<{lname}>")
        elif name not in filled:
            missing.append(name)
    if missing:
        preview = ", ".join(missing[:6])
        raise ValueError(
            f"checkpoint incomplete: {len(missing)} tensors missing ({preview}"
            + (", ..." if len(missing) > 6 else "") + ")"
        )


# ----------------------------------------------------------------------
# Convenience: the reference's load_model() equivalent
# ----------------------------------------------------------------------

def load_model(
    model_name_or_dir: str,
    *,
    dtype=None,
    shardings: Any = None,
    tokenizer: bool = True,
):
    """(tokenizer, params, config) from a local dir or an HF repo id.

    Mirrors the reference's ``load_model`` surface (llama3.2_model.py:
    1082-1099) — AutoTokenizer + snapshot_download + weight load — but
    network access is attempted only when the argument is not an existing
    local directory.
    """
    path = Path(model_name_or_dir)
    if not path.exists():
        from huggingface_hub import snapshot_download

        path = Path(snapshot_download(repo_id=model_name_or_dir))
    tok = None
    if tokenizer:
        from transformers import AutoTokenizer

        tok = AutoTokenizer.from_pretrained(str(path))
    params, config = load_params(path, dtype=dtype, shardings=shardings)
    return tok, params, config
