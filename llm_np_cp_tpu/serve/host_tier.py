"""Host-RAM KV block tier: spill evicted prefix blocks, restore on hit.

The paged pool's prefix cache (serve/prefix_cache.py) makes shared
prompt blocks free to SERVE but not free to KEEP: at fleet scale the
prefix working set dwarfs pool HBM, and LRU reclaim simply drops
cache-only blocks — the next request with that prefix re-prefills
through the paged pool, paying the full ragged-attention sweep for K/V
the fleet already computed.  This module adds the tier under the pool:

- **spill** — when LRU reclaim is about to drop a fully-filled prefix
  block (``PrefixCache.on_reclaim``), the engine hands its device K/V
  (and int8 scale pages) to the tier; the WRITER THREAD copies them to
  host RAM, keyed by the block's existing chained content hash.  Key
  equality stays block-key equality, so ``PrefixCache``, the
  ``PrefixRouter`` and the journal need no new identity scheme.
- **restore** — at admission, ``ServeEngine._prefill_plan`` consults
  the tier AFTER the device cache; hits are staged back via
  ``jax.device_put`` on the writer thread and land as ordinary claimed
  pool blocks before the covering tick dispatches (the engine's
  ``_apply_tier_restores``), so restored prefixes consume ZERO tick
  budget exactly like device prefix hits and ``host_sync`` never waits
  on a transfer.
- **ship** — the fleet's drain/re-home paths (serve/replica.py) spill a
  replica's registered prefix blocks through the SHARED process tier
  before its prefixes re-home, so the destination replica restores them
  instead of re-prefilling.

Restore-vs-recompute is a MEASURED breakeven, not an assumption: a
startup probe times ``jax.device_put`` of one block-sized buffer
(``ensure_probe``) and the engine feeds a rolling measured prefill
token rate (``note_prefill_rate``; seeded from the analytic
TelemetryModel when attached).  ``should_restore`` compares restoring a
span against re-prefilling it; below breakeven the plan falls back to
re-prefill (counted, test-pinned).  ``breakeven_ratio`` > 1 means a
restore is cheaper than recomputing the same block.

THREADING (machine-checked by tools/lint R3, domain ``host_tier``):
the writer thread exclusively owns the host block store (``_wentries``,
``_wbytes``) — spills insert, capacity evicts LRU, restores read and
stage.  The engine/loop side communicates through the lock-protected
job queue (``_pending``) and completion map (``_done``); the counters
share the same lock.  ``match``/``contains`` READ the store without the
lock — dict lookups are GIL-atomic and a lost race just surfaces as a
restore miss the engine already handles by re-prefilling (benign racy
reads are the serve stack's documented pattern).

ZERO-OVERHEAD WHEN OFF: nothing constructs a ``HostTier`` unless
requested (``--kv-tier host``), and every engine hook is a single
``is None`` check (tools/lint R4 ``host_tier`` hook).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, NamedTuple

import numpy as np


class HostBlock(NamedTuple):
    """One pool block's K/V, host-resident.  Arrays are the block's
    device layout minus the block axis: ``[L, BS, K, D]`` (scales
    ``[L, BS, K]`` for int8 pools, else None)."""

    k: np.ndarray
    v: np.ndarray
    k_scale: np.ndarray | None
    v_scale: np.ndarray | None

    @property
    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in self if a is not None)


class HostTier:
    """LRU host pool of spilled KV blocks + the writer thread that
    moves them.

    ``capacity_bytes`` bounds host residency (LRU eviction past it —
    the tier is a cache, dropping is always safe).  One instance is
    shared per PROCESS: every replica's spills and restores go through
    it, which is exactly what makes fleet block shipping work.
    """

    def __init__(
        self,
        capacity_bytes: int,
        *,
        clock: Callable[[], float] = time.perf_counter,
    ) -> None:
        if capacity_bytes <= 0:
            raise ValueError(
                f"capacity_bytes must be > 0, got {capacity_bytes}"
            )
        self.capacity_bytes = int(capacity_bytes)
        self.clock = clock
        # writer-thread-owned (R3 "host_tier" domain): the host block
        # store, LRU-ordered oldest first, and its resident byte count
        self._wentries: OrderedDict[bytes, HostBlock] = OrderedDict()
        self._wbytes = 0
        # shared under _lock: the job queue, the staged-restore
        # completion map, and the counters
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: list = []
        self._done: dict[int, Any] = {}
        # tickets whose waiter timed out: the writer drops their staged
        # payloads instead of parking them in _done forever (the waiter
        # already fell back to re-prefill; an orphaned entry would pin
        # a block of device memory for the process lifetime)
        self._abandoned: set[int] = set()
        # keys with a spill job queued but not yet applied: the dedupe
        # the enqueue side keys off (contains() only sees APPLIED
        # entries, so without this a ship-spill racing an evict-spill
        # would double-queue and double-count)
        self._pending_spill_keys: set[bytes] = set()
        self._stopping = False
        self._next_ticket = 0
        self.n_spilled = 0
        self.spilled_bytes = 0
        self.n_restored = 0
        self.restored_bytes = 0
        self.n_restore_miss = 0
        self.n_dropped = 0
        self.n_skipped = 0  # below-breakeven re-prefill fallbacks
        self.restore_s: list[float] = []
        # breakeven measurements (shared under _lock): the startup
        # device_put probe and the engine-fed prefill-rate EWMA
        self.restore_s_per_block: float | None = None
        self.restore_gbps: float | None = None
        self.prefill_tok_s: float | None = None
        self._probed_bytes = 0
        # test/operator override: "auto" applies the measured breakeven,
        # "always"/"never" force the verdict (the forced-fallback test
        # and the bench's tier-off twin use these)
        self.policy = "auto"
        self._thread = threading.Thread(
            target=self._writer_loop, name="serve-kv-tier-writer",
            daemon=True,
        )
        self._thread.start()

    # -- lookups (engine/loop side; lock-free reads, see module doc) ---
    def match(self, keys: list[bytes]) -> int:
        """Longest leading run of ``keys`` host-resident right now.
        Pure lookup — no LRU touch (the restore jobs touch); a racing
        capacity eviction just turns into a restore miss later."""
        n = 0
        for key in keys:
            if key not in self._wentries:
                break
            n += 1
        return n

    def contains(self, key: bytes) -> bool:
        return key in self._wentries

    @property
    def resident_bytes(self) -> int:
        return self._wbytes

    def __len__(self) -> int:
        return len(self._wentries)

    # -- breakeven policy ----------------------------------------------
    def ensure_probe(self, block_shapes: list[tuple[tuple[int, ...], Any]],
                     *, device_put: Callable | None = None,
                     reps: int = 3) -> None:
        """Measure host→device bandwidth ONCE per tier with a
        block-sized transfer: build zero host buffers of the pool
        block's shapes/dtypes, time ``device_put`` + block-until-ready
        over ``reps`` transfers, keep the median.  Engines call this at
        build time (the probe is startup work, never tick work); later
        engines with the same geometry skip it."""
        import jax

        put = device_put or jax.device_put
        nbytes = 0
        bufs = []
        for shape, dtype in block_shapes:
            a = np.zeros(shape, dtype=dtype)
            bufs.append(a)
            nbytes += a.nbytes
        with self._lock:
            if self.restore_s_per_block is not None \
                    and self._probed_bytes == nbytes:
                return
        samples = []
        for _ in range(max(reps, 1)):
            t0 = self.clock()
            staged = [put(a) for a in bufs]
            for s in staged:
                s.block_until_ready()
            samples.append(self.clock() - t0)
        med = float(np.median(samples))
        with self._lock:
            self.restore_s_per_block = med
            self.restore_gbps = (
                nbytes / med / 1e9 if med > 0 else float("inf")
            )
            self._probed_bytes = nbytes

    def note_prefill_rate(self, tok_s: float) -> None:
        """Feed one measured (or model-seeded) prefill token rate; the
        EWMA is the recompute side of the breakeven."""
        if tok_s <= 0:
            return
        with self._lock:
            if self.prefill_tok_s is None:
                self.prefill_tok_s = float(tok_s)
            else:
                self.prefill_tok_s += 0.2 * (tok_s - self.prefill_tok_s)

    def set_measured(self, *, restore_s_per_block: float | None = None,
                     prefill_tok_s: float | None = None) -> None:
        """Pin the breakeven inputs directly (tests and offline
        calibration; production uses ensure_probe/note_prefill_rate)."""
        with self._lock:
            if restore_s_per_block is not None:
                self.restore_s_per_block = float(restore_s_per_block)
            if prefill_tok_s is not None:
                self.prefill_tok_s = float(prefill_tok_s)

    def breakeven_ratio(self, block_size: int) -> float | None:
        """(seconds to re-prefill one block) / (seconds to restore it):
        > 1 means restoring is cheaper.  None until both sides are
        measured — the scrape gauge reports 0 then."""
        restore_s = self.restore_s_per_block
        tok_s = self.prefill_tok_s
        if not restore_s or not tok_s:
            return None
        return (block_size / tok_s) / restore_s

    def should_restore(self, n_blocks: int, block_size: int) -> bool:
        """The per-prefix restore-vs-recompute verdict for a span of
        ``n_blocks`` (the span cancels out of the measured ratio; it is
        kept in the signature because a future disk tier pays per-span
        seek costs).  Unmeasured sides default to restore — a restore
        is bit-identical, so the optimistic default is correctness-
        neutral, and the probe runs at engine build anyway."""
        if self.policy == "always":
            return True
        if self.policy == "never":
            return False
        ratio = self.breakeven_ratio(block_size)
        return ratio is None or ratio >= 1.0

    def note_skip(self, n_blocks: int) -> None:
        """A below-breakeven host hit fell back to re-prefill."""
        with self._lock:
            self.n_skipped += n_blocks

    # -- spill / restore (enqueue side; any thread) --------------------
    def enqueue_spill(self, key: bytes, k: Any, v: Any,
                      k_scale: Any = None, v_scale: Any = None) -> bool:
        """Queue one block's device arrays for host copy.  Callers pass
        freshly-sliced per-block device arrays (the slice is an async
        device op ordered before any later overwrite of the pool block,
        so the copy is race-free by dispatch order); the writer thread
        pays the device→host sync.  Returns False — and queues nothing
        — when the key is already resident OR already pending (a
        ship-spill racing an evict-spill is routine), so callers' spill
        ledgers can never run ahead of the tier's own accounting."""
        with self._lock:
            if self._stopping:
                return False
            if key in self._pending_spill_keys or key in self._wentries:
                return False
            self._pending_spill_keys.add(key)
            self._pending.append(("spill", key, k, v, k_scale, v_scale))
            self._cond.notify()
        return True

    def enqueue_restore(self, key: bytes, block_id: int,
                        sharding: Any = None) -> int:
        """Queue one host block for device staging; returns the ticket
        ``take_restored`` redeems.  ``sharding`` (replicated, from the
        claiming engine's mesh) keeps staged in-avals placement-stable
        so the restore write never retraces."""
        with self._lock:
            ticket = self._next_ticket
            self._next_ticket += 1
            if self._stopping:
                self._done[ticket] = None
            else:
                self._pending.append(
                    ("restore", ticket, key, block_id, sharding))
                self._cond.notify()
        return ticket

    def take_restored(self, tickets: list[int],
                      timeout: float = 10.0) -> list[Any]:
        """Redeem restore tickets, in order; blocks until the writer
        has staged them all (or ``timeout``, after which missing
        entries come back None — the caller re-prefills, the contract
        every miss path shares).  Each result is ``(block_id, staged
        HostBlock-of-device-arrays, stage_seconds)`` or None."""
        deadline = self.clock() + timeout
        out: list[Any] = []
        with self._lock:
            for t in tickets:
                while t not in self._done:
                    left = deadline - self.clock()
                    if left <= 0 or (self._stopping
                                     and not self._pending):
                        break
                    self._cond.wait(min(left, 0.5))
                if t in self._done:
                    out.append(self._done.pop(t))
                else:
                    # gave up on this ticket: mark it abandoned so the
                    # writer drops the late payload instead of parking
                    # staged device arrays in _done forever
                    self._abandoned.add(t)
                    out.append(None)
        return out

    def await_resident(self, keys: list[bytes],
                       timeout: float = 2.0) -> bool:
        """Wait until every key in ``keys`` is host-resident (or
        ``timeout``) — the PER-CHAIN ship barrier: unlike ``drain``,
        which flushes the tier's whole queue (every job paying its
        device→host sync), this returns the moment the named chain
        lands, however busy the shared queue is.  False on timeout —
        the caller's admission just misses and re-prefills, the
        fallback every tier path shares."""
        deadline = self.clock() + timeout
        with self._lock:
            while True:
                if all(k in self._wentries for k in keys):
                    return True
                left = deadline - self.clock()
                if left <= 0 or self._stopping:
                    return False
                self._cond.wait(min(left, 0.2))

    # -- control -------------------------------------------------------
    def drain(self, timeout: float = 10.0) -> bool:
        """Barrier: every job enqueued before this call is processed
        (tests and the fleet drain path use it before asserting on or
        reading tier state)."""
        ev = threading.Event()
        with self._lock:
            if self._stopping and not self._thread.is_alive():
                return True
            self._pending.append(("flush", ev))
            self._cond.notify()
        return ev.wait(timeout)

    def close(self, timeout: float = 10.0) -> None:
        with self._lock:
            if self._stopping:
                return
            self._stopping = True
            self._cond.notify()
        self._thread.join(timeout)

    def stats(self) -> dict[str, Any]:
        """Point-in-time accounting for scrapes and tests."""
        with self._lock:
            restore_s = list(self.restore_s)
            out = {
                "capacity_bytes": self.capacity_bytes,
                "resident_bytes": self._wbytes,
                "resident_blocks": len(self._wentries),
                "spilled_blocks": self.n_spilled,
                "spilled_bytes": self.spilled_bytes,
                "restored_blocks": self.n_restored,
                "restored_bytes": self.restored_bytes,
                "restore_misses": self.n_restore_miss,
                "dropped_blocks": self.n_dropped,
                "skipped_blocks": self.n_skipped,
                "restore_gbps": self.restore_gbps or 0.0,
                "prefill_tok_s": self.prefill_tok_s or 0.0,
            }
        out["restore_s_p99"] = (
            float(np.percentile(np.asarray(restore_s), 99))
            if restore_s else 0.0
        )
        return out

    # -- writer thread (R3 "host_tier" domain) -------------------------
    def _writer_loop(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._stopping:
                    self._cond.wait(0.5)
                batch, self._pending = self._pending, []
                stopping = self._stopping
            for job in batch:
                self._writer_job(job)
            if stopping:
                with self._lock:
                    leftover, self._pending = self._pending, []
                    # unblock any take_restored waiters: their tickets
                    # resolve to None and the engine re-prefills
                    for job in leftover:
                        if job[0] == "restore":
                            self._done[job[1]] = None
                        elif job[0] == "flush":
                            job[1].set()
                    self._cond.notify_all()
                return

    def _writer_job(self, job: tuple) -> None:
        kind = job[0]
        if kind == "flush":
            job[1].set()
            return
        if kind == "spill":
            self._writer_spill(job)
        else:
            self._writer_restore(job)

    def _writer_spill(self, job: tuple) -> None:
        _, key, k, v, ks, vs = job
        if key in self._wentries:
            # already resident (the enqueue-side dedupe lost a race):
            # content under one key is identical by construction, so
            # touching the LRU slot is the whole job
            self._wentries.move_to_end(key)
            with self._lock:
                self._pending_spill_keys.discard(key)
            return
        try:
            blk = HostBlock(
                k=np.asarray(k), v=np.asarray(v),
                k_scale=np.asarray(ks) if ks is not None else None,
                v_scale=np.asarray(vs) if vs is not None else None,
            )
        except Exception:  # noqa: BLE001 — a failed copy drops, never crashes
            with self._lock:
                self.n_dropped += 1
                self._pending_spill_keys.discard(key)
            return
        self._wentries[key] = blk
        self._wbytes += blk.nbytes
        dropped = 0
        while self._wbytes > self.capacity_bytes and len(self._wentries) > 1:
            _, old = self._wentries.popitem(last=False)
            self._wbytes -= old.nbytes
            dropped += 1
        with self._lock:
            self.n_spilled += 1
            self.spilled_bytes += blk.nbytes
            self.n_dropped += dropped
            self._pending_spill_keys.discard(key)
            # wake await_resident waiters (the per-chain ship barrier)
            self._cond.notify_all()

    def _writer_restore(self, job: tuple) -> None:
        import jax

        _, ticket, key, block_id, sharding = job
        ent = self._wentries.get(key)
        if ent is None:
            with self._lock:
                self.n_restore_miss += 1
                if ticket in self._abandoned:
                    self._abandoned.discard(ticket)
                else:
                    self._done[ticket] = None
                self._cond.notify_all()
            return
        self._wentries.move_to_end(key)  # a restore is an LRU touch
        t0 = self.clock()
        try:
            if sharding is not None:
                put = lambda a: jax.device_put(a, sharding)  # noqa: E731
            else:
                put = jax.device_put
            staged = HostBlock(
                k=put(ent.k), v=put(ent.v),
                k_scale=put(ent.k_scale) if ent.k_scale is not None else None,
                v_scale=put(ent.v_scale) if ent.v_scale is not None else None,
            )
            staged.k.block_until_ready()
        except Exception:  # noqa: BLE001 — staging failure = miss, engine re-prefills
            with self._lock:
                self.n_restore_miss += 1
                if ticket in self._abandoned:
                    self._abandoned.discard(ticket)
                else:
                    self._done[ticket] = None
                self._cond.notify_all()
            return
        dt = self.clock() - t0
        with self._lock:
            self.n_restored += 1
            self.restored_bytes += ent.nbytes
            self.restore_s.append(dt)
            if len(self.restore_s) > 4096:
                del self.restore_s[:2048]
            if ticket in self._abandoned:
                # the waiter timed out and re-prefilled: drop the late
                # payload — nothing will ever redeem this ticket
                self._abandoned.discard(ticket)
            else:
                self._done[ticket] = (block_id, staged, dt)
            self._cond.notify_all()
