"""Synthetic request traces for the serving engine.

A serving benchmark needs arrivals, not a batch: the load pattern that
exposes queueing, admission control, and preemption is requests landing
at random times with mixed prompt lengths.  The standard open-loop model
is a Poisson process (exponential inter-arrival gaps at a target
request rate) — the same workload shape the `serve-bench` CLI
subcommand, bench.py's serving scenario, and the scheduler tests replay,
so one definition lives here.

Prompts are random token ids: serving throughput is content-independent
(decode cost depends on shapes only), and synthetic ids avoid needing a
tokenizer in CPU tests and bench children.
"""

from __future__ import annotations

import time
from typing import Any, Callable

import numpy as np


def replay_arrivals(
    target: Any,
    trace: list[dict[str, Any]],
    snapshot: Callable[[], dict[str, Any]],
    *,
    realtime: bool = False,
    max_ticks: int = 100_000,
    on_tick: Callable[[int], None] | None = None,
) -> dict[str, Any]:
    """The ONE arrival-replay loop behind ``ServeEngine.replay_trace``
    and ``ReplicaSet.replay_trace`` (their hand-rolled twins would
    diverge on the clock discipline otherwise — and bench compares
    results across exactly those two paths).

    ``target`` provides ``clock``/``submit``/``step``; ``snapshot``
    renders the final metrics.  realtime=False (default, what tests and
    bench use on CPU): arrivals are released by a virtual clock that
    advances to the next arrival whenever the target is idle — the
    schedule stress is preserved without wall-clock sleeps.
    realtime=True sleeps until each arrival (live serving simulation).
    ``on_tick(i)`` (optional) runs after the i-th ``step()`` — the hook
    the rolling-upgrade bench uses to trigger a mid-trace roll.
    """
    pending = sorted(trace, key=lambda t: t["arrival_s"])
    t0 = target.clock()
    virtual_now = 0.0
    for tick_i in range(max_ticks):
        now = target.clock() - t0 if realtime else virtual_now
        while pending and pending[0]["arrival_s"] <= now:
            item = pending.pop(0)
            req = target.submit(
                item["prompt"], item["max_new_tokens"],
                seed=item.get("seed", 0),
                callback=item.get("callback"),
                arrival_time=item["arrival_s"],
                speculative=item.get("speculative", False),
                tenant=item.get("tenant", "default"),
            )
            if realtime:
                # wall arrival: TTFT then counts the wait between
                # arrival and the tick loop noticing the request
                req.extra["arrival_wall"] = t0 + item["arrival_s"]
        had_work = target.step()
        if on_tick is not None:
            on_tick(tick_i)
            had_work = had_work or target.step()  # roll may move work
        if not had_work and pending:
            nxt = pending[0]["arrival_s"]
            if realtime:
                time.sleep(max(0.0, nxt - (target.clock() - t0)))
            else:
                virtual_now = nxt
        elif not had_work and not pending:
            return snapshot()
        if not realtime:
            virtual_now = max(virtual_now, target.clock() - t0)
    raise RuntimeError(
        f"trace replay did not drain within {max_ticks} ticks"
    )


def poisson_trace(
    rng: np.random.Generator,
    n_requests: int,
    *,
    rate_rps: float,
    prompt_len_range: tuple[int, int],
    max_new_tokens: int | tuple[int, int],
    vocab_size: int,
    seed_base: int = 0,
    distinct_prompts: int | None = None,
) -> list[dict[str, Any]]:
    """``n_requests`` arrivals for ``ServeEngine.replay_trace``.

    rate_rps: mean arrival rate (requests/second); gaps are exponential.
    prompt_len_range / max_new_tokens: inclusive ranges sampled uniformly
    (an int ``max_new_tokens`` pins every request to that budget, which
    the engine-vs-offline parity tests need).
    distinct_prompts: if set, only this many distinct prompts are
    generated and requests cycle through them — the shared-prefix
    workload shape (many users asking the same things) that the
    refcounted prefix cache is built for.
    """
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    lo, hi = prompt_len_range
    if not (1 <= lo <= hi):
        raise ValueError(f"bad prompt_len_range {prompt_len_range}")
    if distinct_prompts is not None and distinct_prompts < 1:
        raise ValueError(f"distinct_prompts must be >= 1, got {distinct_prompts}")
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, size=n_requests))

    def draw_mnt() -> int:
        if isinstance(max_new_tokens, tuple):
            mlo, mhi = max_new_tokens
            return int(rng.integers(mlo, mhi + 1))
        return int(max_new_tokens)

    def make_prompt() -> np.ndarray:
        plen = int(rng.integers(lo, hi + 1))
        return (
            rng.integers(1, vocab_size, size=plen, dtype=np.int64)
            .astype(np.int32)
        )

    pool = (
        [make_prompt() for _ in range(distinct_prompts)]
        if distinct_prompts is not None else None
    )
    trace: list[dict[str, Any]] = []
    for i in range(n_requests):
        if pool is not None:
            prompt = pool[i % len(pool)]
            mnt = draw_mnt()
        else:
            # draw order (plen, mnt, tokens) is the historical sequence —
            # a fixed seed must keep replaying the exact same trace
            # across versions
            plen = int(rng.integers(lo, hi + 1))
            mnt = draw_mnt()
            prompt = (
                rng.integers(1, vocab_size, size=plen, dtype=np.int64)
                .astype(np.int32)
            )
        trace.append({
            "arrival_s": float(arrivals[i]),
            "prompt": prompt,
            "max_new_tokens": mnt,
            "seed": seed_base + i,
        })
    return trace
