"""Synthetic request traces for the serving engine.

A serving benchmark needs arrivals, not a batch: the load pattern that
exposes queueing, admission control, and preemption is requests landing
at random times with mixed prompt lengths.  The standard open-loop model
is a Poisson process (exponential inter-arrival gaps at a target
request rate) — the same workload shape the `serve-bench` CLI
subcommand, bench.py's serving scenario, and the scheduler tests replay,
so one definition lives here.

Prompts are random token ids: serving throughput is content-independent
(decode cost depends on shapes only), and synthetic ids avoid needing a
tokenizer in CPU tests and bench children.
"""

from __future__ import annotations

from typing import Any

import numpy as np


def poisson_trace(
    rng: np.random.Generator,
    n_requests: int,
    *,
    rate_rps: float,
    prompt_len_range: tuple[int, int],
    max_new_tokens: int | tuple[int, int],
    vocab_size: int,
    seed_base: int = 0,
) -> list[dict[str, Any]]:
    """``n_requests`` arrivals for ``ServeEngine.replay_trace``.

    rate_rps: mean arrival rate (requests/second); gaps are exponential.
    prompt_len_range / max_new_tokens: inclusive ranges sampled uniformly
    (an int ``max_new_tokens`` pins every request to that budget, which
    the engine-vs-offline parity tests need).
    """
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    lo, hi = prompt_len_range
    if not (1 <= lo <= hi):
        raise ValueError(f"bad prompt_len_range {prompt_len_range}")
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, size=n_requests))
    trace: list[dict[str, Any]] = []
    for i in range(n_requests):
        plen = int(rng.integers(lo, hi + 1))
        if isinstance(max_new_tokens, tuple):
            mlo, mhi = max_new_tokens
            mnt = int(rng.integers(mlo, mhi + 1))
        else:
            mnt = int(max_new_tokens)
        trace.append({
            "arrival_s": float(arrivals[i]),
            "prompt": rng.integers(1, vocab_size, size=plen, dtype=np.int64)
            .astype(np.int32),
            "max_new_tokens": mnt,
            "seed": seed_base + i,
        })
    return trace
