"""Stdlib clients for the HTTP front-end: bench loadgen + smoke tests.

Two flavors, both dependency-free:

- ``astream_completion`` — asyncio streams, one coroutine per request;
  what the bench loadgen fans out to measure client-observed TTFT (the
  number the HTTP layer's overhead actually shows up in).
- ``http_get`` / ``post_completion`` — synchronous ``http.client``, the
  "any stock client works" smoke path (no asyncio on the caller side).
"""

from __future__ import annotations

import asyncio
import contextlib
import http.client
import json
import time
from typing import Any

from llm_np_cp_tpu.serve.http.sse import iter_sse_payloads


def http_get(host: str, port: int, path: str,
             timeout: float = 10.0) -> tuple[int, bytes]:
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def post_completion(host: str, port: int, payload: dict[str, Any],
                    timeout: float = 60.0) -> tuple[int, dict[str, Any]]:
    """Non-streaming completion through the stock stdlib client."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        body = json.dumps(payload)
        conn.request("POST", "/v1/completions", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, json.loads(raw) if raw else {}
    finally:
        conn.close()


async def astream_completion(
    host: str, port: int, payload: dict[str, Any], *,
    timeout: float = 120.0,
    disconnect_after: int | None = None,
) -> dict[str, Any]:
    """POST a streaming completion and consume its SSE stream.

    Returns ``{"status", "token_ids", "text", "finish_reason",
    "ttft_s", "latency_s", "error"}``.  ``disconnect_after=n`` closes
    the socket after the n-th token chunk (the forced mid-stream
    disconnect the abort tests drive); the result then carries
    ``finish_reason="disconnected"``.
    """
    t0 = time.perf_counter()
    req = dict(payload)
    req["stream"] = True
    body = json.dumps(req).encode()
    reader, writer = await asyncio.open_connection(host, port)
    out: dict[str, Any] = {
        "status": None, "token_ids": [], "text": "",
        "finish_reason": None, "ttft_s": None, "latency_s": None,
        "error": None,
    }
    try:
        writer.write(
            b"POST /v1/completions HTTP/1.1\r\n"
            + f"Host: {host}:{port}\r\n".encode()
            + b"Content-Type: application/json\r\n"
            + f"Content-Length: {len(body)}\r\n".encode()
            + b"Connection: close\r\n\r\n" + body
        )
        await writer.drain()

        async def consume() -> None:
            status_line = await reader.readline()
            out["status"] = int(status_line.split()[1])
            headers = b""
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                headers += line
            if out["status"] != 200:
                out["error"] = (await reader.read()).decode(errors="replace")
                return
            n = 0
            text_parts: list[str] = []
            async for chunk in iter_sse_payloads(reader):
                choice = chunk["choices"][0]
                if out["ttft_s"] is None:
                    out["ttft_s"] = time.perf_counter() - t0
                if choice.get("token_id") is not None:
                    out["token_ids"].append(choice["token_id"])
                if choice.get("text"):
                    text_parts.append(choice["text"])
                if choice.get("finish_reason"):
                    out["finish_reason"] = choice["finish_reason"]
                n += 1
                if disconnect_after is not None and n >= disconnect_after:
                    out["finish_reason"] = "disconnected"
                    return
            out["text"] = "".join(text_parts)

        await asyncio.wait_for(consume(), timeout=timeout)
    finally:
        writer.close()
        with contextlib.suppress(Exception):
            await writer.wait_closed()
    out["latency_s"] = time.perf_counter() - t0
    return out
