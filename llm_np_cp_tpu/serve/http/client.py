"""Stdlib clients for the HTTP front-end: bench loadgen + smoke tests.

Two flavors, both dependency-free:

- ``astream_completion`` — asyncio streams, one coroutine per request;
  what the bench loadgen fans out to measure client-observed TTFT (the
  number the HTTP layer's overhead actually shows up in).
- ``http_get`` / ``post_completion`` — synchronous ``http.client``, the
  "any stock client works" smoke path (no asyncio on the caller side).
"""

from __future__ import annotations

import asyncio
import contextlib
import http.client
import json
import random
import time
from typing import Any

from llm_np_cp_tpu.serve.http.sse import iter_sse_payloads


def http_get(host: str, port: int, path: str,
             timeout: float = 10.0) -> tuple[int, bytes]:
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def http_post(host: str, port: int, path: str,
              payload: dict[str, Any] | None = None,
              timeout: float = 60.0) -> tuple[int, dict[str, Any]]:
    """JSON POST to an arbitrary path (the /admin lifecycle endpoints);
    returns (status, parsed body or {})."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", path,
                     body=json.dumps(payload or {}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        raw = resp.read()
        try:
            return resp.status, json.loads(raw) if raw else {}
        except ValueError:
            return resp.status, {"raw": raw.decode(errors="replace")}
    finally:
        conn.close()


def post_completion(host: str, port: int, payload: dict[str, Any],
                    timeout: float = 60.0) -> tuple[int, dict[str, Any]]:
    """Non-streaming completion through the stock stdlib client."""
    return http_post(host, port, "/v1/completions", payload, timeout)


async def _astream_once(
    host: str, port: int, body: bytes, t0: float,
    out: dict[str, Any], *,
    timeout: float, disconnect_after: int | None,
    headers: tuple[tuple[str, str], ...] = (),
) -> dict[str, Any]:
    """One streaming POST attempt (no retry).  ``out`` is caller-owned so
    partial progress (tokens already received) survives a mid-stream
    exception — the retry wrapper must see it to resume (or refuse a
    resend)."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(
            b"POST /v1/completions HTTP/1.1\r\n"
            + f"Host: {host}:{port}\r\n".encode()
            + b"Content-Type: application/json\r\n"
            + f"Content-Length: {len(body)}\r\n".encode()
            + b"".join(f"{k}: {v}\r\n".encode() for k, v in headers)
            + b"Connection: close\r\n\r\n" + body
        )
        await writer.drain()

        async def consume() -> None:
            status_line = await reader.readline()
            if not status_line:
                # closed before any response byte — the same transient
                # class as a refused connection, typed so the retry
                # wrapper's except tuple catches it
                raise asyncio.IncompleteReadError(b"", None)
            out["status"] = int(status_line.split()[1])
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                key, _, value = line.decode("latin-1").partition(":")
                if key.strip().lower() == "retry-after":
                    with contextlib.suppress(ValueError):
                        out["retry_after_s"] = float(value.strip())
            if out["status"] != 200:
                out["error"] = (await reader.read()).decode(errors="replace")
                return
            n = 0
            async for chunk in iter_sse_payloads(reader):
                choice = chunk["choices"][0]
                if chunk.get("id"):
                    # the completion id — the resume handle a retry
                    # re-POSTs with after a mid-stream cut
                    out["stream_id"] = chunk["id"]
                if out["ttft_s"] is None:
                    out["ttft_s"] = time.perf_counter() - t0
                if choice.get("token_id") is not None:
                    out["token_ids"].append(choice["token_id"])
                if choice.get("text"):
                    # caller-owned like token_ids: text received before
                    # a mid-stream cut must survive into the resume
                    out["text_parts"].append(choice["text"])
                if choice.get("finish_reason"):
                    out["finish_reason"] = choice["finish_reason"]
                n += 1
                if disconnect_after is not None and n >= disconnect_after:
                    out["finish_reason"] = "disconnected"
                    return

        await asyncio.wait_for(consume(), timeout=timeout)
    finally:
        writer.close()
        with contextlib.suppress(Exception):
            await writer.wait_closed()
    out["latency_s"] = time.perf_counter() - t0
    return out


async def astream_completion(
    host: str, port: int, payload: dict[str, Any], *,
    timeout: float = 120.0,
    disconnect_after: int | None = None,
    retries: int = 0,
    backoff_s: float = 0.25,
    max_backoff_s: float = 4.0,
    rng: random.Random | None = None,
) -> dict[str, Any]:
    """POST a streaming completion and consume its SSE stream.

    Returns ``{"status", "token_ids", "text", "finish_reason",
    "ttft_s", "latency_s", "error", "retries"}``.  ``disconnect_after=n``
    closes the socket after the n-th token chunk (the forced mid-stream
    disconnect the abort tests drive); the result then carries
    ``finish_reason="disconnected"``.

    ``retries``: transient failures — HTTP 429/503 (backpressure, drain,
    a mid-restart blip) and connection errors that struck before any
    token arrived — are retried up to this many times with capped
    exponential backoff plus jitter, honoring the server's ``Retry-After``
    when it is larger than the backoff.  TTFT is measured from the FIRST
    attempt, so retried requests honestly carry their queueing delay.

    RESUME (the serve/journal.py protocol): a stream cut AFTER tokens
    were delivered is never blindly resent — if the stream's completion
    id was seen, the retry re-POSTs ``{"request_id": <id>}`` with
    ``Last-Event-ID: <tokens received>``, and the server replays exactly
    the missing suffix (surviving its own restart via the journal) then
    continues live, so no token is ever generated twice.  Without a
    resume handle the old rule holds: the failure surfaces.  The result
    carries ``resumed`` (resume attempts) and ``resume_latency_s``
    (first cut → first resumed token — the client-observed
    restart-to-first-resumed-token latency).
    """
    t0 = time.perf_counter()
    req = dict(payload)
    req["stream"] = True
    base_body = json.dumps(req).encode()
    rng = rng or random
    attempts = 0
    tokens: list[int] = []
    text_parts: list[str] = []
    stream_id: str | None = None
    ttft_s: float | None = None
    resumed = 0
    resume_latency_s: float | None = None
    t_cut: float | None = None
    while True:
        out: dict[str, Any] = {
            "status": None, "token_ids": [], "text_parts": [],
            "finish_reason": None, "ttft_s": None, "latency_s": None,
            "error": None, "retry_after_s": None, "stream_id": None,
        }
        if tokens and stream_id is not None:
            # resume the cut stream instead of resending the prompt
            # (no "model" key when the original request carried none —
            # the server then echoes its own model id)
            resume_req = {"request_id": stream_id, "stream": True}
            if req.get("model") is not None:
                resume_req["model"] = req["model"]
            body = json.dumps(resume_req).encode()
            headers = (("Last-Event-ID", str(len(tokens))),)
        else:
            body, headers = base_body, ()
        try:
            await _astream_once(
                host, port, body, t0, out,
                timeout=timeout, disconnect_after=disconnect_after,
                headers=headers,
            )
            # a 200 whose SSE stream ended with neither a token nor a
            # finish_reason is a truncated response (a reset can read as
            # clean EOF on loopback) — transient, like a refused
            # connection.  A truncated stream that DID deliver tokens is
            # transient too WHEN it can be resumed (the server replays
            # the suffix); without a resume handle it is returned as-is
            # (resending would duplicate generation).
            cut_mid_stream = (
                out["status"] == 200 and out["finish_reason"] is None
                and (tokens or out["token_ids"])
                and (out["stream_id"] or stream_id) is not None
            )
            transient = out["status"] in (429, 503) or cut_mid_stream or (
                out["status"] == 200 and not out["token_ids"]
                and not tokens and out["finish_reason"] is None
            )
        except (OSError, asyncio.IncompleteReadError) as e:
            if isinstance(e, TimeoutError):
                # py>=3.11 spells asyncio.wait_for's timeout as
                # builtins.TimeoutError, an OSError subclass — a timeout
                # is the caller's budget, never a transient to retry
                raise
            resumable = (
                (out["stream_id"] or stream_id) is not None
                or not (tokens or out["token_ids"])
            )
            if not resumable or attempts >= retries:
                # tokens streamed and no resume handle: a blind resend
                # would generate the whole completion twice — surface
                raise
            out["error"] = f"{type(e).__name__}: {e}"
            transient = True
        # fold this attempt's progress into the stream-so-far (resumes
        # deliver exactly the missing suffix, so append is exact)
        if out["token_ids"]:
            if (t_cut is not None and resume_latency_s is None
                    and out["ttft_s"] is not None):
                # cut → FIRST resumed token (the attempt's ttft is
                # anchored at t0), not cut → end-of-stream
                resume_latency_s = max(t0 + out["ttft_s"] - t_cut, 0.0)
            tokens.extend(out["token_ids"])
        text_parts.extend(out["text_parts"])
        if out["stream_id"]:
            stream_id = out["stream_id"]
        if ttft_s is None:
            ttft_s = out["ttft_s"]
        if not transient or attempts >= retries:
            out["token_ids"] = tokens
            out["text"] = "".join(text_parts)
            out.pop("text_parts", None)
            out["ttft_s"] = ttft_s
            out["latency_s"] = time.perf_counter() - t0
            out["retries"] = attempts
            out["resumed"] = resumed
            out["resume_latency_s"] = resume_latency_s
            return out
        if tokens and stream_id is not None:
            resumed += 1
            if t_cut is None:
                t_cut = time.perf_counter()
        wait = min(backoff_s * (2 ** attempts), max_backoff_s)
        if out.get("retry_after_s"):
            wait = max(wait, out["retry_after_s"])
        await asyncio.sleep(wait * (1.0 + 0.25 * rng.random()))
        attempts += 1
