"""Stdlib clients for the HTTP front-end: bench loadgen + smoke tests.

Two flavors, both dependency-free:

- ``astream_completion`` — asyncio streams, one coroutine per request;
  what the bench loadgen fans out to measure client-observed TTFT (the
  number the HTTP layer's overhead actually shows up in).
- ``http_get`` / ``post_completion`` — synchronous ``http.client``, the
  "any stock client works" smoke path (no asyncio on the caller side).
"""

from __future__ import annotations

import asyncio
import contextlib
import http.client
import json
import random
import time
from typing import Any

from llm_np_cp_tpu.serve.http.sse import iter_sse_payloads


def http_get(host: str, port: int, path: str,
             timeout: float = 10.0) -> tuple[int, bytes]:
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def post_completion(host: str, port: int, payload: dict[str, Any],
                    timeout: float = 60.0) -> tuple[int, dict[str, Any]]:
    """Non-streaming completion through the stock stdlib client."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        body = json.dumps(payload)
        conn.request("POST", "/v1/completions", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, json.loads(raw) if raw else {}
    finally:
        conn.close()


async def _astream_once(
    host: str, port: int, body: bytes, t0: float,
    out: dict[str, Any], *,
    timeout: float, disconnect_after: int | None,
) -> dict[str, Any]:
    """One streaming POST attempt (no retry).  ``out`` is caller-owned so
    partial progress (tokens already received) survives a mid-stream
    exception — the retry wrapper must see it to refuse a resend."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(
            b"POST /v1/completions HTTP/1.1\r\n"
            + f"Host: {host}:{port}\r\n".encode()
            + b"Content-Type: application/json\r\n"
            + f"Content-Length: {len(body)}\r\n".encode()
            + b"Connection: close\r\n\r\n" + body
        )
        await writer.drain()

        async def consume() -> None:
            status_line = await reader.readline()
            if not status_line:
                # closed before any response byte — the same transient
                # class as a refused connection, typed so the retry
                # wrapper's except tuple catches it
                raise asyncio.IncompleteReadError(b"", None)
            out["status"] = int(status_line.split()[1])
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                key, _, value = line.decode("latin-1").partition(":")
                if key.strip().lower() == "retry-after":
                    with contextlib.suppress(ValueError):
                        out["retry_after_s"] = float(value.strip())
            if out["status"] != 200:
                out["error"] = (await reader.read()).decode(errors="replace")
                return
            n = 0
            text_parts: list[str] = []
            async for chunk in iter_sse_payloads(reader):
                choice = chunk["choices"][0]
                if out["ttft_s"] is None:
                    out["ttft_s"] = time.perf_counter() - t0
                if choice.get("token_id") is not None:
                    out["token_ids"].append(choice["token_id"])
                if choice.get("text"):
                    text_parts.append(choice["text"])
                if choice.get("finish_reason"):
                    out["finish_reason"] = choice["finish_reason"]
                n += 1
                if disconnect_after is not None and n >= disconnect_after:
                    out["finish_reason"] = "disconnected"
                    return
            out["text"] = "".join(text_parts)

        await asyncio.wait_for(consume(), timeout=timeout)
    finally:
        writer.close()
        with contextlib.suppress(Exception):
            await writer.wait_closed()
    out["latency_s"] = time.perf_counter() - t0
    return out


async def astream_completion(
    host: str, port: int, payload: dict[str, Any], *,
    timeout: float = 120.0,
    disconnect_after: int | None = None,
    retries: int = 0,
    backoff_s: float = 0.25,
    max_backoff_s: float = 4.0,
    rng: random.Random | None = None,
) -> dict[str, Any]:
    """POST a streaming completion and consume its SSE stream.

    Returns ``{"status", "token_ids", "text", "finish_reason",
    "ttft_s", "latency_s", "error", "retries"}``.  ``disconnect_after=n``
    closes the socket after the n-th token chunk (the forced mid-stream
    disconnect the abort tests drive); the result then carries
    ``finish_reason="disconnected"``.

    ``retries``: transient failures — HTTP 429/503 (backpressure, drain,
    a mid-restart blip) and connection errors that struck before any
    token arrived — are retried up to this many times with capped
    exponential backoff plus jitter, honoring the server's ``Retry-After``
    when it is larger than the backoff.  A stream that already delivered
    tokens is never retried (a blind resend would duplicate output);
    TTFT is measured from the FIRST attempt, so retried requests
    honestly carry their queueing delay.
    """
    t0 = time.perf_counter()
    req = dict(payload)
    req["stream"] = True
    body = json.dumps(req).encode()
    rng = rng or random
    attempts = 0
    while True:
        out: dict[str, Any] = {
            "status": None, "token_ids": [], "text": "",
            "finish_reason": None, "ttft_s": None, "latency_s": None,
            "error": None, "retry_after_s": None,
        }
        try:
            await _astream_once(
                host, port, body, t0, out,
                timeout=timeout, disconnect_after=disconnect_after,
            )
            # a 200 whose SSE stream ended with neither a token nor a
            # finish_reason is a truncated response (a reset can read as
            # clean EOF on loopback) — transient, like a refused
            # connection; a truncated stream that DID deliver tokens is
            # returned as-is (resending would duplicate generation)
            transient = out["status"] in (429, 503) or (
                out["status"] == 200 and not out["token_ids"]
                and out["finish_reason"] is None
            )
        except (OSError, asyncio.IncompleteReadError) as e:
            if isinstance(e, TimeoutError):
                # py>=3.11 spells asyncio.wait_for's timeout as
                # builtins.TimeoutError, an OSError subclass — a timeout
                # is the caller's budget, never a transient to retry
                raise
            if out["token_ids"] or attempts >= retries:
                # tokens already streamed: a blind resend would generate
                # the whole completion twice — surface the failure
                raise
            # transient regardless of how far the response got: a reset
            # after the 200 status line but before the first token (a
            # restart blip, an injected reset) retries like a refusal
            out["error"] = f"{type(e).__name__}: {e}"
            transient = True
        if not transient or out["token_ids"] or attempts >= retries:
            out["retries"] = attempts
            return out
        wait = min(backoff_s * (2 ** attempts), max_backoff_s)
        if out.get("retry_after_s"):
            wait = max(wait, out["retry_after_s"])
        await asyncio.sleep(wait * (1.0 + 0.25 * rng.random()))
        attempts += 1
