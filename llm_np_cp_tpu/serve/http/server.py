"""Dependency-free asyncio HTTP front-end over ``ServeEngine``.

Two threads, one contract:

- The **engine thread** (``EngineRunner``) owns the ``ServeEngine``
  exclusively — every engine entry point (submit/abort/step) runs there,
  so the engine itself never needs locks.  Handlers talk to it through a
  thread-safe command queue; admission decisions (queue-full → 429,
  capacity ValueError → 400) are made ON the engine thread where
  scheduler state is consistent, and the verdict comes back as the first
  event on the request's bridge queue.
- The **event loop** (``HttpServer``) speaks HTTP/1.1 over stdlib
  ``asyncio`` streams (no FastAPI/uvicorn — the container has neither,
  and a serving stack's front-end should not be the dependency
  surface).  Per-token events cross back via
  ``loop.call_soon_threadsafe`` onto per-request ``asyncio.Queue``s.

Endpoints:

- ``POST /v1/completions`` — OpenAI-compatible JSON; ``"stream": true``
  streams SSE chunks fed from the engine's per-request callbacks.
  Client disconnect mid-stream aborts the request (blocks decref back to
  the pool); ``timeout_s`` (or the server-wide ``--request-timeout``)
  becomes an engine deadline with the same abort path.
- ``GET /healthz`` — liveness + supervision state (``ok`` /
  ``degraded`` during a supervised engine restart / ``draining`` /
  ``crashed``).
- ``GET /metrics`` — Prometheus text format from ``ServeMetrics`` plus
  live pool/stream/supervision gauges (restarts_total,
  faults_injected_total, recovery latency, degraded).
- ``GET /debug/trace`` — the tracing ring buffer (serve/tracing.py) as
  Chrome/Perfetto trace-event JSON, when the server was started with
  tracing on (``--trace-ring`` / ``--trace-out``); 404 otherwise.  With
  tracing on, every completion's span starts at socket accept (an
  ``http`` bracket around the engine's queued/prefill/decode spans), so
  network+parse time is separable from queue wait.

Shutdown (SIGTERM/SIGINT): stop admission (503 on new completions),
finish in-flight streams up to ``drain_timeout``, abort stragglers, and
only then close the listening socket.

Failure handling: with supervision on (``max_restarts > 0``), a crashed
or hung (``tick_deadline``) engine tick thread triggers a bounded
exponential-backoff restart that rebuilds the engine + pool and replays
every in-flight request teacher-forced (token-identical recovery; see
``EngineRunner``).  With supervision off, a dead tick thread fails all
streams cleanly and wedges the server at 503, as before.
"""

from __future__ import annotations

import asyncio
import contextlib
import itertools
import json
import queue as queue_mod
import signal
import sys
import threading
import time
from collections import deque
from typing import Any

from llm_np_cp_tpu.serve.http.protocol import (
    HTTPError,
    chunk_payload,
    completion_payload,
    error_body,
    parse_completion_request,
    parse_completion_rid,
    parse_last_event_id,
    parse_resume_request,
)
from llm_np_cp_tpu.serve.http.sse import DONE_SENTINEL, sse_event
from llm_np_cp_tpu.serve.metrics import ServeMetrics
from llm_np_cp_tpu.serve.scheduler import QueueFull, TenantThrottled
from llm_np_cp_tpu.serve.tracing import (
    gen_trace_id,
    make_traceparent,
    parse_traceparent,
)

TERMINAL_EVENTS = ("stop", "length", "aborted")


class _ResumeEcho:
    """The one payload field ``_stream_response`` reads, for resumed
    streams (which carry no CompletionPayload)."""

    def __init__(self, echo_model: str) -> None:
        self.echo_model = echo_model
_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 408: "Request Timeout",
    409: "Conflict", 413: "Payload Too Large",
    429: "Too Many Requests",
    500: "Internal Server Error", 503: "Service Unavailable",
}
MAX_BODY_BYTES = 8 << 20


class EngineRunner:
    """Supervises the engine tick loop on a worker thread and bridges it
    to asyncio handlers.

    Commands (submit/abort) are drained at the top of every loop
    iteration, then one ``engine.step()`` runs if there is work;  when
    idle the loop blocks on the command queue (no spin).  Events flow
    back per request: ``("accepted",)`` / ``("rejected", retry_after)`` /
    ``("error", msg)`` on the admission verdict, ``("token", id, delta)``
    per generated token, ``("finish", reason, final_text_delta)``
    terminally.

    SUPERVISION (``max_restarts > 0``): a crashed tick thread — or one a
    watchdog declares hung because no tick heartbeat landed within
    ``tick_deadline`` — no longer takes the server down.  The runner
    bumps a *generation* counter (superseding the old thread: if it ever
    wakes it sees the stale generation and exits without touching the
    bridges), waits a bounded exponential backoff, rebuilds the engine +
    block pool (``ServeEngine.clone_fresh`` — the compiled steps are
    shared, so a restart never recompiles), and REPLAYS every in-flight
    request with its already-delivered tokens teacher-forced
    (``ServeEngine.recover`` — the evict-requeue discipline, so the
    recovered streams are token-identical to an uninterrupted run and no
    token is ever re-sent).  The command queue survives the restart, so
    submits that arrive during recovery just queue up; ``/healthz``
    reports ``degraded`` until the rebuilt engine completes its first
    loop pass.  Once ``max_restarts`` is exhausted (or with supervision
    off, the default for library users), the terminal-crash backstop
    behaves exactly as before: every stream gets a clean ``aborted``
    event, ``/healthz`` flips 503, new work is refused.
    """

    def __init__(self, engine: Any, *, request_timeout: float | None = None,
                 idle_poll_s: float = 0.02,
                 metrics_max_samples: int = 100_000,
                 tick_deadline: float | None = None,
                 max_restarts: int = 0,
                 restart_backoff_s: float = 0.5,
                 restart_window_s: float = 300.0) -> None:
        self.engine = engine
        self.faults = getattr(engine, "faults", None)
        # which replica this runner is in a fleet (ReplicaRunner sets
        # it); the canonical request log tags every line with it
        self.replica_index = 0
        self.request_timeout = request_timeout
        self.idle_poll_s = idle_poll_s
        self.tick_deadline = tick_deadline
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s
        self.restart_window_s = restart_window_s
        # a server runs for weeks: bound the metrics sample lists
        # (counters stay exact; percentiles become a recent window) and
        # trim the scheduler's terminal-request ledgers below — nothing
        # in the HTTP layer reads them, and each entry pins its prompt
        # array and callback closures
        engine.metrics.max_samples = metrics_max_samples
        self._cmds: queue_mod.Queue = queue_mod.Queue()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._watchdog: threading.Thread | None = None
        # rid → (loop, asyncio.Queue); written by both threads, but each
        # rid is registered exactly once (submit) and removed exactly
        # once (engine thread, on the terminal event / reject)
        self._live: dict[int, tuple[asyncio.AbstractEventLoop,
                                    asyncio.Queue]] = {}
        # set when the tick thread dies terminally (supervision off or
        # restart budget exhausted): the server turns /healthz unhealthy
        # and rejects new work instead of silently wedging every stream
        self.crashed: str | None = None
        # exactly one rolling upgrade at a time (the ReplicaRunner
        # fleet guard, fleet-of-one spelling): a second concurrent
        # detach would supersede the first rebuild's generation and its
        # replay snapshot would never run anywhere
        self._upgrade_lock = threading.Lock()
        # -- supervision state (everything below guarded by _sup_lock) -
        # reentrant: _exec holds it across engine.submit/abort (so the
        # generation check is atomic with the engine call), and abort's
        # terminal events re-enter it through the _bridge callbacks
        self._sup_lock = threading.RLock()
        # commands a superseded thread had in hand when it noticed the
        # generation bump: drained BEFORE the queue by the live thread,
        # preserving arrival order (a tail re-put would reorder a submit
        # behind its own abort)
        self._handback: deque = deque()
        self._gen = 0  # engine generation; a restart increments it
        # lifetime restart count (the restarts_total metric); the BUDGET
        # is restart INTENSITY — deaths inside restart_window_s — so a
        # week-long server does not spend its whole allowance on
        # isolated, fully-recovered blips months apart
        self.restarts = 0
        self._recent_deaths: list[float] = []
        self.recovering = False
        self.recovery_latency_s: list[float] = []
        self._death_t: float | None = None
        self._beat = time.monotonic()
        # the current restart's backoff delay: the watchdog extends its
        # staleness budget by this much while recovering, so a wedged
        # REBUILT engine is still caught (just a little later) instead
        # of recovery muting the watchdog outright
        self._backoff_delay = 0.0
        # replay ledger: rid → {prompt, max_tokens, seed, deadline_at,
        # tokens (+ text deltas) delivered so far}, insertion-ordered
        # (original FIFO) — everything a restart needs to teacher-force
        # the stream back, and what a Last-Event-ID resume replays
        self._inflight: dict[int, dict] = {}
        # terminal output of DETACHED streams (finished while no client
        # was attached — journal-recovered requests above all), kept so
        # a late resume still gets its suffix + finish; bounded LRU
        self._resumable: dict[int, dict] = {}
        # CLAIMED terminals (a resume already replayed them once), kept
        # in a smaller LRU so a client whose first resume read tore on
        # the wire can retry instead of 404ing — the PR 9 single-shot
        # claim made bounded multi-read
        self._claimed: dict[int, dict] = {}
        # a planned weight swap's (params, version, share_from) for the
        # next rebuild (rolling upgrade); under _sup_lock, consumed by
        # _rebuild_and_replay on the new tick thread
        self._pending_weights: tuple | None = None
        # fleet hook (serve/replica.ReplicaRunner): called from
        # _terminal_crash with the in-flight replay list; returns the
        # rids a live peer adopted (those streams are NOT abort-flushed)
        self.on_terminal_crash = None
        # durable request journal (serve/journal.py): replay the
        # unterminated requests a dead PROCESS left behind — runs here
        # in the constructor, before any thread exists, so engine access
        # stays single-threaded
        self.journal = getattr(engine, "journal", None)
        self.journal_replayed = 0
        self.journal_resumed = 0
        if self.journal is not None:
            self._replay_journal()
        # past every replayed rid, PARKED ones included: a request
        # recovered terminal (finish_recovered) never touches the
        # engine's _next_id, and re-issuing its rid would let a fresh
        # request shadow the parked stream a client is about to resume
        self._rid = itertools.count(max(
            getattr(engine, "_next_id", 0),
            max(self._resumable, default=-1) + 1,
        ))

    # -- journal replay + stream resume --------------------------------
    def _replay_journal(self) -> None:
        """Teacher-force every unterminated journaled request back into
        the engine (the ``kill -9`` analogue of the supervised restart's
        in-process replay).  Delivered tokens are forced, the REMAINING
        deadline budget is resumed (the journal stores deadlines as wall
        time; expired budgets get swept on the first tick), and the
        ledger is rebuilt so a client can re-attach via Last-Event-ID."""
        if self.journal is None:
            return
        now_wall = time.time()
        clock_now = self.engine.clock()
        for rec in self.journal.replay():
            deadline_at = None
            if rec.get("deadline_wall") is not None:
                # remaining budget on the NEW engine clock; negative =
                # expired while the process was down → swept first tick
                deadline_at = clock_now + (rec["deadline_wall"] - now_wall)
            self._replay_one(0, dict(
                rec, deadline_at=deadline_at,
                deltas=self._replay_deltas(rec["tokens"]),
            ), require_live=False)
            self.journal_replayed += 1

    def _replay_deltas(self, tokens: list) -> list:
        """Per-token text deltas for a journaled token prefix (a fresh
        detokenizer replayed over the same ids yields the same deltas
        the original stream emitted) — what a resuming client's replayed
        suffix carries as text."""
        tok = getattr(self.engine, "tokenizer", None)
        if tok is None or not tokens:
            return [None] * len(tokens)
        from llm_np_cp_tpu.generate import IncrementalDetok

        detok = IncrementalDetok(tok)
        return [detok.push(t) for t in tokens]

    def _replay_one(self, gen: int, rec: dict, *,
                    require_live: bool = True) -> None:
        """Recover ONE ledger/journal record into ``self.engine`` —
        the per-request move shared by the supervised restart's replay,
        the constructor's journal replay, and a fleet peer adopting a
        dead replica's stream.  ``require_live`` is the supervised-
        restart discipline (a stream whose client went away while the
        engine was down is dropped); journal/fleet replays keep
        detached requests generating for a later resume."""
        rid = rec["rid"]
        if require_live and rid not in self._live:
            # the stream went away while we were down — drop its ledger
            # entry too, or it would be re-scanned (and leak) on every
            # future restart
            with self._sup_lock:
                if gen == self._gen:
                    self._inflight.pop(rid, None)
            return
        engine = self.engine
        tokens = rec["tokens"]
        stops = tuple(getattr(engine, "stop_tokens", ()) or ())
        done = len(tokens) >= rec["max_tokens"]
        stopped = bool(tokens) and tokens[-1] in stops
        if done or stopped:
            # fully generated pre-crash; only the finish event was
            # lost — deliver it without re-running anything
            self._finish_replayed(gen, rec, "stop" if stopped else "length")
            return
        cb, on_event = self._bridge(gen)
        try:
            req = engine.recover(
                rec["prompt"], rec["max_tokens"], request_id=rid,
                seed=rec["seed"], generated=tokens, callback=cb,
                on_event=on_event, deadline_at=rec.get("deadline_at"),
                trace_id=rec.get("trace"),
                lineage={
                    "replays": int(rec.get("replays", 0)) + 1,
                    "drains": int(rec.get("drains", 0)),
                },
                speculative=bool(rec.get("spec", False)),
                tenant=rec.get("tenant", "default"),
                weights_version=rec.get("wv"),
            )
        except Exception as e:  # noqa: BLE001 — per-request fate
            # a request the rebuilt pool cannot re-admit fails alone,
            # not the whole replay
            self._finish_replayed(gen, rec, "aborted")
            print(f"[serve] recovery dropped request {rid}: {e}",
                  file=sys.stderr)
        else:
            # the request now lives on THIS runner's replica (a drain
            # adoption moved it) — the canonical log tags it here
            req.extra["replica"] = self.replica_index
            with self._sup_lock:
                if gen == self._gen:
                    self._inflight[rid] = dict(
                        rec, tokens=list(tokens),
                        replays=int(rec.get("replays", 0)) + 1,
                        deltas=list(rec.get("deltas") or
                                    [None] * len(tokens)),
                    )

    def _finish_replayed(self, gen: int, rec: dict, reason: str) -> None:
        """Terminal bookkeeping for a replayed request that needs no
        re-run: deliver the lost finish to an attached stream, or park
        the full output for a late Last-Event-ID resume."""
        rid = rec["rid"]
        with self._sup_lock:
            if gen != self._gen:
                return
            self._inflight.pop(rid, None)
        tail = self.engine.finish_recovered(
            rec["prompt"], rec["max_tokens"], request_id=rid,
            generated=rec["tokens"], reason=reason,
            trace_id=rec.get("trace"),
            lineage={
                "replays": int(rec.get("replays", 0)) + 1,
                "drains": int(rec.get("drains", 0)),
            },
            tenant=rec.get("tenant", "default"),
            weights_version=rec.get("wv"),
        )
        if rid in self._live:
            self._push(rid, ("finish", reason, tail))
            self._live.pop(rid, None)
            self._claim_insert(rid, self._fin_record(rec, reason, tail))
        else:
            self._stash_resumable(rid, rec, reason, tail)

    @staticmethod
    def _fin_record(rec: dict, reason: str,
                    tail: str | None) -> dict:
        """The ONE parked/claimed terminal record shape (the resume
        wire format) — built here for ``_stash_resumable`` and both
        ``_claim_insert`` call sites, so a new field cannot be added to
        one copy and silently missed in another."""
        return {
            "tokens": list(rec["tokens"]),
            "deltas": list(rec.get("deltas") or
                           [None] * len(rec["tokens"])),
            "reason": reason,
            "tail": tail,
            # a late resume's response still carries the request's
            # ORIGINAL trace context
            "trace": rec.get("trace"),
        }

    def _stash_resumable(self, rid: int, rec: dict, reason: str,
                         tail: str | None) -> None:
        """Park a DETACHED stream's terminal output (bounded LRU): a
        client resuming after the finish still gets its journaled
        suffix + finish exactly once."""
        self._resumable[rid] = self._fin_record(rec, reason, tail)
        while len(self._resumable) > 512:
            self._resumable.pop(next(iter(self._resumable)))

    def resume(self, rid: int, last_idx: int,
               loop: asyncio.AbstractEventLoop, aq: asyncio.Queue) -> None:
        """Re-attach a dropped SSE stream: replay delivered tokens from
        index ``last_idx`` (the client's Last-Event-ID), then continue
        live.  The attach runs ON the engine thread, atomically between
        ticks, so the replayed suffix and the live continuation can
        neither race nor duplicate."""
        self._cmds.put(("attach", rid, last_idx, loop, aq))
        if self.crashed:
            # same crash race answer as submit(): nobody will process
            # the command (duplicates are harmless — the handler stops
            # at the first terminal event)
            aq.put_nowait(("gone",
                           f"engine tick thread crashed: {self.crashed}"))

    # -- event-loop side ----------------------------------------------
    def start(self) -> None:
        self._spawn_thread(self._gen)
        if self.tick_deadline is not None:
            self._watchdog = threading.Thread(
                target=self._watch, name="serve-engine-watchdog",
                daemon=True,
            )
            self._watchdog.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._cmds.put(("wake",))
        thread = self._thread
        if thread is not None:
            thread.join(timeout=timeout)
        if self._watchdog is not None:
            self._watchdog.join(timeout=1.0)
        if self.journal is not None:
            # drain's aborts already journaled their terminals; flush
            # them so a CLEAN shutdown leaves an empty replay set
            self.journal.close()

    @property
    def inflight(self) -> int:
        """Live bridged requests (accepted, not yet terminal)."""
        return len(self._live)

    @property
    def state(self) -> str:
        """``ok`` | ``degraded`` (restart in progress) | ``crashed``."""
        if self.crashed:
            return "crashed"
        return "degraded" if self.recovering else "ok"

    def serving_engines(self) -> list:
        """Engines whose ActionPolicy verdicts may govern admission —
        a crashed engine's tick thread can never RELEASE a shed flag,
        so its frozen verdict must not shed the server forever."""
        return [] if self.crashed else [self.engine]

    def next_rid(self) -> int:
        return next(self._rid)

    def submit(self, rid: int, payload: Any,
               loop: asyncio.AbstractEventLoop, aq: asyncio.Queue) -> None:
        self._live[rid] = (loop, aq)
        self._cmds.put(("submit", rid, payload))
        # crash race: if the tick thread died terminally between the
        # handler's pre-check and this registration, its backstop flush
        # may have already run — nobody will ever answer this command, so
        # answer it here (a duplicate event from the flush is harmless:
        # the handler stops at the first terminal one)
        if self.crashed and self._live.pop(rid, None) is not None:
            aq.put_nowait(("error",
                           f"engine tick thread crashed: {self.crashed}"))

    def abort(self, rid: int) -> None:
        self._cmds.put(("abort", rid))

    def abort_all(self) -> None:
        self._cmds.put(("abort_all",))

    # -- engine-thread side -------------------------------------------
    def _push(self, rid: int, item: tuple) -> None:
        ent = self._live.get(rid)
        if ent is None:
            return
        loop, aq = ent
        try:
            loop.call_soon_threadsafe(aq.put_nowait, item)
        except RuntimeError:
            # loop already closed (shutdown race) — nobody is reading
            self._live.pop(rid, None)

    def _bridge(self, gen: int) -> tuple:
        """Per-request engine callbacks for generation ``gen``.  The gen
        guard (under the supervision lock, so it is atomic with the
        restart's replay snapshot) makes a superseded engine mute: a hung
        thread that wakes mid-emit after a restart cannot append to the
        replay ledger or push duplicate tokens at a stream the rebuilt
        engine now owns."""

        def cb(req: Any, tok: int, delta: str | None) -> None:
            with self._sup_lock:
                if gen != self._gen:
                    return
                rec = self._inflight.get(req.req_id)
                if rec is not None:
                    rec["tokens"].append(int(tok))
                    deltas = rec.get("deltas")
                    if deltas is not None:
                        deltas.append(delta)
            self._push(req.req_id, ("token", int(tok), delta))

        def on_event(req: Any, event: str) -> None:
            if event not in TERMINAL_EVENTS:
                return
            with self._sup_lock:
                if gen != self._gen:
                    return
                rec = self._inflight.pop(req.req_id, None)
            if req.req_id not in self._live:
                # DETACHED terminal (a journal-recovered stream whose
                # client has not re-attached yet): park the output so a
                # late Last-Event-ID resume still completes
                if rec is not None:
                    self._stash_resumable(
                        req.req_id, rec, event,
                        req.extra.pop("final_text_delta", None))
                return
            tail = req.extra.pop("final_text_delta", None)
            self._push(req.req_id, ("finish", event, tail))
            self._live.pop(req.req_id, None)
            if rec is not None:
                # the DELIVERED terminal stays re-readable for a while
                # too: a client whose final read tore on the wire can
                # retry the whole stream from the claimed LRU
                self._claim_insert(
                    req.req_id, self._fin_record(rec, event, tail))

        return cb, on_event

    def _claim_insert(self, rid: int, fin: dict) -> None:
        """Park a terminal's full output in the CLAIMED LRU (bounded,
        most recent last): any recently finished stream can be
        re-replayed by a retrying client — the PR 9 single-shot claim,
        made bounded multi-read."""
        self._claimed.pop(rid, None)
        self._claimed[rid] = fin
        while len(self._claimed) > 64:
            self._claimed.pop(next(iter(self._claimed)))

    def _next_handback(self, gen: int) -> tuple | None:
        """Pop the next handed-back command — only for the LIVE
        generation (a stale thread popping and re-appending would rotate
        the hand-back order)."""
        with self._sup_lock:
            if gen == self._gen and self._handback:
                return self._handback.popleft()
        return None

    def _exec(self, cmd: tuple, gen: int) -> bool:
        """Execute one command for generation ``gen``.  The gen check and
        the engine call are ATOMIC under the supervision lock — a thread
        superseded between draining a command and executing it must not
        submit into an engine no thread will ever tick.  Returns False
        (after handing the command to the live generation, order
        preserved) when superseded."""
        with self._sup_lock:
            if gen != self._gen:
                self._handback.append(cmd)
                return False
            self._exec_inner(cmd, gen)
        return True

    def _exec_inner(self, cmd: tuple, gen: int) -> None:
        kind = cmd[0]
        if kind == "submit":
            _, rid, payload = cmd
            deadline = payload.timeout_s
            if self.request_timeout is not None:
                deadline = min(deadline or self.request_timeout,
                               self.request_timeout)
            cb, on_event = self._bridge(gen)
            try:
                req = self.engine.submit(
                    payload.prompt_ids, payload.max_tokens,
                    request_id=rid, seed=payload.seed, callback=cb,
                    on_event=on_event, deadline_s=deadline,
                    trace_id=getattr(payload, "trace_id", None),
                    speculative=getattr(payload, "speculative", False),
                    tenant=getattr(payload, "tenant", "default"),
                )
            except TenantThrottled as e:
                # same 429 + Retry-After contract as a full queue, but
                # the message names the tenant's cap, not the queue
                self._push(rid, ("rejected", 1, str(e)))
                self._live.pop(rid, None)
            except QueueFull:
                self._push(rid, ("rejected", 1))
                self._live.pop(rid, None)
            except ValueError as e:
                self._push(rid, ("error", str(e)))
                self._live.pop(rid, None)
            else:
                # route verdict + replica tag for the canonical request
                # log (the router filled payload.route_spilled)
                req.extra["replica"] = self.replica_index
                if getattr(payload, "route_spilled", False):
                    req.extra["spilled"] = True
                self._inflight[rid] = {
                    "rid": rid,
                    "prompt": payload.prompt_ids,
                    "max_tokens": payload.max_tokens,
                    "seed": payload.seed,
                    # the ABSOLUTE deadline on the engine clock (shared
                    # by clone_fresh rebuilds): recovery resumes the
                    # remaining budget instead of granting a fresh
                    # window per crash
                    "deadline_at": req.deadline,
                    # trace continuity + survival lineage: a restart
                    # replay or a drain-to-peer continues the SAME
                    # trace, with its replays/drains counters
                    "trace": req.extra.get("trace"),
                    "replays": 0,
                    "drains": 0,
                    # speculative opt-in: a restart replay resumes the
                    # same decoding mode (tokens identical either way)
                    "spec": bool(getattr(payload, "speculative", False)),
                    # the weight version that admitted this request — a
                    # restart replay or a drain-to-peer keeps reporting
                    # it, whatever weights the adopting engine runs
                    "wv": int(req.extra.get("weights_version", 0)),
                    # the tenant rides the recovery record too: a
                    # restart replay or drain-to-peer re-admits under
                    # the tenant that submitted the stream
                    "tenant": getattr(payload, "tenant", "default"),
                    "tokens": [],
                    # parallel text deltas, so a Last-Event-ID resume
                    # replays the exact text the stream would have
                    # carried
                    "deltas": [],
                }
                self._push(rid, ("accepted",))
        elif kind == "attach":
            self._exec_attach(cmd)
        elif kind == "recover":
            # a peer replica's drained stream (fleet adoption) — the
            # same teacher-forced move as a restart replay
            self._replay_one(gen, cmd[1], require_live=False)
        elif kind == "abort":
            self.engine.abort(cmd[1])
        elif kind == "abort_all":
            for rid in list(self._live):
                self.engine.abort(rid)

    def _exec_attach(self, cmd: tuple) -> None:
        """Attach a resuming client to a live or parked stream (on the
        engine thread — atomic with respect to token emission, so the
        replayed suffix and live continuation cannot interleave out of
        order).  Event ids are delivered-token indices: the client's
        Last-Event-ID is the count it HAS, so the replay starts there."""
        _, rid, last_idx, loop, aq = cmd
        rec = self._inflight.get(rid)
        fin = None
        if rec is None:
            fin = self._resumable.get(rid)
            if fin is None:
                # bounded multi-read: a terminal a resume already
                # claimed stays re-readable from the small claimed LRU,
                # so a client retrying after a flaky first read is not
                # 404'd (the PR 9 single-shot claim, loosened)
                fin = self._claimed.get(rid)
        src = rec if rec is not None else fin
        verdict = None
        if src is not None and rid in self._live:
            # already claimed: a duplicate resume (or a guessed id) must
            # not rebind the live bridge entry — that would hijack the
            # attached client's stream and strand it without a terminal
            verdict = ("gone",
                       f"request {rid} already has an attached stream")
        elif src is None:
            verdict = ("gone", f"unknown or expired request id {rid}")
        elif last_idx > len(src["tokens"]):
            if rec is not None:
                # the async-fsync window: the client can legitimately be
                # AHEAD of the journal (a watermark lost to the kill or
                # a dropped write batch) while the recovered request is
                # still regenerating its deterministic stream — tell the
                # client to retry shortly, not that the stream is gone
                verdict = ("busy",
                           f"request {rid} has regenerated "
                           f"{len(src['tokens'])} of the {last_idx} "
                           "tokens the client holds; retry shortly")
            else:
                verdict = ("gone",
                           f"Last-Event-ID {last_idx} is past the "
                           f"{len(src['tokens'])} tokens delivered for "
                           f"request {rid}")
        if verdict is not None:
            try:
                loop.call_soon_threadsafe(aq.put_nowait, verdict)
            except RuntimeError:
                pass
            return
        self._live[rid] = (loop, aq)
        self.journal_resumed += 1
        # the accepted verdict carries the stream's ORIGINAL trace id,
        # so the resumed response can emit the same traceparent the
        # first response did — a reconnect continues the trace
        self._push(rid, ("accepted", src.get("trace")))
        toks = src["tokens"][last_idx:]
        deltas = src.get("deltas") or []
        deltas = deltas[last_idx:]
        for i, tok in enumerate(toks):
            self._push(rid, ("token", int(tok),
                             deltas[i] if i < len(deltas) else None))
        if fin is not None:
            # the stream finished while detached: suffix + finish.  The
            # claim moves it to the bounded claimed-LRU (most recent
            # claim last) instead of discarding — a retry re-reads it
            # until the LRU evicts
            self._resumable.pop(rid, None)
            self._claim_insert(rid, fin)
            self._push(rid, ("finish", fin["reason"], fin["tail"]))
            self._live.pop(rid, None)

    # -- supervision ---------------------------------------------------
    def _spawn_thread(self, gen: int, *, delay: float = 0.0,
                      replay: list[dict] | None = None) -> None:
        self._thread = threading.Thread(
            target=self._run, args=(gen, delay, replay),
            name=f"serve-engine-tick-{gen}", daemon=True,
        )
        self._thread.start()

    def _run(self, gen: int, delay: float = 0.0,
             replay: list[dict] | None = None) -> None:
        try:
            if delay:
                time.sleep(delay)  # exponential backoff before rebuild
            if self._stop.is_set():
                return
            if gen == self._gen:
                self._beat = time.monotonic()  # backoff slept the clock off
            if replay is not None:
                self._rebuild_and_replay(gen, replay)
            self._loop(gen)
        except BaseException as e:  # noqa: BLE001 — supervisor boundary
            import traceback

            traceback.print_exc()
            self._on_engine_death(f"{type(e).__name__}: {e}", gen)

    def _loop(self, gen: int) -> None:
        engine = self.engine
        faults = self.faults
        while not self._stop.is_set() and gen == self._gen:
            cmd = self._next_handback(gen)
            if cmd is None:
                try:
                    block = not engine.scheduler.has_work
                    cmd = self._cmds.get(
                        block=block,
                        timeout=self.idle_poll_s if block else None,
                    )
                except queue_mod.Empty:
                    cmd = None
            while cmd is not None:
                if cmd[0] != "wake" and not self._exec(cmd, gen):
                    return  # superseded; _exec handed the command back
                cmd = self._next_handback(gen)
                if cmd is None:
                    try:
                        cmd = self._cmds.get_nowait()
                    except queue_mod.Empty:
                        cmd = None
            if self._stop.is_set() or gen != self._gen:
                break
            if engine.scheduler.has_work:
                if faults is not None:
                    hang = faults.trip("tick_hang")
                    if hang is not None:
                        time.sleep(hang)
                        if gen != self._gen:
                            return  # the watchdog already superseded us
                    if faults.trip("tick_crash") is not None:
                        from llm_np_cp_tpu.serve.faults import FaultInjected

                        raise FaultInjected("tick_crash")
                    if faults.trip("proc_kill") is not None:
                        # the kill -9 site: no drain, no flush, no
                        # atexit — exactly what the request journal's
                        # restart/resume path must survive
                        import os

                        print("[chaos] proc_kill: SIGKILL self",
                              file=sys.stderr, flush=True)
                        os.kill(os.getpid(), signal.SIGKILL)
                engine.step()
                # terminal requests already delivered their events
                # through the bridge — dropping them here keeps a
                # long-running server's memory flat
                engine.scheduler.finished.clear()
                engine.scheduler.aborted.clear()
            elif engine.actions is not None:
                # an idle server must still RELEASE auto-actions:
                # shed_load 503s the fresh work that would otherwise
                # produce the ticks on_tick releases through, so a
                # drained-idle server would shed forever once the
                # in-flight streams finished
                engine._actions_tick([])
            # tick heartbeat: the watchdog declares the engine hung when
            # this goes stale past tick_deadline (idle passes beat every
            # idle_poll_s, so only a stuck tick can starve it).  Gen
            # guard: a superseded hung thread that wakes here must not
            # freshen the heartbeat the NEW generation is judged by
            if gen == self._gen:
                self._beat = time.monotonic()
            if self.recovering:
                with self._sup_lock:
                    if gen == self._gen and self.recovering:
                        self.recovering = False
                        if self._death_t is not None:
                            self.recovery_latency_s.append(
                                time.monotonic() - self._death_t)
                            self._death_t = None

    def _rebuild_and_replay(self, gen: int, replay: list[dict]) -> None:
        """Fresh engine + pool (shared compiled steps), then resubmit
        every in-flight request with its delivered tokens teacher-forced.
        Runs ON the new tick thread, so engine access stays
        single-threaded."""
        old = self.engine
        tr = getattr(old, "tracer", None)
        t_restart = tr.now_us() if tr is not None else 0.0
        # Drop the dead engine's device slabs BEFORE the new pool is
        # allocated: restart peak memory must stay ~one pool, or an
        # HBM-sized production pool would OOM every rebuild and turn a
        # recoverable blip into a terminal 503.  A hung-but-alive thread
        # that later dispatches into the yanked pool fails in ITS
        # generation and is ignored.
        old.pool.pages = None
        with self._sup_lock:
            pend = self._pending_weights
        if pend is not None:
            # a planned weight swap rides the restart machinery: same
            # drain/replay/zombie-mute discipline, new params.  The
            # jitted steps take params as ARGUMENTS, so a same-shaped
            # swap reuses every warm compile; share_from (a peer that
            # already rolled) makes genuinely-new avals compile once
            # per fleet
            new_params, new_version, share_from = pend
            engine = old.clone_fresh(params=new_params,
                                     weights_version=new_version)
            if share_from is not None:
                engine.share_compiled_steps(share_from)
        else:
            engine = old.clone_fresh()
        # mute the zombie's counters: the clone shares the REAL metrics
        # object; a watchdog-superseded-but-alive thread finishing its
        # slow tick would otherwise keep writing on_token/on_finish into
        # it (engine internals have no gen guard — only the bridge does)
        # and double-count with the replay below.  The tracer is muted
        # the same way: a zombie tick must not interleave stale spans
        # into the timeline the rebuilt engine now owns — and so is the
        # journal: a zombie's stale watermarks must not corrupt the
        # delivered-count marks the rebuilt engine now advances.
        old.metrics = ServeMetrics(clock=old.clock)
        old.tracer = None
        old.journal = None
        # ...and the request log: a zombie's stale terminal lines must
        # not interleave with the rebuilt engine's canonical log — and
        # the sentinel: clone_fresh SHARES it (engine-thread-only
        # state), so a zombie tick observing concurrently with the
        # rebuilt engine would corrupt the EWMA baselines
        old.request_log = None
        old.sentinel = None
        # ...and the action policy: a zombie tick feeding stale signals
        # would corrupt the streak/burn state the rebuilt engine's
        # ticks now advance
        old.actions = None
        # ...and the host tier: the clone shares the REAL (process-
        # wide) tier; a zombie tick's late reclaim must not spill its
        # yanked pool's garbage into the shared host store, nor its
        # wall times pollute the breakeven measurements
        old.host_tier = None
        # ...and the tenant ledger: the clone shares the REAL ledger
        # (bills survive the restart); a zombie's stale terminals must
        # not double-charge a tenant the rebuilt engine re-runs
        old.tenants = None
        with self._sup_lock:
            if gen != self._gen:
                # superseded DURING the rebuild (it wedged long enough
                # for the watchdog to spawn a newer generation, which now
                # owns self.engine) — walk away without touching anything
                return
            self.engine = engine
            if pend is not None and self._pending_weights is pend:
                self._pending_weights = None

        for rec in replay:
            if gen != self._gen:
                return  # superseded mid-replay — the newer thread redoes it
            # an upgrade's leftover streams keep generating detached (a
            # journal-recovered client may attach later); a crash
            # restart's streams must have a live client
            self._replay_one(
                gen, rec,
                require_live=not rec.pop("detached_ok", False),
            )
            if gen == self._gen:
                self._beat = time.monotonic()
        if tr is not None:
            tr.complete("restart", t_restart, cat="supervisor", args={
                "gen": gen, "replayed": len(replay),
            })

    # -- planned lifecycle (rolling weight swap) -----------------------
    def detach_inflight(self) -> list[dict]:
        """Supersede the live tick generation and hand back the
        in-flight replay snapshot — the first half of a PLANNED swap
        (upgrade or removal), sharing the crash path's discipline: the
        old thread goes zombie (gen bump + handback), the snapshot is
        what peers adopt (drain) or the rebuilt engine replays."""
        with self._sup_lock:
            self._gen += 1
            self.recovering = True
            self._beat = time.monotonic()
            # the rebuild includes a params device_put — give the
            # watchdog the same grace a backoff restart gets
            self._backoff_delay = max(self._backoff_delay, 10.0)
            replay = [dict(rec, tokens=list(rec["tokens"]),
                           deltas=list(rec.get("deltas") or ()))
                      for rec in self._inflight.values()]
            self._inflight.clear()
        self._cmds.put(("wake",))  # unblock an idle superseded thread
        return replay

    def rebuild_upgraded(self, params: Any, version: int,
                         replay: list[dict], *,
                         share_from: Any = None) -> None:
        """Second half of the swap: spawn the new generation's tick
        thread, which rebuilds via ``clone_fresh(params=...)`` and
        replays ``replay`` teacher-forced (token-identical).
        ``share_from`` is a peer engine that already rolled — its
        jitted callables are adopted so new-weight avals compile once
        per FLEET.  Caller ran ``detach_inflight`` first."""
        with self._sup_lock:
            if self._stop.is_set():
                raise RuntimeError("runner is stopped")
            self._pending_weights = (params, int(version), share_from)
            new_gen = self._gen
        self._spawn_thread(new_gen, replay=replay)

    def await_recovered(self, timeout_s: float = 300.0) -> None:
        """Block until the rebuilt engine completes its first loop pass
        (``recovering`` clears) — the roll moves to the next replica
        only once this one is serving again."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.crashed:
                raise RuntimeError(
                    f"replica crashed during upgrade: {self.crashed}"
                )
            if not self.recovering:
                return
            time.sleep(0.01)
        raise TimeoutError(
            f"upgrade rebuild did not complete within {timeout_s:g}s"
        )

    def rolling_upgrade(self, params_fn: Any, *,
                        version: int | None = None,
                        timeout_s: float = 300.0) -> dict:
        """The fleet-of-one roll (``POST /admin/upgrade`` on a
        single-replica server): no peer to drain to, so in-flight
        streams are replayed IN PLACE on the rebuilt engine —
        teacher-forced, so delivered tokens never change; tokens still
        to come are sampled by the new weights (with one replica there
        is no same-version peer to finish them on, and the request's
        version tag records its admission version either way)."""
        from llm_np_cp_tpu.serve.lifecycle import load_upgrade_params

        if not self._upgrade_lock.acquire(blocking=False):
            raise RuntimeError("a rolling upgrade is already in progress")
        try:
            if self.crashed:
                raise RuntimeError(
                    f"cannot upgrade a crashed server: {self.crashed}"
                )
            params = load_upgrade_params(
                params_fn, replica=self.replica_index,
                faults=self.faults, metrics=self.engine.metrics,
                rolled=[], version=version,
            )
            if version is None:
                version = getattr(self.engine, "weights_version", 0) + 1
            replay = [dict(rec, detached_ok=True)
                      for rec in self.detach_inflight()]
            self.rebuild_upgraded(params, version, replay)
            try:
                self.await_recovered(timeout_s)
            except TimeoutError as e:
                # surface the same clean abort shape as a checkpoint
                # failure — the admin handler turns it into a 500
                # instead of a dropped connection; the supervisor
                # keeps rebuilding
                from llm_np_cp_tpu.serve.lifecycle import UpgradeAborted

                raise UpgradeAborted(
                    f"replica {self.replica_index} rebuild timed out: "
                    f"{e}", rolled=[], version=version,
                ) from e
            self.engine.metrics.on_lifecycle_action("upgrade_replica")
            return {"rolled": [self.replica_index], "version": version}
        finally:
            self._upgrade_lock.release()

    def _on_engine_death(self, reason: str, gen: int) -> None:
        """Crash/hang handler (from the dying thread or the watchdog):
        either schedule a supervised restart or go terminally dark."""
        now = time.monotonic()
        with self._sup_lock:
            if gen != self._gen:
                return  # a superseded thread died late — already handled
            # budget = restart intensity, not lifetime total: only
            # deaths within the window count (a crash LOOP exhausts it;
            # isolated recovered blips don't), and the backoff exponent
            # follows the same count so it too is per-incident
            self._recent_deaths = [
                t for t in self._recent_deaths
                if now - t < self.restart_window_s
            ]
            if self._stop.is_set() \
                    or len(self._recent_deaths) >= self.max_restarts:
                self._terminal_crash(reason)
                return
            self._recent_deaths.append(now)
            self.restarts += 1
            self._gen += 1
            self.recovering = True
            if self._death_t is None:
                self._death_t = now
            delay = min(
                self.restart_backoff_s
                * (2 ** (len(self._recent_deaths) - 1)),
                10.0,
            )
            self._backoff_delay = delay
            self._beat = time.monotonic()  # restart clock starts now
            replay = [dict(rec, tokens=list(rec["tokens"]))
                      for rec in self._inflight.values()]
            new_gen = self._gen
        tr = getattr(self.engine, "tracer", None)
        if tr is not None:
            tr.instant("engine-death", cat="supervisor", args={
                "reason": reason, "gen": gen, "restart": new_gen,
            })
        print(f"[serve] engine death ({reason}); supervised restart "
              f"{len(replay)} in-flight to replay, "
              f"{len(self._recent_deaths)}/{self.max_restarts} deaths in "
              f"window, backoff {delay:.2f}s", file=sys.stderr)
        self._spawn_thread(new_gen, delay=delay, replay=replay)

    def _terminal_crash(self, reason: str) -> None:
        """The pre-supervision backstop (caller holds ``_sup_lock``): a
        dead tick thread must not wedge the server — every in-flight
        stream gets a terminal event (clients see a clean end instead of
        hanging until their own timeouts), /healthz flips unhealthy, and
        new submits are refused."""
        self.crashed = reason
        tr = getattr(self.engine, "tracer", None)
        if tr is not None:
            tr.instant("engine-terminal-crash", cat="supervisor",
                       args={"reason": reason})
        # supersede a HUNG (still running) thread too: without the gen
        # bump it would wake and keep ticking — a zombie generation
        # burning the device for already-flushed streams
        self._gen += 1
        self.recovering = False
        # fleet drain (serve/replica.ReplicaRunner): a live peer can
        # ADOPT this runner's unterminated streams — those clients see a
        # pause and then the peer's token-identical continuation instead
        # of an abort
        adopted: set[int] = set()
        hook = self.on_terminal_crash
        if hook is not None and self._inflight:
            replay = [dict(rec, tokens=list(rec["tokens"]),
                           deltas=list(rec.get("deltas") or ()))
                      for rec in self._inflight.values()]
            adopted = hook(replay)
        for rid in list(self._live):
            if rid in adopted:
                continue  # a peer now owns this stream's bridge entry
            self._push(rid, ("finish", "aborted", None))
            self._live.pop(rid, None)
        # the flush IS these requests' terminal: journal it (the writer
        # thread outlives the tick thread), or the next process start
        # would replay streams whose clients already saw 'aborted' —
        # generating for nobody and inflating journal_replayed_total
        journal = self.journal
        if journal is not None:
            for rid in self._inflight:
                if rid not in adopted:
                    journal.terminal(rid, "aborted")
        self._inflight.clear()

    def _watch(self) -> None:
        """Watchdog: declare the engine hung when the tick heartbeat goes
        stale past ``tick_deadline`` (a tick stuck in a device call or an
        injected hang), and hand it to the death handler.  While a
        restart is in progress the staleness budget stretches by that
        restart's backoff delay — recovery never MUTES the watchdog, so
        a rebuilt engine that wedges in its replay or first tick is
        itself caught and handed back to the supervisor."""
        assert self.tick_deadline is not None
        interval = max(self.tick_deadline / 4.0, 0.01)
        while not self._stop.is_set() and not self.crashed:
            time.sleep(interval)
            with self._sup_lock:
                gen = self._gen
                beat = self._beat
                grace = self._backoff_delay if self.recovering else 0.0
            stale = time.monotonic() - beat
            if stale > self.tick_deadline + grace:
                self._on_engine_death(
                    f"engine tick hung ({stale:.2f}s > tick-deadline "
                    f"{self.tick_deadline:g}s + {grace:g}s restart grace)",
                    gen,
                )


class HttpServer:
    """The asyncio front: routing, SSE streaming, drain shutdown."""

    def __init__(
        self,
        engine: Any,
        *,
        model_id: str,
        tokenizer: Any = None,
        request_timeout: float | None = None,
        drain_timeout: float = 30.0,
        default_max_tokens: int = 16,
        max_tokens_cap: int | None = None,
        tick_deadline: float | None = None,
        max_restarts: int = 0,
        restart_backoff_s: float = 0.5,
        restart_window_s: float = 300.0,
        runner: Any = None,
        upgrade_loader: Any = None,
    ) -> None:
        self.engine = engine
        self.model_id = model_id
        # rolling weight swaps (POST /admin/upgrade): the loader maps
        # the request body to fresh params (the serve CLI wires a
        # checkpoint reload); None = the endpoint 404s with a hint.
        # One admin mutation at a time — a roll and a scale racing
        # would drain the same peers out from under each other
        self.upgrade_loader = upgrade_loader
        self._admin_lock = threading.Lock()
        self.tokenizer = tokenizer if tokenizer is not None \
            else getattr(engine, "tokenizer", None)
        self.drain_timeout = drain_timeout
        self.default_max_tokens = default_max_tokens
        self.max_tokens_cap = max_tokens_cap
        # ``runner`` injects a prebuilt fleet (serve/replica.ReplicaRunner
        # — N supervised engine replicas behind prefix-affinity routing);
        # default is the single-engine runner, exactly as before
        self.runner = runner if runner is not None else EngineRunner(
            engine, request_timeout=request_timeout,
            tick_deadline=tick_deadline, max_restarts=max_restarts,
            restart_backoff_s=restart_backoff_s,
            restart_window_s=restart_window_s,
        )
        self.draining = False
        self.host: str | None = None
        self.port: int | None = None
        self._server: asyncio.AbstractServer | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._done: asyncio.Event | None = None
        self._drain_task: asyncio.Task | None = None
        self._conn_tasks: set[asyncio.Task] = set()
        self._signals: list[int] = []

    # ------------------------------------------------------------------
    async def start(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._loop = asyncio.get_running_loop()
        self._done = asyncio.Event()
        self.runner.start()
        self._server = await asyncio.start_server(self._on_conn, host, port)
        sock = self._server.sockets[0]
        self.host, self.port = sock.getsockname()[:2]
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._loop.add_signal_handler(sig, self.begin_drain)
                self._signals.append(sig)
            except (NotImplementedError, RuntimeError, ValueError):
                # not the main thread (CLI smoke tests run the server in
                # a worker thread) or an embedded loop — drain stays
                # reachable programmatically
                break

    def begin_drain(self) -> None:
        """Idempotent shutdown trigger — the SIGTERM handler and the
        test hook both land here."""
        if self._drain_task is None and self._loop is not None:
            self._drain_task = self._loop.create_task(self._drain())

    async def _drain(self) -> None:
        self.draining = True
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.drain_timeout
        while self.runner.inflight and loop.time() < deadline:
            await asyncio.sleep(0.02)
        if self.runner.inflight:
            self.runner.abort_all()
            grace = loop.time() + 5.0
            while self.runner.inflight and loop.time() < grace:
                await asyncio.sleep(0.02)
        # every stream got its terminal event; give the handlers a
        # bounded window to flush their last bytes BEFORE the socket
        # closes (the acceptance criterion for drain)
        flush_deadline = loop.time() + 5.0
        while self._conn_tasks and loop.time() < flush_deadline:
            await asyncio.sleep(0.02)
        assert self._server is not None
        self._server.close()
        await self._server.wait_closed()
        for sig in self._signals:
            with contextlib.suppress(Exception):
                self._loop.remove_signal_handler(sig)  # type: ignore[union-attr]
        self.runner.stop()
        assert self._done is not None
        self._done.set()

    async def serve_until_shutdown(self) -> None:
        assert self._done is not None, "call start() first"
        await self._done.wait()

    # ------------------------------------------------------------------
    @property
    def tracer(self) -> Any:
        """The live engine's trace recorder (rebinds across supervised
        restarts — the recorder object itself is shared), or None."""
        return getattr(self.runner.engine, "tracer", None)

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
        # request spans start AT SOCKET ACCEPT: time spent reading and
        # parsing the request is part of what the client experiences,
        # and must be separable from engine queue wait in the trace.
        # -1 sentinel (engine.step discipline): if the tracer appears
        # only AFTER accept (the supervised-restart mute window), the
        # span must not start at the trace epoch
        tracer = self.tracer
        t_accept = tracer.now_us() if tracer is not None else -1.0
        try:
            await self._handle(reader, writer, t_accept)
        except (ConnectionResetError, BrokenPipeError, asyncio.TimeoutError):
            pass
        finally:
            if task is not None:
                self._conn_tasks.discard(task)
            with contextlib.suppress(Exception):
                writer.close()
                await writer.wait_closed()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter,
                      t_accept: float = -1.0) -> None:
        try:
            method, path, headers, body = await asyncio.wait_for(
                self._read_request(reader), timeout=30.0,
            )
        except HTTPError as e:
            await self._respond_error(writer, e)
            return
        except (asyncio.IncompleteReadError, ValueError,
                asyncio.TimeoutError):
            return  # torn/oversized request line — nothing to answer
        if method == "GET" and path == "/healthz":
            crashed = self.runner.crashed
            # degraded (supervised restart in progress) stays 200: the
            # server still accepts and queues work, so a load balancer
            # must not eject it mid-recovery — that would turn a blip
            # back into an outage
            status = 503 if (self.draining or crashed) else 200
            state = ("crashed" if crashed
                     else "draining" if self.draining
                     else self.runner.state)
            payload = {
                "status": state, "model": self.model_id,
                "restarts": self.runner.restarts,
                "weights_version": getattr(
                    self.runner.engine, "weights_version", 0),
            }
            mesh = getattr(self.runner.engine, "mesh_desc", None)
            if mesh:
                payload["mesh"] = mesh
            replica_states = getattr(self.runner, "replica_states", None)
            if replica_states is not None:
                payload["replicas"] = replica_states()
            if crashed:
                payload["error"] = crashed
            await self._respond(writer, status, json.dumps(payload).encode())
        elif method == "GET" and path == "/metrics":
            await self._respond(
                writer, 200, self._render_metrics().encode(),
                content_type="text/plain; version=0.0.4; charset=utf-8",
            )
        elif method == "GET" and path == "/debug/slo":
            await self._respond_slo(writer)
        elif method == "GET" and path == "/debug/tenants":
            await self._respond_tenants(writer)
        elif method == "GET" and path == "/debug/trace":
            tracer = self.tracer
            if tracer is None:
                await self._respond_error(writer, HTTPError(
                    404, "tracing is off; start the server with "
                    "--trace-ring N (and/or --trace-out PATH)"))
            else:
                # point-in-time ring-buffer snapshot, loadable straight
                # into ui.perfetto.dev.  Serialized OFF the event loop:
                # a full ring is hundreds of thousands of dicts, and
                # json.dumps-ing them inline would stall every live SSE
                # stream — the instrument must not perturb what it
                # measures
                body = await asyncio.get_running_loop().run_in_executor(
                    None, lambda: json.dumps(tracer.to_dict()).encode())
                await self._respond(writer, 200, body)
        elif path == "/admin/upgrade":
            if method != "POST":
                await self._respond_error(writer, HTTPError(
                    405, "use POST for /admin/upgrade"))
            else:
                await self._admin_upgrade(writer, body)
        elif path == "/admin/scale":
            if method != "POST":
                await self._respond_error(writer, HTTPError(
                    405, "use POST for /admin/scale"))
            else:
                await self._admin_scale(writer, body)
        elif path == "/v1/completions":
            if method != "POST":
                await self._respond_error(writer, HTTPError(
                    405, "use POST for /v1/completions"))
            else:
                await self._completions(reader, writer, body, headers,
                                        t_accept)
        elif path.startswith("/v1/completions/"):
            # stream resume by id: GET /v1/completions/cmpl-N with a
            # Last-Event-ID header replays the journaled suffix over
            # SSE and continues live (serve/journal.py)
            if method != "GET":
                await self._respond_error(writer, HTTPError(
                    405, "use GET to resume a completion stream"))
                return
            try:
                rid = parse_completion_rid(path.rsplit("/", 1)[1])
                last_idx = parse_last_event_id(
                    headers.get("last-event-id"))
            except HTTPError as e:
                await self._respond_error(writer, e)
                return
            await self._resume(reader, writer, rid, last_idx,
                               self.model_id, t_accept)
        else:
            await self._respond_error(writer, HTTPError(
                404, f"no route for {method} {path}"))

    async def _read_request(
        self, reader: asyncio.StreamReader,
    ) -> tuple[str, str, dict[str, str], bytes]:
        line = await reader.readline()
        if not line:
            raise asyncio.IncompleteReadError(b"", None)
        parts = line.decode("latin-1").split()
        if len(parts) != 3:
            raise HTTPError(400, "malformed request line")
        method, path = parts[0].upper(), parts[1]
        headers: dict[str, str] = {}
        while True:
            hline = await reader.readline()
            if hline in (b"\r\n", b"\n", b""):
                break
            key, _, value = hline.decode("latin-1").partition(":")
            headers[key.strip().lower()] = value.strip()
        try:
            n = int(headers.get("content-length", "0"))
        except ValueError as e:
            raise HTTPError(400, "bad Content-Length") from e
        if n > MAX_BODY_BYTES:
            raise HTTPError(413, f"body exceeds {MAX_BODY_BYTES} bytes")
        body = await reader.readexactly(n) if n else b""
        return method, path, headers, body

    def _render_metrics(self) -> str:
        # durable-journal observables (zero when journaling is off):
        # what the restart/resume acceptance checks and an operator's
        # alerting read off the scrape
        journal_gauges = {
            "journal_replayed_total": float(
                getattr(self.runner, "journal_replayed", 0)),
            "journal_resumed_total": float(
                getattr(self.runner, "journal_resumed", 0)),
        }
        # OTLP span export (serve/otel.py): shipped/dropped counters so
        # a silent collector outage is visible on the scrape
        otel = getattr(self.tracer, "otel", None)
        if otel is not None:
            ostats = otel.stats()
            journal_gauges.update({
                "otlp_spans_exported_total": float(ostats["spans"]),
                "otlp_spans_dropped_total": float(ostats["dropped"]),
                "otlp_export_errors_total": float(
                    ostats["export_errors"]),
            })
        journal = getattr(self.runner, "journal", None)
        if journal is not None:
            jstats = journal.stats()
            journal_gauges.update({
                "journal_records_total": float(jstats["records"]),
                "journal_fsync_p99_s": jstats["fsync_p99_s"],
                "journal_write_errors_total": float(
                    jstats["write_errors"] + jstats["fsync_errors"]),
                "journal_epoch": float(jstats["epoch"]),
            })
        render = getattr(self.runner, "render_metrics", None)
        if render is not None:
            # replica fleet: per-replica series with replica labels +
            # router counters (serve/replica.ReplicaRunner)
            return render(extra_gauges={
                "draining": 1.0 if self.draining else 0.0,
                **journal_gauges,
            })
        # the runner's engine, NOT self.engine: a supervised restart
        # rebinds it, and a scrape must see the live pool/scheduler
        engine = self.runner.engine
        stats = engine.pool.stats()
        faults = self.runner.faults
        recov = self.runner.recovery_latency_s
        wv = getattr(engine, "weights_version", 0)
        text = engine.metrics.prometheus(
            # the version label appears once an upgrade rolled (wv > 0)
            # — pre-upgrade series keep their exact labelsets
            const_labels={"version": str(wv)} if wv else None,
            extra_gauges={
            "weights_version": float(wv),
            "pool_blocks_free": stats["free"],
            "pool_blocks_request_held": stats["request_held"],
            "pool_blocks_cache_only": stats["cache_only"],
            "pool_kv_bytes_shard": stats["kv_bytes_shard"],
            "pool_kv_shards": stats["kv_shards"],
            "inflight_streams": self.runner.inflight,
            "queue_depth_live": engine.scheduler.queue_depth,
            "draining": 1.0 if self.draining else 0.0,
            # supervision observables: the chaos e2e (and an operator's
            # alerting) read recovery off this scrape
            "restarts_total": self.runner.restarts,
            "faults_injected_total": (
                faults.injected_total if faults is not None else 0.0
            ),
            "degraded": 1.0 if self.runner.state == "degraded" else 0.0,
            "recovery_latency_s_last": recov[-1] if recov else 0.0,
            "decode_impl_degraded": (
                1.0 if engine.decode_degraded else 0.0
            ),
            **journal_gauges,
        })
        tenants = getattr(engine, "tenants", None)
        if tenants is not None:
            # tenant-labeled series (serve/tenants.py) ride the same
            # scrape; the ledger bounds its own label cardinality
            text += tenants.prometheus(
                const_labels={"version": str(wv)} if wv else None,
            )
        return text

    async def _respond_slo(self, writer: asyncio.StreamWriter) -> None:
        """``GET /debug/slo``: the fleet's SLO accounting as one JSON —
        attainment, goodput, burn rates, summed across replicas with a
        per-replica breakdown.  404 + hint when no policy is attached
        (the ``/debug/trace`` discipline)."""
        from llm_np_cp_tpu.serve.slo import aggregate_slo

        replicas = getattr(self.runner, "replicas", None)
        runners = replicas if replicas is not None else [self.runner]
        trackers = [
            getattr(r.engine.metrics, "slo", None) for r in runners
        ]
        if not any(t is not None for t in trackers):
            await self._respond_error(writer, HTTPError(
                404, "SLO accounting is off; start the server with "
                "--slo-ttft/--slo-tpot"))
            return
        body = aggregate_slo(trackers)
        if replicas is not None:
            body["replicas"] = [
                t.snapshot() if t is not None else None for t in trackers
            ]
        await self._respond(writer, 200, json.dumps(body).encode())

    async def _respond_tenants(self, writer: asyncio.StreamWriter) -> None:
        """``GET /debug/tenants``: the fleet's per-tenant accounting as
        one JSON — requests, tokens, device-cost attribution, SLO
        detail, throttles — summed across replicas with a per-replica
        breakdown.  404 + hint when no ledger is attached (the
        ``/debug/slo`` discipline)."""
        from llm_np_cp_tpu.serve.tenants import aggregate_tenants

        replicas = getattr(self.runner, "replicas", None)
        runners = replicas if replicas is not None else [self.runner]
        ledgers = [
            getattr(r.engine, "tenants", None) for r in runners
        ]
        if not any(t is not None for t in ledgers):
            await self._respond_error(writer, HTTPError(
                404, "tenant accounting is off; start the server with "
                "--tenants"))
            return
        body = aggregate_tenants(ledgers)
        if replicas is not None:
            body["replicas"] = [
                t.snapshot() if t is not None else None for t in ledgers
            ]
        await self._respond(writer, 200, json.dumps(body).encode())

    # -- fleet lifecycle admin (serve/lifecycle.py) --------------------
    async def _admin_upgrade(self, writer: asyncio.StreamWriter,
                             body: bytes) -> None:
        """``POST /admin/upgrade``: roll the fleet onto fresh weights,
        one replica at a time, zero dropped streams.  Body (optional
        JSON): ``{"model": <checkpoint for the loader>, "version": N}``.
        Responds after the roll with ``{"rolled": [...], "version"}``;
        409 when a roll is already in progress, 500 with the rolled
        prefix when the roll aborted (checkpoint failure — the fleet
        keeps serving, mixed-version)."""
        from llm_np_cp_tpu.serve.lifecycle import UpgradeAborted

        if self.upgrade_loader is None:
            await self._respond_error(writer, HTTPError(
                404, "no upgrade loader configured; the serve CLI "
                "wires one (POST /admin/upgrade)"))
            return
        try:
            data = json.loads(body) if body else {}
            if not isinstance(data, dict):
                raise ValueError("body must be a JSON object")
        except ValueError as e:
            await self._respond_error(writer, HTTPError(
                400, f"bad JSON body: {e}"))
            return
        version = data.get("version")
        if version is not None and (
            not isinstance(version, int) or isinstance(version, bool)
            or version < 1
        ):
            await self._respond_error(writer, HTTPError(
                400, f"version must be a positive integer, "
                f"got {version!r}"))
            return
        if not self._admin_lock.acquire(blocking=False):
            await self._respond_error(writer, HTTPError(
                409, "an admin operation is already in progress"))
            return
        loader = self.upgrade_loader
        loop = asyncio.get_running_loop()
        try:
            result = await loop.run_in_executor(
                None,
                lambda: self.runner.rolling_upgrade(
                    lambda: loader(data), version=version,
                ),
            )
        except UpgradeAborted as e:
            await self._respond(writer, 500, json.dumps({
                "error": str(e), "rolled": e.rolled,
            }).encode())
            return
        except RuntimeError as e:
            # only a concurrent roll is a Conflict; a crashed/stopped
            # runner or an empty fleet is the server's unavailability,
            # and a 409 would invite the client to retry-until-done
            # against a fleet that can never finish a roll
            status = 409 if "in progress" in str(e) else 503
            await self._respond_error(writer, HTTPError(status, str(e)))
            return
        finally:
            self._admin_lock.release()
        await self._respond(writer, 200, json.dumps(result).encode())

    async def _admin_scale(self, writer: asyncio.StreamWriter,
                           body: bytes) -> None:
        """``POST /admin/scale`` ``{"replicas": N}``: elastic DP for
        the HTTP fleet — grow with warmed share-nothing clones, shrink
        with drain-to-peer removals."""
        if getattr(self.runner, "add_replica", None) is None:
            await self._respond_error(writer, HTTPError(
                400, "single-engine server cannot scale; start with "
                "--replicas N"))
            return
        try:
            data = json.loads(body) if body else {}
            n = data["replicas"]
            if not isinstance(n, int) or isinstance(n, bool) \
                    or not (1 <= n <= 64):
                raise ValueError(f"replicas must be in [1, 64], got {n!r}")
        except (KeyError, TypeError, ValueError) as e:
            await self._respond_error(writer, HTTPError(
                400, f'bad body (want {{"replicas": N}}): {e}'))
            return
        if not self._admin_lock.acquire(blocking=False):
            await self._respond_error(writer, HTTPError(
                409, "an admin operation is already in progress"))
            return

        def apply() -> tuple[list[int], list[int]]:
            added: list[int] = []
            removed: list[int] = []
            while self.runner.active_replicas() < n:
                added.append(self.runner.add_replica())
            while self.runner.active_replicas() > n:
                removed.append(self.runner.remove_replica())
            return added, removed

        loop = asyncio.get_running_loop()
        try:
            added, removed = await loop.run_in_executor(None, apply)
        except RuntimeError as e:
            await self._respond_error(writer, HTTPError(400, str(e)))
            return
        finally:
            self._admin_lock.release()
        await self._respond(writer, 200, json.dumps({
            "replicas": self.runner.active_replicas(),
            "added": added, "removed": removed,
            "states": self.runner.replica_states(),
        }).encode())

    def _shed_retry_after(self) -> float | None:
        """503-first load shedding: the max Retry-After across SERVING
        replicas whose ActionPolicy is shedding, or None when admission
        is open.  Only serving replicas vote (``serving_engines`` —
        removed/crashed replicas' tick threads can never release a shed
        flag, and a frozen verdict must not shed the fleet forever).
        Racy boolean reads by design (like the routing load reads) —
        one request admitted a tick early or late is noise."""
        worst = None
        for engine in self.runner.serving_engines():
            acts = getattr(engine, "actions", None)
            if acts is not None and acts.shedding:
                ra = acts.retry_after()
                worst = ra if worst is None else max(worst, ra)
        return worst

    # ------------------------------------------------------------------
    async def _completions(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter,
                           body: bytes, headers: dict[str, str],
                           t_accept: float = -1.0) -> None:
        if self.draining or self.runner.crashed:
            msg = ("engine tick thread crashed: " + self.runner.crashed
                   if self.runner.crashed
                   else "server is draining for shutdown")
            await self._respond_error(writer, HTTPError(
                503, msg, etype="server_error",
                headers=(("Retry-After", "1"),),
            ))
            return
        faults = self.runner.faults
        if faults is not None:
            retry_after = faults.trip("http_429")
            if retry_after is not None:
                # injected transient reject: exercises client
                # retry/backoff without having to saturate the queue
                await self._respond_error(writer, HTTPError(
                    429, "chaos: injected transient reject",
                    etype="rate_limit_error",
                    headers=(("Retry-After", f"{max(retry_after, 0):g}"),),
                ))
                return
        try:
            resume = parse_resume_request(
                body, headers, model_id=self.model_id)
            if resume is not None:
                # re-POST with the original request id: the resume
                # protocol's POST spelling (GET /v1/completions/<id> is
                # the other)
                rid, last_idx, echo_model = resume
                await self._resume(reader, writer, rid, last_idx,
                                   echo_model, t_accept)
                return
            # 503-first load shedding (serve/lifecycle.ActionPolicy):
            # when the SLO error budget burns past threshold, FRESH
            # admissions shed at the door with a burn-scaled
            # Retry-After — resumes above attach to work already done
            # and always pass
            shed = self._shed_retry_after()
            if shed is not None:
                await self._respond_error(writer, HTTPError(
                    503, "load shedding: SLO error budget is burning "
                    "past threshold; retry later",
                    etype="server_error",
                    headers=(("Retry-After", f"{shed:g}"),),
                ))
                return
            payload = parse_completion_request(
                body, model_id=self.model_id, tokenizer=self.tokenizer,
                default_max_tokens=self.default_max_tokens,
                max_tokens_cap=self.max_tokens_cap,
                header_tenant=headers.get("x-tenant-id"),
            )
        except HTTPError as e:
            await self._respond_error(writer, e)
            return

        # W3C trace context: continue the caller's trace or start one —
        # every request has ONE trace id from here through routing,
        # journal replay, and drain-to-peer (a malformed header means a
        # fresh trace, never a 400)
        ctx = parse_traceparent(headers.get("traceparent"))
        payload.trace_id = ctx[0] if ctx is not None else gen_trace_id()

        loop = asyncio.get_running_loop()
        aq: asyncio.Queue = asyncio.Queue()
        rid = self.runner.next_rid()
        tracer = self.tracer
        if tracer is not None:
            # the http bracket span: accept → response done, enclosing
            # the engine's queued/prefill/decode spans on the same
            # track.  t_accept < 0 means the tracer appeared after
            # accept (restart mute window) — begin at now, not at the
            # trace epoch
            tracer.async_begin(rid, "http",
                               ts_us=t_accept if t_accept >= 0.0 else None,
                               args={"stream": bool(payload.stream),
                                     "trace": payload.trace_id})
        try:
            await self._completions_inner(
                reader, writer, payload, rid, loop, aq)
        finally:
            if tracer is not None:
                tracer.async_end(rid, "http")

    async def _completions_inner(self, reader, writer, payload, rid,
                                 loop, aq) -> None:
        self.runner.submit(rid, payload, loop, aq)
        verdict = await aq.get()
        if verdict[0] == "rejected":
            msg = (verdict[2] + "; retry later" if len(verdict) > 2
                   else "request queue is full; retry later")
            await self._respond_error(writer, HTTPError(
                429, msg,
                etype="rate_limit_error",
                headers=(("Retry-After", str(verdict[1])),),
            ))
            return
        if verdict[0] == "error":
            await self._respond_error(writer, HTTPError(400, verdict[1]))
            return
        if verdict[0] == "finish":
            # terminal before acceptance: only the tick-thread crash
            # backstop produces this — the request never ran
            await self._respond_error(writer, HTTPError(
                503, "engine tick thread crashed before the request "
                "was accepted", etype="server_error",
            ))
            return
        created = int(time.time())
        # emit the trace context back: the client (or a proxy) can join
        # its own telemetry to this server's spans/logs by trace id
        tp = getattr(payload, "trace_id", None)
        resp_headers = (
            (("traceparent", make_traceparent(tp)),) if tp else ()
        )
        # Disconnect watch: drain (and DISCARD, bounded-memory) anything
        # else the client sends — we are Connection: close, so stray
        # bytes are pipelining we don't support — and complete only at
        # EOF, which for an HTTP/1.1 client means it hung up → abort.
        # (A client that half-closes its write side after the body is
        # indistinguishable from a disconnect here and is also aborted;
        # real HTTP clients don't half-close.)
        monitor = asyncio.ensure_future(self._watch_disconnect(reader))
        try:
            if payload.stream:
                await self._stream_response(
                    writer, aq, monitor, rid, payload, created,
                    extra_headers=resp_headers)
            else:
                await self._unary_response(
                    writer, aq, monitor, rid, payload, created,
                    extra_headers=resp_headers)
        finally:
            monitor.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await monitor

    async def _resume(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter, rid: int,
                      last_idx: int, echo_model: str,
                      t_accept: float = -1.0) -> None:
        """Re-attach a dropped SSE stream (serve/journal.py resume
        protocol): replay the delivered-token suffix from the client's
        Last-Event-ID, then continue live.  404 when the id is unknown
        or already claimed — the client falls back to a fresh POST."""
        if self.draining or self.runner.crashed:
            await self._respond_error(writer, HTTPError(
                503, "server is draining for shutdown"
                if self.draining else
                "engine tick thread crashed: " + str(self.runner.crashed),
                etype="server_error", headers=(("Retry-After", "1"),),
            ))
            return
        loop = asyncio.get_running_loop()
        aq: asyncio.Queue = asyncio.Queue()
        tracer = self.tracer
        if tracer is not None:
            tracer.async_begin(rid, "http",
                               ts_us=t_accept if t_accept >= 0.0 else None,
                               args={"resume": True,
                                     "last_event_id": last_idx})
        try:
            self.runner.resume(rid, last_idx, loop, aq)
            verdict = await aq.get()
            if verdict[0] == "gone":
                await self._respond_error(writer, HTTPError(
                    404, verdict[1], code="unknown_completion"))
                return
            if verdict[0] == "busy":
                # the client is ahead of the journaled prefix while the
                # recovered stream regenerates — retryable, not terminal
                await self._respond_error(writer, HTTPError(
                    503, verdict[1], etype="server_error",
                    headers=(("Retry-After", "1"),),
                ))
                return
            if verdict[0] == "finish":
                await self._respond_error(writer, HTTPError(
                    503, "engine tick thread crashed before the resume "
                    "was attached", etype="server_error",
                ))
                return
            created = int(time.time())
            payload = _ResumeEcho(echo_model)
            # the attach verdict carries the original trace id (when
            # the ledger/parked entry kept one): the resumed stream
            # emits the SAME traceparent as the first response
            tp = verdict[1] if len(verdict) > 1 else None
            resume_headers = (
                (("traceparent", make_traceparent(tp)),) if tp else ()
            )
            monitor = asyncio.ensure_future(
                self._watch_disconnect(reader))
            try:
                await self._stream_response(
                    writer, aq, monitor, rid, payload, created,
                    start_idx=last_idx, extra_headers=resume_headers)
            finally:
                monitor.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await monitor
        finally:
            if tracer is not None:
                tracer.async_end(rid, "http")

    @staticmethod
    async def _watch_disconnect(reader: asyncio.StreamReader) -> None:
        while True:
            data = await reader.read(4096)
            if not data:
                return

    async def _next_event(self, aq: asyncio.Queue,
                          monitor: asyncio.Future) -> tuple | None:
        """Next engine event, or None if the client disconnected first."""
        getter = asyncio.ensure_future(aq.get())
        done, _ = await asyncio.wait(
            {getter, monitor}, return_when=asyncio.FIRST_COMPLETED,
        )
        if getter in done:
            return getter.result()
        getter.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await getter
        return None

    async def _stream_response(self, writer, aq, monitor, rid,
                               payload, created, start_idx: int = 0,
                               extra_headers: tuple = ()) -> None:
        # delivered-token index, carried as the SSE event id on every
        # token frame: a client that reconnects with Last-Event-ID = the
        # last id it saw gets exactly the tokens it is missing
        idx = start_idx
        head = (
            "HTTP/1.1 200 OK\r\n"
            "Content-Type: text/event-stream\r\n"
            "Cache-Control: no-cache\r\n"
            "Connection: close\r\n"
        )
        for key, value in extra_headers:
            head += f"{key}: {value}\r\n"
        try:
            writer.write(head.encode() + b"\r\n")
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError, OSError):
            # gone before the first byte: the request must not keep its
            # decode slot generating for a dead socket
            self.runner.abort(rid)
            return
        while True:
            ev = await self._next_event(aq, monitor)
            if ev is None:  # client went away mid-stream
                self.runner.abort(rid)
                return
            if ev[0] == "token":
                _, tok, delta = ev
                idx += 1
                frame = sse_event(chunk_payload(
                    rid, payload.echo_model, created,
                    text=delta or "", token_id=tok, finish_reason=None,
                ), event_id=idx)
            else:  # ("finish", reason, tail)
                _, reason, tail = ev
                frame = sse_event(chunk_payload(
                    rid, payload.echo_model, created,
                    text=tail or "", token_id=None, finish_reason=reason,
                )) + DONE_SENTINEL
            faults = self.runner.faults
            if faults is not None and faults.trip("http_reset") is not None:
                # injected socket reset mid-stream: the client sees a
                # hard RST, the request aborts like any disconnect
                writer.transport.abort()
                self.runner.abort(rid)
                return
            try:
                writer.write(frame)
                await writer.drain()
            except (ConnectionResetError, BrokenPipeError, OSError):
                self.runner.abort(rid)
                return
            if ev[0] == "finish":
                return

    async def _unary_response(self, writer, aq, monitor, rid,
                              payload, created,
                              extra_headers: tuple = ()) -> None:
        token_ids: list[int] = []
        text_parts: list[str] = []
        while True:
            ev = await self._next_event(aq, monitor)
            if ev is None:
                self.runner.abort(rid)
                return
            if ev[0] == "token":
                token_ids.append(ev[1])
                if ev[2]:
                    text_parts.append(ev[2])
            else:
                reason, tail = ev[1], ev[2]
                if tail:
                    text_parts.append(tail)
                break
        body = json.dumps(completion_payload(
            rid, payload.echo_model, created,
            text="".join(text_parts), token_ids=token_ids,
            finish_reason=reason,
            prompt_tokens=int(payload.prompt_ids.size),
        )).encode()
        await self._respond(writer, 200, body,
                            extra_headers=extra_headers)

    # ------------------------------------------------------------------
    async def _respond(self, writer: asyncio.StreamWriter, status: int,
                       body: bytes,
                       content_type: str = "application/json",
                       extra_headers: tuple = ()) -> None:
        head = (
            f"HTTP/1.1 {status} {_REASONS.get(status, '')}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(body)}\r\n"
            "Connection: close\r\n"
        )
        for key, value in extra_headers:
            head += f"{key}: {value}\r\n"
        writer.write(head.encode() + b"\r\n" + body)
        with contextlib.suppress(ConnectionResetError, BrokenPipeError,
                                 OSError):
            await writer.drain()

    async def _respond_error(self, writer: asyncio.StreamWriter,
                             e: HTTPError) -> None:
        await self._respond(
            writer, e.status, error_body(e.message, e.etype, e.code),
            extra_headers=tuple(e.headers),
        )


async def run_server(
    engine: Any,
    *,
    model_id: str,
    tokenizer: Any = None,
    host: str = "127.0.0.1",
    port: int = 8000,
    request_timeout: float | None = None,
    drain_timeout: float = 30.0,
    default_max_tokens: int = 16,
    max_tokens_cap: int | None = None,
    tick_deadline: float | None = None,
    max_restarts: int = 0,
    restart_backoff_s: float = 0.5,
    restart_window_s: float = 300.0,
    port_file: str | None = None,
    exit_after_s: float | None = None,
    on_started: Any = None,
    runner: Any = None,
    upgrade_loader: Any = None,
) -> HttpServer:
    """Start serving and block until drain shutdown completes."""
    server = HttpServer(
        engine, model_id=model_id, tokenizer=tokenizer,
        request_timeout=request_timeout, drain_timeout=drain_timeout,
        default_max_tokens=default_max_tokens,
        max_tokens_cap=max_tokens_cap,
        tick_deadline=tick_deadline, max_restarts=max_restarts,
        restart_backoff_s=restart_backoff_s,
        restart_window_s=restart_window_s,
        runner=runner,
        upgrade_loader=upgrade_loader,
    )
    await server.start(host, port)
    if port_file:
        with open(port_file, "w") as f:
            f.write(f"{server.host} {server.port}\n")
    if exit_after_s is not None:
        asyncio.get_running_loop().call_later(
            exit_after_s, server.begin_drain)
    if on_started is not None:
        on_started(server)
    await server.serve_until_shutdown()
    return server


def serve_forever(engine: Any, **kwargs: Any) -> None:
    """Synchronous entry for the CLI: run the server on a fresh event
    loop until a drain shutdown (SIGTERM/SIGINT) completes."""
    asyncio.run(run_server(engine, **kwargs))
