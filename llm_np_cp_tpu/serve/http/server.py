"""Dependency-free asyncio HTTP front-end over ``ServeEngine``.

Two threads, one contract:

- The **engine thread** (``EngineRunner``) owns the ``ServeEngine``
  exclusively — every engine entry point (submit/abort/step) runs there,
  so the engine itself never needs locks.  Handlers talk to it through a
  thread-safe command queue; admission decisions (queue-full → 429,
  capacity ValueError → 400) are made ON the engine thread where
  scheduler state is consistent, and the verdict comes back as the first
  event on the request's bridge queue.
- The **event loop** (``HttpServer``) speaks HTTP/1.1 over stdlib
  ``asyncio`` streams (no FastAPI/uvicorn — the container has neither,
  and a serving stack's front-end should not be the dependency
  surface).  Per-token events cross back via
  ``loop.call_soon_threadsafe`` onto per-request ``asyncio.Queue``s.

Endpoints:

- ``POST /v1/completions`` — OpenAI-compatible JSON; ``"stream": true``
  streams SSE chunks fed from the engine's per-request callbacks.
  Client disconnect mid-stream aborts the request (blocks decref back to
  the pool); ``timeout_s`` (or the server-wide ``--request-timeout``)
  becomes an engine deadline with the same abort path.
- ``GET /healthz`` — liveness + draining state.
- ``GET /metrics`` — Prometheus text format from ``ServeMetrics`` plus
  live pool/stream gauges.

Shutdown (SIGTERM/SIGINT): stop admission (503 on new completions),
finish in-flight streams up to ``drain_timeout``, abort stragglers, and
only then close the listening socket.
"""

from __future__ import annotations

import asyncio
import contextlib
import itertools
import json
import queue as queue_mod
import signal
import threading
import time
from typing import Any

from llm_np_cp_tpu.serve.http.protocol import (
    HTTPError,
    chunk_payload,
    completion_payload,
    error_body,
    parse_completion_request,
)
from llm_np_cp_tpu.serve.http.sse import DONE_SENTINEL, sse_event
from llm_np_cp_tpu.serve.scheduler import QueueFull

TERMINAL_EVENTS = ("stop", "length", "aborted")
_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 408: "Request Timeout",
    413: "Payload Too Large", 429: "Too Many Requests",
    500: "Internal Server Error", 503: "Service Unavailable",
}
MAX_BODY_BYTES = 8 << 20


class EngineRunner:
    """Owns the engine tick loop on a worker thread and bridges it to
    asyncio handlers.

    Commands (submit/abort) are drained at the top of every loop
    iteration, then one ``engine.step()`` runs if there is work;  when
    idle the loop blocks on the command queue (no spin).  Events flow
    back per request: ``("accepted",)`` / ``("rejected", retry_after)`` /
    ``("error", msg)`` on the admission verdict, ``("token", id, delta)``
    per generated token, ``("finish", reason, final_text_delta)``
    terminally.
    """

    def __init__(self, engine: Any, *, request_timeout: float | None = None,
                 idle_poll_s: float = 0.02,
                 metrics_max_samples: int = 100_000) -> None:
        self.engine = engine
        self.request_timeout = request_timeout
        self.idle_poll_s = idle_poll_s
        # a server runs for weeks: bound the metrics sample lists
        # (counters stay exact; percentiles become a recent window) and
        # trim the scheduler's terminal-request ledgers below — nothing
        # in the HTTP layer reads them, and each entry pins its prompt
        # array and callback closures
        engine.metrics.max_samples = metrics_max_samples
        self._cmds: queue_mod.Queue = queue_mod.Queue()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # rid → (loop, asyncio.Queue); written by both threads, but each
        # rid is registered exactly once (submit) and removed exactly
        # once (engine thread, on the terminal event / reject)
        self._live: dict[int, tuple[asyncio.AbstractEventLoop,
                                    asyncio.Queue]] = {}
        self._rid = itertools.count(getattr(engine, "_next_id", 0))
        # set when the tick thread dies on an unexpected exception: the
        # server turns /healthz unhealthy and rejects new work instead
        # of silently wedging every stream
        self.crashed: str | None = None

    # -- event-loop side ----------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="serve-engine-tick", daemon=True,
        )
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._cmds.put(("wake",))
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    @property
    def inflight(self) -> int:
        """Live bridged requests (accepted, not yet terminal)."""
        return len(self._live)

    def next_rid(self) -> int:
        return next(self._rid)

    def submit(self, rid: int, payload: Any,
               loop: asyncio.AbstractEventLoop, aq: asyncio.Queue) -> None:
        self._live[rid] = (loop, aq)
        self._cmds.put(("submit", rid, payload))
        # crash race: if the tick thread died between the handler's
        # pre-check and this registration, its backstop flush may have
        # already run — nobody will ever answer this command, so answer
        # it here (a duplicate event from the flush is harmless: the
        # handler stops at the first terminal one)
        if self.crashed and self._live.pop(rid, None) is not None:
            aq.put_nowait(("error",
                           f"engine tick thread crashed: {self.crashed}"))

    def abort(self, rid: int) -> None:
        self._cmds.put(("abort", rid))

    def abort_all(self) -> None:
        self._cmds.put(("abort_all",))

    # -- engine-thread side -------------------------------------------
    def _push(self, rid: int, item: tuple) -> None:
        ent = self._live.get(rid)
        if ent is None:
            return
        loop, aq = ent
        try:
            loop.call_soon_threadsafe(aq.put_nowait, item)
        except RuntimeError:
            # loop already closed (shutdown race) — nobody is reading
            self._live.pop(rid, None)

    def _exec(self, cmd: tuple) -> None:
        kind = cmd[0]
        if kind == "submit":
            _, rid, payload = cmd
            deadline = payload.timeout_s
            if self.request_timeout is not None:
                deadline = min(deadline or self.request_timeout,
                               self.request_timeout)

            def cb(req: Any, tok: int, delta: str | None) -> None:
                self._push(req.req_id, ("token", int(tok), delta))

            def on_event(req: Any, event: str) -> None:
                if event in TERMINAL_EVENTS:
                    self._push(req.req_id, (
                        "finish", event,
                        req.extra.pop("final_text_delta", None),
                    ))
                    self._live.pop(req.req_id, None)

            try:
                self.engine.submit(
                    payload.prompt_ids, payload.max_tokens,
                    request_id=rid, seed=payload.seed, callback=cb,
                    on_event=on_event, deadline_s=deadline,
                )
            except QueueFull:
                self._push(rid, ("rejected", 1))
                self._live.pop(rid, None)
            except ValueError as e:
                self._push(rid, ("error", str(e)))
                self._live.pop(rid, None)
            else:
                self._push(rid, ("accepted",))
        elif kind == "abort":
            self.engine.abort(cmd[1])
        elif kind == "abort_all":
            for rid in list(self._live):
                self.engine.abort(rid)

    def _run(self) -> None:
        engine = self.engine
        try:
            while not self._stop.is_set():
                try:
                    block = not engine.scheduler.has_work
                    cmd = self._cmds.get(
                        block=block,
                        timeout=self.idle_poll_s if block else None,
                    )
                except queue_mod.Empty:
                    cmd = None
                while cmd is not None:
                    if cmd[0] != "wake":
                        self._exec(cmd)
                    try:
                        cmd = self._cmds.get_nowait()
                    except queue_mod.Empty:
                        cmd = None
                if self._stop.is_set():
                    break
                if engine.scheduler.has_work:
                    engine.step()
                    # terminal requests already delivered their events
                    # through the bridge — dropping them here keeps a
                    # long-running server's memory flat
                    engine.scheduler.finished.clear()
                    engine.scheduler.aborted.clear()
        except BaseException as e:  # noqa: BLE001 — last-resort backstop
            # A dead tick thread must not wedge the server: every
            # in-flight stream gets a terminal event (clients see a
            # clean end instead of hanging until their own timeouts),
            # /healthz flips unhealthy, and new submits are refused.
            self.crashed = f"{type(e).__name__}: {e}"
            import traceback

            traceback.print_exc()
            for rid in list(self._live):
                self._push(rid, ("finish", "aborted", None))
                self._live.pop(rid, None)


class HttpServer:
    """The asyncio front: routing, SSE streaming, drain shutdown."""

    def __init__(
        self,
        engine: Any,
        *,
        model_id: str,
        tokenizer: Any = None,
        request_timeout: float | None = None,
        drain_timeout: float = 30.0,
        default_max_tokens: int = 16,
        max_tokens_cap: int | None = None,
    ) -> None:
        self.engine = engine
        self.model_id = model_id
        self.tokenizer = tokenizer if tokenizer is not None \
            else getattr(engine, "tokenizer", None)
        self.drain_timeout = drain_timeout
        self.default_max_tokens = default_max_tokens
        self.max_tokens_cap = max_tokens_cap
        self.runner = EngineRunner(engine, request_timeout=request_timeout)
        self.draining = False
        self.host: str | None = None
        self.port: int | None = None
        self._server: asyncio.AbstractServer | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._done: asyncio.Event | None = None
        self._drain_task: asyncio.Task | None = None
        self._conn_tasks: set[asyncio.Task] = set()
        self._signals: list[int] = []

    # ------------------------------------------------------------------
    async def start(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._loop = asyncio.get_running_loop()
        self._done = asyncio.Event()
        self.runner.start()
        self._server = await asyncio.start_server(self._on_conn, host, port)
        sock = self._server.sockets[0]
        self.host, self.port = sock.getsockname()[:2]
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._loop.add_signal_handler(sig, self.begin_drain)
                self._signals.append(sig)
            except (NotImplementedError, RuntimeError, ValueError):
                # not the main thread (CLI smoke tests run the server in
                # a worker thread) or an embedded loop — drain stays
                # reachable programmatically
                break

    def begin_drain(self) -> None:
        """Idempotent shutdown trigger — the SIGTERM handler and the
        test hook both land here."""
        if self._drain_task is None and self._loop is not None:
            self._drain_task = self._loop.create_task(self._drain())

    async def _drain(self) -> None:
        self.draining = True
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.drain_timeout
        while self.runner.inflight and loop.time() < deadline:
            await asyncio.sleep(0.02)
        if self.runner.inflight:
            self.runner.abort_all()
            grace = loop.time() + 5.0
            while self.runner.inflight and loop.time() < grace:
                await asyncio.sleep(0.02)
        # every stream got its terminal event; give the handlers a
        # bounded window to flush their last bytes BEFORE the socket
        # closes (the acceptance criterion for drain)
        flush_deadline = loop.time() + 5.0
        while self._conn_tasks and loop.time() < flush_deadline:
            await asyncio.sleep(0.02)
        assert self._server is not None
        self._server.close()
        await self._server.wait_closed()
        for sig in self._signals:
            with contextlib.suppress(Exception):
                self._loop.remove_signal_handler(sig)  # type: ignore[union-attr]
        self.runner.stop()
        assert self._done is not None
        self._done.set()

    async def serve_until_shutdown(self) -> None:
        assert self._done is not None, "call start() first"
        await self._done.wait()

    # ------------------------------------------------------------------
    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
        try:
            await self._handle(reader, writer)
        except (ConnectionResetError, BrokenPipeError, asyncio.TimeoutError):
            pass
        finally:
            if task is not None:
                self._conn_tasks.discard(task)
            with contextlib.suppress(Exception):
                writer.close()
                await writer.wait_closed()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            method, path, headers, body = await asyncio.wait_for(
                self._read_request(reader), timeout=30.0,
            )
        except HTTPError as e:
            await self._respond_error(writer, e)
            return
        except (asyncio.IncompleteReadError, ValueError,
                asyncio.TimeoutError):
            return  # torn/oversized request line — nothing to answer
        if method == "GET" and path == "/healthz":
            crashed = self.runner.crashed
            status = 503 if (self.draining or crashed) else 200
            state = ("crashed" if crashed
                     else "draining" if self.draining else "ok")
            payload = {"status": state, "model": self.model_id}
            if crashed:
                payload["error"] = crashed
            await self._respond(writer, status, json.dumps(payload).encode())
        elif method == "GET" and path == "/metrics":
            await self._respond(
                writer, 200, self._render_metrics().encode(),
                content_type="text/plain; version=0.0.4; charset=utf-8",
            )
        elif path == "/v1/completions":
            if method != "POST":
                await self._respond_error(writer, HTTPError(
                    405, "use POST for /v1/completions"))
            else:
                await self._completions(reader, writer, body)
        else:
            await self._respond_error(writer, HTTPError(
                404, f"no route for {method} {path}"))

    async def _read_request(
        self, reader: asyncio.StreamReader,
    ) -> tuple[str, str, dict[str, str], bytes]:
        line = await reader.readline()
        if not line:
            raise asyncio.IncompleteReadError(b"", None)
        parts = line.decode("latin-1").split()
        if len(parts) != 3:
            raise HTTPError(400, "malformed request line")
        method, path = parts[0].upper(), parts[1]
        headers: dict[str, str] = {}
        while True:
            hline = await reader.readline()
            if hline in (b"\r\n", b"\n", b""):
                break
            key, _, value = hline.decode("latin-1").partition(":")
            headers[key.strip().lower()] = value.strip()
        try:
            n = int(headers.get("content-length", "0"))
        except ValueError as e:
            raise HTTPError(400, "bad Content-Length") from e
        if n > MAX_BODY_BYTES:
            raise HTTPError(413, f"body exceeds {MAX_BODY_BYTES} bytes")
        body = await reader.readexactly(n) if n else b""
        return method, path, headers, body

    def _render_metrics(self) -> str:
        stats = self.engine.pool.stats()
        return self.engine.metrics.prometheus(extra_gauges={
            "pool_blocks_free": stats["free"],
            "pool_blocks_request_held": stats["request_held"],
            "pool_blocks_cache_only": stats["cache_only"],
            "inflight_streams": self.runner.inflight,
            "queue_depth_live": self.engine.scheduler.queue_depth,
            "draining": 1.0 if self.draining else 0.0,
        })

    # ------------------------------------------------------------------
    async def _completions(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter,
                           body: bytes) -> None:
        if self.draining or self.runner.crashed:
            msg = ("engine tick thread crashed: " + self.runner.crashed
                   if self.runner.crashed
                   else "server is draining for shutdown")
            await self._respond_error(writer, HTTPError(
                503, msg, etype="server_error",
                headers=(("Retry-After", "1"),),
            ))
            return
        try:
            payload = parse_completion_request(
                body, model_id=self.model_id, tokenizer=self.tokenizer,
                default_max_tokens=self.default_max_tokens,
                max_tokens_cap=self.max_tokens_cap,
            )
        except HTTPError as e:
            await self._respond_error(writer, e)
            return

        loop = asyncio.get_running_loop()
        aq: asyncio.Queue = asyncio.Queue()
        rid = self.runner.next_rid()
        self.runner.submit(rid, payload, loop, aq)
        verdict = await aq.get()
        if verdict[0] == "rejected":
            await self._respond_error(writer, HTTPError(
                429, "request queue is full; retry later",
                etype="rate_limit_error",
                headers=(("Retry-After", str(verdict[1])),),
            ))
            return
        if verdict[0] == "error":
            await self._respond_error(writer, HTTPError(400, verdict[1]))
            return
        if verdict[0] == "finish":
            # terminal before acceptance: only the tick-thread crash
            # backstop produces this — the request never ran
            await self._respond_error(writer, HTTPError(
                503, "engine tick thread crashed before the request "
                "was accepted", etype="server_error",
            ))
            return
        created = int(time.time())
        # Disconnect watch: drain (and DISCARD, bounded-memory) anything
        # else the client sends — we are Connection: close, so stray
        # bytes are pipelining we don't support — and complete only at
        # EOF, which for an HTTP/1.1 client means it hung up → abort.
        # (A client that half-closes its write side after the body is
        # indistinguishable from a disconnect here and is also aborted;
        # real HTTP clients don't half-close.)
        monitor = asyncio.ensure_future(self._watch_disconnect(reader))
        try:
            if payload.stream:
                await self._stream_response(
                    writer, aq, monitor, rid, payload, created)
            else:
                await self._unary_response(
                    writer, aq, monitor, rid, payload, created)
        finally:
            monitor.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await monitor

    @staticmethod
    async def _watch_disconnect(reader: asyncio.StreamReader) -> None:
        while True:
            data = await reader.read(4096)
            if not data:
                return

    async def _next_event(self, aq: asyncio.Queue,
                          monitor: asyncio.Future) -> tuple | None:
        """Next engine event, or None if the client disconnected first."""
        getter = asyncio.ensure_future(aq.get())
        done, _ = await asyncio.wait(
            {getter, monitor}, return_when=asyncio.FIRST_COMPLETED,
        )
        if getter in done:
            return getter.result()
        getter.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await getter
        return None

    async def _stream_response(self, writer, aq, monitor, rid,
                               payload, created) -> None:
        try:
            writer.write(
                b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: text/event-stream\r\n"
                b"Cache-Control: no-cache\r\n"
                b"Connection: close\r\n\r\n"
            )
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError, OSError):
            # gone before the first byte: the request must not keep its
            # decode slot generating for a dead socket
            self.runner.abort(rid)
            return
        while True:
            ev = await self._next_event(aq, monitor)
            if ev is None:  # client went away mid-stream
                self.runner.abort(rid)
                return
            if ev[0] == "token":
                _, tok, delta = ev
                frame = sse_event(chunk_payload(
                    rid, payload.echo_model, created,
                    text=delta or "", token_id=tok, finish_reason=None,
                ))
            else:  # ("finish", reason, tail)
                _, reason, tail = ev
                frame = sse_event(chunk_payload(
                    rid, payload.echo_model, created,
                    text=tail or "", token_id=None, finish_reason=reason,
                )) + DONE_SENTINEL
            try:
                writer.write(frame)
                await writer.drain()
            except (ConnectionResetError, BrokenPipeError, OSError):
                self.runner.abort(rid)
                return
            if ev[0] == "finish":
                return

    async def _unary_response(self, writer, aq, monitor, rid,
                              payload, created) -> None:
        token_ids: list[int] = []
        text_parts: list[str] = []
        while True:
            ev = await self._next_event(aq, monitor)
            if ev is None:
                self.runner.abort(rid)
                return
            if ev[0] == "token":
                token_ids.append(ev[1])
                if ev[2]:
                    text_parts.append(ev[2])
            else:
                reason, tail = ev[1], ev[2]
                if tail:
                    text_parts.append(tail)
                break
        body = json.dumps(completion_payload(
            rid, payload.echo_model, created,
            text="".join(text_parts), token_ids=token_ids,
            finish_reason=reason,
            prompt_tokens=int(payload.prompt_ids.size),
        )).encode()
        await self._respond(writer, 200, body)

    # ------------------------------------------------------------------
    async def _respond(self, writer: asyncio.StreamWriter, status: int,
                       body: bytes,
                       content_type: str = "application/json",
                       extra_headers: tuple = ()) -> None:
        head = (
            f"HTTP/1.1 {status} {_REASONS.get(status, '')}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(body)}\r\n"
            "Connection: close\r\n"
        )
        for key, value in extra_headers:
            head += f"{key}: {value}\r\n"
        writer.write(head.encode() + b"\r\n" + body)
        with contextlib.suppress(ConnectionResetError, BrokenPipeError,
                                 OSError):
            await writer.drain()

    async def _respond_error(self, writer: asyncio.StreamWriter,
                             e: HTTPError) -> None:
        await self._respond(
            writer, e.status, error_body(e.message, e.etype, e.code),
            extra_headers=tuple(e.headers),
        )


async def run_server(
    engine: Any,
    *,
    model_id: str,
    tokenizer: Any = None,
    host: str = "127.0.0.1",
    port: int = 8000,
    request_timeout: float | None = None,
    drain_timeout: float = 30.0,
    default_max_tokens: int = 16,
    max_tokens_cap: int | None = None,
    port_file: str | None = None,
    exit_after_s: float | None = None,
    on_started: Any = None,
) -> HttpServer:
    """Start serving and block until drain shutdown completes."""
    server = HttpServer(
        engine, model_id=model_id, tokenizer=tokenizer,
        request_timeout=request_timeout, drain_timeout=drain_timeout,
        default_max_tokens=default_max_tokens,
        max_tokens_cap=max_tokens_cap,
    )
    await server.start(host, port)
    if port_file:
        with open(port_file, "w") as f:
            f.write(f"{server.host} {server.port}\n")
    if exit_after_s is not None:
        asyncio.get_running_loop().call_later(
            exit_after_s, server.begin_drain)
    if on_started is not None:
        on_started(server)
    await server.serve_until_shutdown()
    return server


def serve_forever(engine: Any, **kwargs: Any) -> None:
    """Synchronous entry for the CLI: run the server on a fresh event
    loop until a drain shutdown (SIGTERM/SIGINT) completes."""
    asyncio.run(run_server(engine, **kwargs))
