"""OpenAI-compatible streaming HTTP front-end over ``ServeEngine``.

Stdlib-only (asyncio streams — no FastAPI/uvicorn): the serving tick
loop runs on a worker thread (``EngineRunner``), HTTP handlers on the
event loop, bridged by per-request asyncio queues.  See ``server`` for
the architecture, ``protocol`` for request/response shapes, ``sse`` for
the streaming wire format, ``client`` for the stdlib loadgen/smoke
clients.
"""

from llm_np_cp_tpu.serve.http.protocol import (
    CompletionPayload,
    HTTPError,
    parse_completion_request,
)
from llm_np_cp_tpu.serve.http.server import (
    EngineRunner,
    HttpServer,
    run_server,
    serve_forever,
)
from llm_np_cp_tpu.serve.http.sse import DONE_SENTINEL, sse_event

__all__ = [
    "CompletionPayload",
    "DONE_SENTINEL",
    "EngineRunner",
    "HTTPError",
    "HttpServer",
    "parse_completion_request",
    "run_server",
    "serve_forever",
    "sse_event",
]
