"""Server-Sent Events framing (the OpenAI streaming wire format).

One event per generated token: ``data: <json>\\n\\n``, terminated by the
literal ``data: [DONE]\\n\\n`` sentinel.  Kept apart from the HTTP server
so the framing is unit-testable against raw bytes and reusable by the
stdlib client (bench loadgen / smoke tests) without importing asyncio
server machinery.
"""

from __future__ import annotations

import json
from typing import Any, AsyncIterator

DONE_SENTINEL = b"data: [DONE]\n\n"


def sse_event(payload: dict[str, Any], *,
              event_id: int | None = None) -> bytes:
    """One ``data:`` frame, optionally carrying an ``id:`` line.  Token
    frames use the delivered-token index as the event id — what a
    reconnecting client sends back as ``Last-Event-ID`` to resume the
    stream after a server restart (serve/journal.py).  Payloads are
    single-line JSON, so the multi-line ``data:`` continuation rule
    never applies."""
    head = f"id: {event_id}\n".encode() if event_id is not None else b""
    return head + b"data: " \
        + json.dumps(payload, separators=(",", ":")).encode() + b"\n\n"


def parse_sse_line(line: bytes) -> dict[str, Any] | None:
    """Decode one stripped SSE line → payload dict, None for the [DONE]
    sentinel / blank separators / comments / non-data fields (``id:``,
    ``event:``, ``retry:``).  Raises ValueError on a ``data:`` line
    that is not valid JSON (a framing bug, not traffic)."""
    line = line.strip()
    if not line or line.startswith(b":"):
        return None
    if (line.startswith(b"id:") or line.startswith(b"event:")
            or line.startswith(b"retry:")):
        return None
    if not line.startswith(b"data:"):
        raise ValueError(f"not an SSE data line: {line!r}")
    body = line[len(b"data:"):].strip()
    if body == b"[DONE]":
        return None
    return json.loads(body)


async def iter_sse_payloads(reader) -> AsyncIterator[dict[str, Any]]:
    """Yield decoded payloads from an ``asyncio.StreamReader`` until the
    [DONE] sentinel or EOF."""
    while True:
        line = await reader.readline()
        if not line:
            return
        stripped = line.strip()
        if stripped == b"data: [DONE]" or stripped == b"data:[DONE]":
            return
        payload = parse_sse_line(line)
        if payload is not None:
            yield payload
