"""OpenAI-compatible completions protocol: parse, validate, render.

Pure functions over bytes/dicts — no sockets, no engine — so every
status-code branch (400 malformed JSON, 404 unknown model, 413 oversized
body) is unit-testable without a running server.

Scope: ``POST /v1/completions`` with a single prompt.  ``prompt`` is a
string (tokenized with the server's tokenizer) or a list of token ids
(the tokenizer-free path tests and the bench loadgen use).  Sampling is
engine-level (one compiled sampler for the whole packed batch), so
per-request ``temperature``/``top_p`` are accepted but ignored — the
response echoes the engine's behavior, it does not silently vary it.
Streaming chunks carry a ``token_id`` extension field per token (the
final chunk has only the held-back text tail + ``finish_reason``);
non-streaming responses carry the full ``token_ids`` list — either way
tokenizer-less clients (and the parity tests) consume exact ids, not
just text.

``finish_reason`` uses the engine's uniform vocabulary: ``stop``,
``length``, and ``aborted`` (client disconnect or deadline — the
non-OpenAI extension this server's abort path needs a name for).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

import numpy as np


class HTTPError(Exception):
    """Maps straight to an HTTP error response."""

    def __init__(
        self, status: int, message: str, *,
        etype: str = "invalid_request_error", code: str | None = None,
        headers: tuple[tuple[str, str], ...] = (),
    ) -> None:
        super().__init__(message)
        self.status = status
        self.message = message
        self.etype = etype
        self.code = code
        self.headers = headers


def error_body(message: str, etype: str = "invalid_request_error",
               code: str | None = None) -> bytes:
    return json.dumps(
        {"error": {"message": message, "type": etype, "code": code}}
    ).encode()


@dataclasses.dataclass
class CompletionPayload:
    """A validated /v1/completions request, ready for the engine."""

    prompt_ids: np.ndarray  # [P] int32
    max_tokens: int
    stream: bool
    seed: int
    echo_model: str  # what the response's "model" field echoes
    timeout_s: float | None  # per-request deadline (caps the server's)


def parse_completion_request(
    body: bytes,
    *,
    model_id: str,
    tokenizer: Any = None,
    default_max_tokens: int = 16,
    max_tokens_cap: int | None = None,
) -> CompletionPayload:
    """Validate a raw request body → payload, raising ``HTTPError`` with
    the right status for every malformed shape.  Capacity limits are NOT
    checked here — the engine owns those (its ValueError comes back to
    the client as a 400 through the runner's error event)."""
    try:
        obj = json.loads(body)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise HTTPError(400, f"invalid JSON body: {e}") from e
    if not isinstance(obj, dict):
        raise HTTPError(400, "request body must be a JSON object")

    model = obj.get("model", model_id)
    if not isinstance(model, str) or model != model_id:
        raise HTTPError(
            404, f"model {model!r} not found; this server serves "
            f"{model_id!r}", code="model_not_found",
        )

    prompt = obj.get("prompt")
    if isinstance(prompt, str) and prompt:
        if tokenizer is None:
            raise HTTPError(
                400, "this server has no tokenizer loaded; pass 'prompt' "
                "as a list of token ids",
            )
        ids = tokenizer(prompt, return_tensors="np")["input_ids"][0]
        prompt_ids = np.asarray(ids, dtype=np.int32).reshape(-1)
    elif isinstance(prompt, list) and prompt and all(
        isinstance(t, int) and not isinstance(t, bool) for t in prompt
    ):
        prompt_ids = np.asarray(prompt, dtype=np.int32)
    else:
        raise HTTPError(
            400, "'prompt' must be a non-empty string or a non-empty "
            "list of token ids",
        )

    max_tokens = obj.get("max_tokens", default_max_tokens)
    if not isinstance(max_tokens, int) or isinstance(max_tokens, bool) \
            or max_tokens < 1:
        raise HTTPError(400, f"'max_tokens' must be an int >= 1, got "
                             f"{max_tokens!r}")
    if max_tokens_cap is not None and max_tokens > max_tokens_cap:
        # the operator's per-request decode budget (serve --max-tokens)
        # is a hard cap, not just the pool-sizing input — reject rather
        # than silently clamp so clients learn the server's limit
        raise HTTPError(
            400, f"'max_tokens' {max_tokens} exceeds this server's "
            f"per-request cap {max_tokens_cap}",
        )
    stream = obj.get("stream", False)
    if not isinstance(stream, bool):
        raise HTTPError(400, "'stream' must be a boolean")
    seed = obj.get("seed", 0)
    if not isinstance(seed, int) or isinstance(seed, bool):
        raise HTTPError(400, "'seed' must be an int")
    timeout_s = obj.get("timeout_s")
    if timeout_s is not None and (
        not isinstance(timeout_s, (int, float)) or isinstance(timeout_s, bool)
        or timeout_s <= 0
    ):
        raise HTTPError(400, "'timeout_s' must be a number > 0")
    n = obj.get("n", 1)
    if n != 1:
        raise HTTPError(400, "'n' != 1 is not supported")
    return CompletionPayload(
        prompt_ids=prompt_ids,
        max_tokens=max_tokens,
        stream=stream,
        seed=seed,
        echo_model=model,
        timeout_s=float(timeout_s) if timeout_s is not None else None,
    )


def parse_completion_rid(raw: Any) -> int:
    """``"cmpl-123"`` (what responses echo) or a bare int → 123."""
    if isinstance(raw, int) and not isinstance(raw, bool) and raw >= 0:
        return raw
    if isinstance(raw, str) and raw.startswith("cmpl-"):
        tail = raw[len("cmpl-"):]
        if tail.isdigit():
            return int(tail)
    raise HTTPError(
        400, f"'request_id' must be a completion id like 'cmpl-7' "
        f"(or its bare integer), got {raw!r}")


def parse_last_event_id(raw: Any) -> int:
    """``Last-Event-ID`` header / ``last_event_id`` field → delivered-
    token count (0 = replay from the start)."""
    if raw is None:
        return 0
    try:
        n = int(raw)
    except (TypeError, ValueError):
        n = -1
    if n < 0 or isinstance(raw, bool):
        raise HTTPError(
            400, f"Last-Event-ID must be a delivered-token count >= 0, "
            f"got {raw!r}")
    return n


def parse_resume_request(
    body: bytes, headers: dict[str, str], *, model_id: str,
) -> tuple[int, int, str] | None:
    """Stream-resume detection for ``POST /v1/completions``: a body
    naming a ``request_id`` is a resume of that dropped SSE stream →
    ``(rid, last delivered-token index, echo model)``; None means a
    fresh completion (the normal parser takes over).  The resume index
    comes from the ``Last-Event-ID`` header (the SSE reconnect
    convention — event ids on token frames are delivered-token indices)
    or a ``last_event_id`` body field."""
    try:
        obj = json.loads(body)
    except (json.JSONDecodeError, UnicodeDecodeError):
        return None  # let the normal parser raise its 400
    if not isinstance(obj, dict) or "request_id" not in obj:
        return None
    rid = parse_completion_rid(obj["request_id"])
    model = obj.get("model", model_id)
    if not isinstance(model, str) or model != model_id:
        raise HTTPError(
            404, f"model {model!r} not found; this server serves "
            f"{model_id!r}", code="model_not_found",
        )
    if obj.get("stream", True) is not True:
        raise HTTPError(400, "resume replays an SSE stream; "
                             "'stream' must be true")
    raw = headers.get("last-event-id")
    if raw is None:
        raw = obj.get("last_event_id")
    return rid, parse_last_event_id(raw), model


# ----------------------------------------------------------------------
# Response builders
# ----------------------------------------------------------------------
def completion_id(rid: int) -> str:
    return f"cmpl-{rid}"


def chunk_payload(
    rid: int, model: str, created: int, *,
    text: str, token_id: int | None, finish_reason: str | None,
) -> dict[str, Any]:
    """One streaming SSE chunk (OpenAI text_completion shape plus the
    ``token_id`` extension)."""
    choice: dict[str, Any] = {
        "index": 0,
        "text": text,
        "finish_reason": finish_reason,
    }
    if token_id is not None:
        choice["token_id"] = token_id
    return {
        "id": completion_id(rid),
        "object": "text_completion",
        "created": created,
        "model": model,
        "choices": [choice],
    }


def completion_payload(
    rid: int, model: str, created: int, *,
    text: str, token_ids: list[int], finish_reason: str,
    prompt_tokens: int,
) -> dict[str, Any]:
    """The non-streaming response object (plus ``token_ids``)."""
    return {
        "id": completion_id(rid),
        "object": "text_completion",
        "created": created,
        "model": model,
        "choices": [{
            "index": 0,
            "text": text,
            "token_ids": token_ids,
            "finish_reason": finish_reason,
        }],
        "usage": {
            "prompt_tokens": prompt_tokens,
            "completion_tokens": len(token_ids),
            "total_tokens": prompt_tokens + len(token_ids),
        },
    }
