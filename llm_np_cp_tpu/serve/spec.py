"""Host-side draft streams for speculative serving (the unified tick).

The serve engine's speculative mode is draft-then-verify folded into the
ONE ``mixed_step`` dispatch per tick: each speculating request proposes
up to ``spec_k`` candidate tokens, the tick packs them as a ragged
q-slice of width ``k'+1`` (the verified input token plus the drafts)
alongside normal prefill chunks and plain decode rows, and the verifier
samples at EVERY packed position with the engine's deterministic
(seed, content-position) keys.  The longest draft prefix matching those
samples is accepted — so accepted streams are token-identical to plain
decode by construction, and a verify sweep reads each request's K/V
blocks ONCE for up to ``k+1`` emitted tokens (the raw tok/s lever at the
HBM roofline: per-seq throughput multiplies by the mean accept length).

The draft source is deliberately HOST-SIDE — prompt-lookup (n-gram)
drafting over the request's own token history — because the whole point
of the unified tick is ~1 device dispatch per tick: a model-based draft
would cost k extra sequential dispatches per tick and hand the win back
to latency.  Prompt lookup is free, needs no second checkpoint, and is
strong exactly where speculation pays (extractive/repetitive spans:
quoting the prompt, code, structured output); where it is weak the
per-request rolling-acceptance fallback turns the request back into a
plain decode row, so a cold stream costs one lane of padding per tick
at worst, never a regression in tokens.

``DraftState`` is the per-slot draft stream: an incremental n-gram →
position index over prompt + generated tokens.  ``propose(k)`` returns
the continuation of the most recent PRIOR occurrence of the current
suffix n-gram (longest n first), ``extend`` appends newly accepted
tokens.  O(1) per token to maintain, O(ngram range) per proposal.
"""

from __future__ import annotations


class DraftState:
    """Prompt-lookup draft stream for one request.

    Keeps the request's token history (prompt + generated) and, for each
    n in ``[ngram_min, ngram_max]``, a map from n-gram → its latest two
    end positions.  The current suffix always maps to the history's own
    tail (it was registered when its last token arrived), so proposals
    read the PREVIOUS occurrence — the most recent place the stream has
    been before — and copy the tokens that followed it.
    """

    def __init__(self, ngram_max: int = 3, ngram_min: int = 2) -> None:
        if not 1 <= ngram_min <= ngram_max:
            raise ValueError(
                f"need 1 <= ngram_min <= ngram_max, got "
                f"{ngram_min}..{ngram_max}"
            )
        self.ngram_max = ngram_max
        self.ngram_min = ngram_min
        self._ctx: list[int] = []
        # n → {ngram tuple → (previous end pos, latest end pos)}; an end
        # position is the index AFTER the n-gram (where its continuation
        # starts)
        self._index: dict[int, dict[tuple, tuple]] = {
            n: {} for n in range(ngram_min, ngram_max + 1)
        }

    @property
    def size(self) -> int:
        """Tokens consumed so far (callers extend with history[size:])."""
        return len(self._ctx)

    def extend(self, tokens) -> None:
        ctx = self._ctx
        for t in tokens:
            ctx.append(int(t))
            end = len(ctx)
            for n in range(self.ngram_min, self.ngram_max + 1):
                if end < n:
                    continue
                key = tuple(ctx[end - n:end])
                idx = self._index[n]
                prev = idx.get(key)
                idx[key] = (prev[1] if prev is not None else None, end)

    def propose(self, k: int) -> list[int]:
        """Up to ``k`` draft tokens continuing the current suffix, or []
        when the suffix has no prior occurrence (the request decodes
        plain this tick)."""
        if k <= 0:
            return []
        ctx = self._ctx
        end = len(ctx)
        for n in range(self.ngram_max, self.ngram_min - 1, -1):
            if end < n:
                continue
            hit = self._index[n].get(tuple(ctx[end - n:end]))
            if hit is None:
                continue
            prev, latest = hit
            # the latest registration is the suffix itself (position ==
            # end); a prior occurrence is what we can copy forward from
            pos = latest if latest < end else prev
            if pos is None or pos >= end:
                continue
            # the continuation window [pos, pos+k) clips at the context
            # end when the match sits near the tail — i.e. the stream is
            # cycling with period end-pos.  Copy modularly so a tight
            # loop (the single-repeated-token case above all) still
            # yields k drafts instead of one per tick.
            period = end - pos
            return [ctx[pos + (i % period)] for i in range(k)]
        return []
