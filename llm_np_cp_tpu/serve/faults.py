"""Deterministic fault injection for the serving stack.

A production engine's failure paths (kernel faults, hung ticks, crashed
tick threads, flaky checkpoint IO, socket resets) are exactly the code
that never runs in a clean test suite — so they rot.  The
``FaultInjector`` makes every one of them exercisable on a *seeded,
replayable schedule*: a chaos spec names injection sites and when they
trip, the injection points threaded through the stack ask ``trip(site)``
per hit, and the injector answers from the schedule.  The injector only
*decides*; each site owns its fault's behavior (raise ``FaultInjected``,
raise ``OSError``, sleep past the tick deadline, abort a socket), so the
schedule stays behavior-free and one spec grammar covers every layer.

Spec grammar (events joined by ``;`` or ``,``)::

    site@N          fire on the N-th hit of that site (1-based)
    site@N:C        fire on hits N .. N+C-1 (C consecutive faults —
                    the transient-error shape retry logic must survive)
    site%P          fire each hit with probability P (seeded RNG, so a
                    given seed replays the identical schedule)
    ...=ARG         optional float argument (hang duration in seconds,
                    Retry-After for injected 429s); default 1.0

Sites (each named where it is threaded in):

- ``decode``      — engine decode dispatch (``ServeEngine.step``); on
                    the paged impl this exercises the runtime
                    gather-fallback path
- ``prefill``     — ``ServeEngine._prefill_request`` entry
- ``tick_crash``  — the HTTP runner's tick loop (supervised restart)
- ``tick_hang``   — ditto, but sleep ``ARG`` seconds (watchdog food)
- ``ckpt_read``   — transient ``OSError`` during checkpoint shard reads
                    (``utils/loading.py`` bounded retry)
- ``http_429``    — reject a ``/v1/completions`` with 429 + Retry-After
                    ``ARG`` (client retry/backoff food)
- ``http_reset``  — hard-abort the client socket mid-SSE-stream
- ``proc_kill``   — SIGKILL the WHOLE PROCESS from the tick loop (hit
                    once per busy tick, so ``proc_kill@N`` dies after N
                    ticks) — the deterministic ``kill -9`` the durable
                    request journal's restart/resume path is tested
                    against (serve/journal.py)
- ``journal_write`` / ``journal_fsync`` — fail the journal writer
                    thread's file write / fsync (durability degradation:
                    the batch is dropped and counted, serving continues)
- ``host_sync``   — sleep ``ARG`` seconds inside the tick's host_sync
                    phase (the device→host token fetch): a REAL
                    injected host-sync regression the tick sentinel
                    attributes to the right phase — what the
                    ``ActionPolicy`` shed-prefill auto-action is
                    tested against (serve/lifecycle.py)
- ``upgrade_ckpt`` — fail the checkpoint read of a rolling weight
                    upgrade mid-roll (serve/replica.py
                    ``rolling_upgrade``): the roll must abort cleanly
                    with the replica still live on its old weights

No-op by default: nothing constructs an injector unless a chaos spec is
given (``--chaos-spec`` / ``LLMTPU_CHAOS_SPEC``), and every injection
point is a single ``is None`` check when chaos is off — zero overhead in
production and in benches.
"""

from __future__ import annotations

import dataclasses
import random
import threading
from collections import Counter

SITES = (
    "decode",
    "prefill",
    "tick_crash",
    "tick_hang",
    "ckpt_read",
    "http_429",
    "http_reset",
    "proc_kill",
    "journal_write",
    "journal_fsync",
    "host_sync",
    "upgrade_ckpt",
)


class FaultInjected(RuntimeError):
    """An injected (not organic) fault — recovery paths treat it exactly
    like the real failure it stands in for, but logs/metrics can tell
    the two apart."""

    def __init__(self, site: str) -> None:
        super().__init__(f"chaos: injected fault at site {site!r}")
        self.site = site


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One parsed spec event."""

    site: str
    start: int | None = None  # 1-based hit index (deterministic events)
    count: int = 1
    prob: float | None = None  # per-hit probability (seeded events)
    arg: float = 1.0

    def triggers(self, hit: int, rng: random.Random) -> bool:
        if self.prob is not None:
            return rng.random() < self.prob
        assert self.start is not None
        return self.start <= hit < self.start + self.count


def parse_chaos_spec(spec: str) -> list[FaultEvent]:
    """Parse the spec grammar above; raises ValueError with the offending
    token on malformed input (the CLI surfaces it pre-model-load)."""
    events: list[FaultEvent] = []
    for raw in spec.replace(",", ";").split(";"):
        token = raw.strip()
        if not token:
            continue
        try:
            body, _, arg_s = token.partition("=")
            arg = float(arg_s) if arg_s else 1.0
            if "@" in body:
                site, _, when = body.partition("@")
                n_s, _, c_s = when.partition(":")
                start, count = int(n_s), int(c_s) if c_s else 1
                if start < 1 or count < 1:
                    raise ValueError("hit index/count must be >= 1")
                event = FaultEvent(site=site.strip(), start=start,
                                   count=count, arg=arg)
            elif "%" in body:
                site, _, p_s = body.partition("%")
                prob = float(p_s)
                if not 0.0 <= prob <= 1.0:
                    raise ValueError("probability must be in [0, 1]")
                event = FaultEvent(site=site.strip(), prob=prob, arg=arg)
            else:
                raise ValueError("expected site@N[:C][=ARG] or site%P[=ARG]")
        except ValueError as e:
            raise ValueError(f"bad chaos event {token!r}: {e}") from None
        if event.site not in SITES:
            raise ValueError(
                f"bad chaos event {token!r}: unknown site {event.site!r} "
                f"(known: {', '.join(SITES)})"
            )
        events.append(event)
    return events


class FaultInjector:
    """Seeded, replayable fault schedule over the sites above.

    Thread-safe: sites are hit from the engine tick thread, the asyncio
    event loop, the watchdog, and checkpoint loading.  The per-site hit
    counters survive engine rebuilds (the injector object outlives any
    one engine), so a schedule like ``decode@40`` keeps counting across
    a supervised restart.
    """

    def __init__(self, spec: str, *, seed: int = 0) -> None:
        self.spec = spec
        self.seed = seed
        self._events = parse_chaos_spec(spec)
        # one RNG PER SITE (seeded from (seed, site) — random.Random
        # seeds strings deterministically): sites are hit from different
        # threads, and a shared stream would make a multi-site %P
        # schedule depend on thread interleaving, breaking replay
        self._rngs: dict[str, random.Random] = {}
        self._lock = threading.Lock()
        self.hits: Counter[str] = Counter()
        self.injected: Counter[str] = Counter()

    @classmethod
    def from_spec(cls, spec: str | None, *, seed: int = 0
                  ) -> "FaultInjector | None":
        """None for an empty/missing spec — the zero-overhead default."""
        if not spec or not spec.strip():
            return None
        return cls(spec, seed=seed)

    @property
    def injected_total(self) -> int:
        with self._lock:
            return sum(self.injected.values())

    def trip(self, site: str) -> float | None:
        """Count one hit of ``site``; return the event's ARG when a fault
        should fire now, else None.  The caller owns the fault behavior."""
        with self._lock:
            self.hits[site] += 1
            hit = self.hits[site]
            rng = self._rngs.get(site)
            if rng is None:
                rng = self._rngs[site] = random.Random(f"{self.seed}:{site}")
            for ev in self._events:
                if ev.site == site and ev.triggers(hit, rng):
                    self.injected[site] += 1
                    return ev.arg
        return None

    def snapshot(self) -> dict[str, int]:
        """Per-site injected counts plus the total (metrics scrape)."""
        with self._lock:
            out = {f"injected_{site}": n for site, n in
                   sorted(self.injected.items())}
            out["injected_total"] = sum(self.injected.values())
            return out


# -- process-global injector --------------------------------------------
# Checkpoint loading runs before any engine exists (and must not import
# the serving stack), so installing an injector wires the engine-less
# injection points through hooks owned by THEIR modules — the dependency
# points serve → utils, never back.  Installed by the CLI when
# --chaos-spec / LLMTPU_CHAOS_SPEC is set; tests install and uninstall
# around themselves.
_ACTIVE: FaultInjector | None = None


def install(injector: FaultInjector | None) -> None:
    global _ACTIVE
    _ACTIVE = injector
    from llm_np_cp_tpu.utils import loading

    if injector is None:
        loading.SHARD_READ_HOOK = None
    else:
        def _ckpt_read_hook(path) -> None:
            if injector.trip("ckpt_read") is not None:
                raise OSError(
                    f"chaos: injected transient read error on {path.name}"
                )

        loading.SHARD_READ_HOOK = _ckpt_read_hook


def active() -> FaultInjector | None:
    return _ACTIVE
