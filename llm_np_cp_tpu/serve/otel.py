"""OTLP/HTTP span export: ship the serve trace plane to a real
collector.

The ``TraceRecorder`` timeline has so far only left the process as a
Chrome trace-event dump (``--trace-out`` / ``GET /debug/trace``) — fine
for one operator staring at one file, useless for a fleet whose traces
should land in the collector the rest of the infrastructure already
ships to.  This module closes the carried ROADMAP item with a
stdlib-only OTLP/HTTP **JSON** exporter (the OpenTelemetry protocol's
``application/json`` encoding, POSTed to ``<endpoint>`` — typically
``http://collector:4318/v1/traces``):

- ``OtlpExporter.offer(event)`` — the recorder's sink hook: every
  event the recorder keeps is ALSO enqueued here (one lock-protected
  list append; the recorder guards the call with the standard is-None
  check, so no exporter = zero overhead, pinned by tools/lint R4).
- a dedicated WRITER THREAD (the journal/request-log ownership shape,
  machine-checked by lint R3's ``otel`` domain) drains the queue in
  batches, converts trace events to OTLP ``ResourceSpans``, and POSTs
  them with ``urllib`` — a slow or dead collector shows up as dropped
  batches and a counter, never as tick or event-loop latency
  (faults-site discipline: telemetry degradation is not an outage).
  The pending queue is bounded (``pending_max``): a HUNG collector —
  blackholed, not refused, so every POST eats the full timeout — makes
  ``offer`` drop-and-count instead of growing memory without bound.

Conversion rules (lossy by design — OTLP has spans, not Perfetto's
event zoo):

- ``ph: X`` complete slices → spans with the slice's start/end.
- ``ph: b``/``e`` async request phases → spans matched per
  ``(id, name)`` by the writer thread (its ``_wopen`` map); an
  unmatched ``b`` at close exports as a zero-length span rather than
  vanishing.
- ``ph: i``/``n`` instants → zero-length spans with an
  ``llm.instant: true`` attribute (``finish``/``anomaly``/
  ``lifecycle-action`` markers survive the trip).
- metadata events (``ph: M``) are skipped.
- span ``traceId``: the event's W3C ``args.trace`` when present (the
  SAME 32-hex id the journal/request-log/merge plane uses — a request
  routed, killed, replayed, and drained lands in the collector as one
  trace), else a per-process synthetic trace id so tick-phase spans
  group under one service timeline.
- timestamps: the recorder's µs-since-epoch rebased onto its
  ``wall_epoch`` anchor → Unix nanos, the same rebasing
  ``summarize_trace --merge`` does.

THREAD SAFETY: ``offer`` may be called from any thread (it runs under
the recorder's lock); the pending queue and counters are lock-
protected, the open-span map and HTTP plumbing are writer-thread-owned
(R3 ``otel`` domain).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request
from typing import Any

_HEX32 = re.compile(r"^[0-9a-f]{32}$")


def _otlp_value(v: Any) -> dict[str, Any]:
    """One OTLP AnyValue."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _attrs(pairs: dict[str, Any]) -> list[dict[str, Any]]:
    return [
        {"key": k, "value": _otlp_value(v)} for k, v in pairs.items()
    ]


class OtlpExporter:
    """Batched, drop-on-failure OTLP/HTTP JSON span exporter.

    Engine/recorder-side API: ``offer(event)`` (enqueue only, no IO).
    Control: ``flush()`` (barrier: everything offered before the call
    has been attempted against the collector), ``close()``,
    ``stats()``.
    """

    def __init__(
        self,
        endpoint: str,
        *,
        service_name: str = "llm-serve",
        resource_attrs: dict[str, Any] | None = None,
        wall_epoch: float | None = None,
        batch_max: int = 512,
        pending_max: int = 65536,
        flush_interval_s: float = 1.0,
        timeout_s: float = 5.0,
    ) -> None:
        if not endpoint:
            raise ValueError("otlp endpoint must be a non-empty URL")
        if batch_max < 1:
            raise ValueError(f"batch_max must be >= 1, got {batch_max}")
        if pending_max < 1:
            raise ValueError(
                f"pending_max must be >= 1, got {pending_max}"
            )
        self.endpoint = endpoint
        self.timeout_s = timeout_s
        self.batch_max = batch_max
        self.pending_max = pending_max
        self.flush_interval_s = flush_interval_s
        # µs-since-recorder-epoch → Unix nanos anchor; attach() copies
        # the recorder's own wall anchor so exported spans line up with
        # summarize_trace --merge timelines
        self.wall_epoch = wall_epoch if wall_epoch is not None \
            else time.time()
        self._resource = {
            "attributes": _attrs({
                "service.name": service_name,
                "process.pid": os.getpid(),
                **(resource_attrs or {}),
            }),
        }
        # synthetic trace id for events with no W3C id of their own
        # (tick phases, lifecycle instants): one service-level trace
        # per process
        self._proc_trace_id = os.urandom(16).hex()
        # shared under _lock: the pending queue and the stats counters
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: list = []
        self._stopping = False
        self.n_spans = 0
        self.n_batches = 0
        self.n_dropped = 0
        self.n_export_errors = 0
        # writer-thread-owned from here on (R3 "otel" domain): open
        # async spans awaiting their ``e`` event
        self._wopen: dict[tuple, dict] = {}
        self._thread = threading.Thread(
            target=self._writer_loop, name="serve-otlp-exporter",
            daemon=True,
        )
        self._thread.start()

    # -- recorder-side hook (enqueue only, no IO) ----------------------
    def offer(self, event: dict[str, Any]) -> None:
        with self._lock:
            if self._stopping:
                return
            if len(self._pending) >= self.pending_max:
                # a HUNG collector (blackholed, not refused) blocks the
                # writer in its POST timeout while the engine keeps
                # producing; the queue must not grow without bound —
                # drop-and-count, like every other degradation here
                self.n_dropped += 1
                return
            self._pending.append(event)
            if len(self._pending) >= self.batch_max:
                self._cond.notify()

    def attach(self, tracer: Any) -> "OtlpExporter":
        """Wire this exporter as ``tracer``'s sink (idempotent helper
        for the CLI): adopts the recorder's wall anchor so span
        timestamps and merged trace timelines agree."""
        self.wall_epoch = tracer.wall_epoch
        tracer.otel = self
        return self

    # -- control -------------------------------------------------------
    def flush(self, timeout: float = 10.0) -> bool:
        ev = threading.Event()
        with self._lock:
            if self._stopping and self._thread.is_alive() is False:
                return True
            self._pending.append(("flush", ev))
            self._cond.notify()
        return ev.wait(timeout)

    def close(self, timeout: float = 10.0) -> None:
        with self._lock:
            if self._stopping:
                return
            self._stopping = True
            self._cond.notify()
        self._thread.join(timeout)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "spans": self.n_spans,
                "batches": self.n_batches,
                "dropped": self.n_dropped,
                "export_errors": self.n_export_errors,
            }

    # -- writer thread (R3 "otel" domain) ------------------------------
    def _writer_loop(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._stopping:
                    self._cond.wait(self.flush_interval_s)
                batch, self._pending = self._pending, []
                stopping = self._stopping
            if batch:
                self._writer_batch(batch)
            if stopping:
                with self._lock:
                    leftover, self._pending = self._pending, []
                if leftover:
                    self._writer_batch(leftover)
                # unmatched async begins: export as zero-length spans
                # rather than losing the request's last phase
                tails = [
                    self._span_from(ev, ev["ts"], ev["ts"])
                    for ev in self._wopen.values()
                ]
                self._wopen.clear()
                if tails:
                    self._export(tails)
                return

    def _writer_batch(self, batch: list) -> None:
        spans: list[dict] = []
        barriers = []
        for item in batch:
            if not isinstance(item, dict):
                barriers.append(item[1])
                continue
            span = self._convert(item)
            if span is not None:
                spans.append(span)
        # ship in bounded slices so one huge drain cannot build an
        # unbounded request body
        for i in range(0, len(spans), self.batch_max):
            self._export(spans[i:i + self.batch_max])
        for ev in barriers:
            ev.set()

    def _convert(self, ev: dict[str, Any]) -> dict | None:
        ph = ev.get("ph")
        if ph == "X":
            ts = ev.get("ts", 0.0)
            return self._span_from(ev, ts, ts + ev.get("dur", 0.0))
        if ph == "b":
            self._wopen[(ev.get("id"), ev.get("name"))] = ev
            return None
        if ph == "e":
            begin = self._wopen.pop((ev.get("id"), ev.get("name")), None)
            if begin is None:
                return None  # end without a begin (ring displaced it)
            return self._span_from(begin, begin.get("ts", 0.0),
                                   ev.get("ts", 0.0))
        if ph in ("i", "n"):
            ts = ev.get("ts", 0.0)
            return self._span_from(ev, ts, ts, instant=True)
        return None  # metadata / counter events

    def _span_from(self, ev: dict[str, Any], t0_us: float,
                   t1_us: float, *, instant: bool = False) -> dict:
        args = ev.get("args") or {}
        trace = args.get("trace")
        trace_id = (
            trace if isinstance(trace, str) and _HEX32.match(trace)
            else self._proc_trace_id
        )
        attrs: dict[str, Any] = {"llm.cat": ev.get("cat", "")}
        if ev.get("id") is not None:
            attrs["llm.rid"] = ev["id"]
        if instant:
            attrs["llm.instant"] = True
        for k, v in args.items():
            if k != "trace":
                attrs[f"llm.{k}"] = v
        base_ns = self.wall_epoch * 1e9
        return {
            "traceId": trace_id,
            "spanId": os.urandom(8).hex(),
            "name": str(ev.get("name", "?")),
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(int(base_ns + t0_us * 1e3)),
            "endTimeUnixNano": str(int(base_ns + max(t1_us, t0_us) * 1e3)),
            "attributes": _attrs(attrs),
        }

    def _export(self, spans: list[dict]) -> None:
        if not spans:
            return
        payload = {
            "resourceSpans": [{
                "resource": self._resource,
                "scopeSpans": [{
                    "scope": {"name": "llm_np_cp_tpu.serve"},
                    "spans": spans,
                }],
            }],
        }
        req = urllib.request.Request(
            self.endpoint,
            data=json.dumps(payload, separators=(",", ":")).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
        except (urllib.error.URLError, OSError, ValueError):
            # collector down/slow/misconfigured: telemetry degradation,
            # never an outage — drop the batch and count it
            with self._lock:
                self.n_export_errors += 1
                self.n_dropped += len(spans)
            return
        with self._lock:
            self.n_spans += len(spans)
            self.n_batches += 1
