"""Continuous-batching serving engine over a paged KV block pool.

The layers below this package are batch-job shaped: ``Generator`` takes
one fixed batch and sizes a contiguous cache slab per call.  Serving
"heavy traffic from millions of users" (ROADMAP north star) needs the
request level instead: a queue, admission control, and a shared KV pool
whose granularity is a *block*, not a whole request — the design argued
by *Ragged Paged Attention* (PAPERS.md) for TPU inference.

Modules:
- ``block_pool``  — fixed-size KV blocks in one preallocated slab per
  layer, a free-list allocator, per-request block tables (int8 blocks
  reuse cache.quantize_kv/dequantize_kv).
- ``scheduler``   — continuous batching: admit queued requests into
  decode slots as others finish, evict-on-OOM with requeue; pure
  Python/NumPy, so policies are testable without a model.
- ``engine``      — ``ServeEngine``: jit-stable prefill/decode steps
  over the packed active batch with per-request streaming callbacks;
  decode K/V access is gathered through block tables (``"xla"``/
  ``"flash_decode"``) or zero-gather via the block-table-native Pallas
  kernel (``"paged"``).
- ``prefix_cache`` — refcounted prompt-prefix block sharing: chained
  content hashes → pool block ids, claimed at admission so matching
  prefill chunks are skipped entirely.
- ``spec``        — host-side draft streams for speculative serving
  (``DraftState``: prompt-lookup n-gram drafting over each request's
  own token history); the unified tick packs the drafts as ragged
  verify slices into its ONE dispatch and accepts the longest prefix
  matching the deterministic (seed, content-pos) samples — accepted
  streams are token-identical to plain decode.
- ``faults``      — deterministic, seeded fault injection
  (``FaultInjector``): chaos specs schedule decode/prefill faults, hung
  or crashed ticks, transient checkpoint IO errors, and HTTP
  resets/429s through injection points threaded across the stack;
  no-op (one is-None check) by default.
- ``metrics``     — queue depth, TTFT, per-request decode tok/s, pool
  occupancy, preemptions, aborts/rejects, prefix hit-rate, K/V bytes per
  tick, per-request queue-wait/prefill phase splits; exported as a dict
  and as Prometheus text with real TTFT/decode-rate histograms
  (thread-safe copy-on-read snapshots — the HTTP scrape handler reads
  while the engine thread writes).
- ``tracing``     — request-lifecycle spans (queued → prefill → decode
  → finish, with eviction/recovery annotations) and per-tick phase
  slices as Chrome/Perfetto trace-event JSON (``TraceRecorder``);
  zero-overhead is-None hooks when off, ring-buffered for the
  ``GET /debug/trace`` endpoint, dumped via ``--trace-out``.
- ``journal``     — durable request journal (``RequestJournal``):
  admissions, per-tick delivery watermarks, and terminals CRC-framed
  and fsync'd off the tick thread; a killed process (``kill -9``, OOM,
  rolling deploy) replays unterminated requests token-identically on
  restart, and clients resume dropped SSE streams via
  ``Last-Event-ID``; zero-overhead is-None hooks when off.
- ``slo``         — SLO goodput accounting (``SLOPolicy``/
  ``SLOTracker``: attainment, goodput_tok_s, multi-window error-budget
  burn rates) and the ``TickSentinel`` per-phase anomaly detector;
  zero-overhead is-None hooks when off.
- ``telemetry``   — device roofline telemetry (``TelemetryModel``): an
  analytic per-tick byte/FLOP model (weights streamed per dispatch, KV
  read/written from the planned tick composition, int8-aware) combined
  with the measured dispatch wall → achieved GB/s, utilization vs the
  HBM roofline, an MFU estimate, and per-request cost attribution
  (exact KV bytes + token-share of weights/device time, conserving);
  zero-overhead is-None hooks when off.
- ``otel``        — stdlib OTLP/HTTP JSON span export
  (``OtlpExporter``): converts ``TraceRecorder`` events to OTLP
  ResourceSpans and ships them off-thread to a collector, batched,
  drop-and-count on failure.
- ``request_log`` — the canonical request log (``RequestLog``): one
  wide-event JSON line per terminal request (trace id, route, prefix
  reuse, survival lineage, per-phase latencies, SLO verdict), written
  off the tick thread with the journal's writer discipline.
- ``tenants``     — multi-tenant accounting (``TenantLedger``):
  per-tenant request/token/device-cost totals, per-tenant SLO burn,
  fair-share prefill ordering, per-tenant in-flight caps, and
  bounded-cardinality tenant-labeled Prometheus series; ``X-Tenant-Id``
  identities normalized through ``normalize_tenant``; zero-overhead
  is-None hooks when off.
- ``replica``     — mesh-scale-out: ``ReplicaSet``/``ReplicaRunner``
  run N data-parallel engine replicas (each optionally TP-sharded via
  ``ServeEngine(mesh_plan=...)`` on its own mesh slice) behind a
  ``PrefixRouter`` that keys on the prefix cache's chained content
  hash, so shared-prompt traffic lands on the replica already holding
  its blocks; spill-to-least-loaded under queue pressure, per-replica
  abort/drain/supervised recovery.
- ``lifecycle``   — zero-downtime fleet operations: rolling checkpoint
  upgrades (drain-to-peer, clone_fresh on new weights, compiled steps
  re-jitted once per fleet, per-request weight-version tagging),
  elastic add/remove replicas with an optional ``Autoscaler`` policy,
  and the ``ActionPolicy`` closing the loop from sentinel/SLO signals
  to shed-prefill and 503-first load-shedding auto-actions.
- ``http``        — the OpenAI-compatible streaming HTTP front-end
  (``serve`` CLI subcommand): SSE token streams, abort on disconnect or
  deadline, 429 backpressure off the scheduler's queue cap, Prometheus
  ``/metrics``, SIGTERM drain.
"""

from llm_np_cp_tpu.serve.block_pool import BlockPool, FreeList
from llm_np_cp_tpu.serve.faults import FaultInjected, FaultInjector
from llm_np_cp_tpu.serve.engine import (
    ServeEngine,
    pool_geometry,
    worst_case_slots,
)
from llm_np_cp_tpu.serve.journal import RequestJournal, scan_journal
from llm_np_cp_tpu.serve.lifecycle import (
    ActionPolicy,
    Autoscaler,
    LifecycleController,
    UpgradeAborted,
)
from llm_np_cp_tpu.serve.metrics import ServeMetrics
from llm_np_cp_tpu.serve.otel import OtlpExporter
from llm_np_cp_tpu.serve.prefix_cache import PrefixCache, prefix_block_keys
from llm_np_cp_tpu.serve.request_log import RequestLog, read_request_log
from llm_np_cp_tpu.serve.slo import (
    SLOPolicy,
    SLOTracker,
    TickSentinel,
    aggregate_slo,
)
from llm_np_cp_tpu.serve.replica import (
    PrefixRouter,
    ReplicaRunner,
    ReplicaSet,
)
from llm_np_cp_tpu.serve.scheduler import (
    QueueFull,
    Request,
    RequestState,
    Scheduler,
    TenantThrottled,
)
from llm_np_cp_tpu.serve.spec import DraftState
from llm_np_cp_tpu.serve.telemetry import TelemetryModel
from llm_np_cp_tpu.serve.tenants import (
    TenantLedger,
    aggregate_tenants,
    normalize_tenant,
)
from llm_np_cp_tpu.serve.trace import poisson_trace
from llm_np_cp_tpu.serve.tracing import TraceRecorder

__all__ = [
    "ActionPolicy",
    "Autoscaler",
    "BlockPool",
    "DraftState",
    "LifecycleController",
    "UpgradeAborted",
    "FaultInjected",
    "FaultInjector",
    "FreeList",
    "OtlpExporter",
    "PrefixCache",
    "PrefixRouter",
    "QueueFull",
    "ReplicaRunner",
    "ReplicaSet",
    "Request",
    "RequestJournal",
    "RequestLog",
    "RequestState",
    "SLOPolicy",
    "SLOTracker",
    "Scheduler",
    "ServeEngine",
    "ServeMetrics",
    "TelemetryModel",
    "TenantLedger",
    "TenantThrottled",
    "TickSentinel",
    "TraceRecorder",
    "aggregate_slo",
    "aggregate_tenants",
    "normalize_tenant",
    "poisson_trace",
    "pool_geometry",
    "prefix_block_keys",
    "read_request_log",
    "scan_journal",
    "worst_case_slots",
]
