"""Multi-tenant accounting: per-tenant cost metering, SLO burn, and
the fair-share admission signal.

The observability stack below this module — the PR 10 SLO/request-log
plane and the PR 13 roofline cost attribution — aggregates everything
into one anonymous pool.  This module adds the tenant dimension on top
of those EXISTING ledgers (grounding: the fused tick stays intact —
tenancy is host-side bookkeeping over per-request cost fields the
telemetry model already fills; it adds zero dispatches, zero host
syncs, zero step compiles):

- ``normalize_tenant`` — the ONE validator every surface shares.
  Tenant strings originate from untrusted HTTP headers, so the charset
  is whitelisted to ``[A-Za-z0-9._-]`` and the length capped: a string
  that passes is Prometheus-label-safe and JSON-safe by construction,
  and the scrape/request-log emitters never need escaping.
- ``TenantLedger`` — per-engine accounting, fed at request terminals
  (``on_terminal``) and admission throttles (``on_throttle``):
  requests/tokens/finish-reasons, the four PR 13 cost-attribution
  fields summed per tenant (conservation against the global
  ``ServeMetrics`` ledgers is test-pinned), and a lazy per-tenant
  ``SLOTracker`` (attainment, goodput, 5m/1h burn) when a policy is
  attached.  ``cost_shares`` is the admission-control read: the
  fairness sort key ``ServeEngine._fair_prefill_order`` feeds
  ``Scheduler.plan_tick``.
- ``aggregate_tenants`` — fleet aggregation for ``ReplicaSet.snapshot``
  and ``GET /debug/tenants``: summed counters, per-tenant burn rates
  recomputed from summed window totals (the ``aggregate_slo``
  discipline).

ZERO-OVERHEAD WHEN OFF (the R4 guarded-hook discipline): the engine's
``tenants`` attribute is ``None`` unless ``--tenants`` (or a fairness/
cap flag) asked for it, and every hook sits behind an ``is None``
check.  Cardinality is bounded: the Prometheus exposition emits the
top-``max_series`` tenants by accumulated cost and rolls the rest into
one ``tenant="other"`` labelset, so a tenant-id cardinality attack
cannot blow up the scrape.

THREAD SAFETY (R3): ``TenantLedger`` counters are mutated under its
own ``_lock`` — terminals land from the engine tick thread while the
scrape/debug endpoints read from the asyncio thread (the
``ServeMetrics`` discipline).  ``clone_fresh`` carries the ledger
across supervised restarts (a restart IS the same replica), and the
supervisor zombie-mutes it exactly like the metrics object.
"""

from __future__ import annotations

import string
import threading
import time
from collections import Counter
from typing import Any, Callable, Iterable

from llm_np_cp_tpu.serve.slo import SLOPolicy, SLOTracker, aggregate_slo

DEFAULT_TENANT = "default"
#: Hard cap on tenant-id length; also the charset whitelist below.
#: Everything that passes is Prometheus-label- and JSON-safe verbatim.
TENANT_MAX_LEN = 64
_TENANT_CHARS = frozenset(string.ascii_letters + string.digits + "._-")

#: The rollup label for tenants past the top-``max_series`` by cost.
OTHER_TENANT = "other"


def normalize_tenant(value: Any) -> str:
    """Validate/normalize one tenant id from an untrusted source.

    ``None`` and ``""`` mean "no tenant" → ``"default"``.  Anything
    else must be a string of at most ``TENANT_MAX_LEN`` characters
    drawn from ``[A-Za-z0-9._-]`` — the intersection of what Prometheus
    label values, JSON strings, and log lines can carry verbatim.
    Raises ``ValueError`` with an actionable message otherwise (the
    HTTP layer maps it to a 400)."""
    if value is None or value == "":
        return DEFAULT_TENANT
    if not isinstance(value, str):
        raise ValueError(
            f"tenant must be a string, got {type(value).__name__}"
        )
    if len(value) > TENANT_MAX_LEN:
        raise ValueError(
            f"tenant id exceeds {TENANT_MAX_LEN} characters "
            f"({len(value)})"
        )
    bad = set(value) - _TENANT_CHARS
    if bad:
        shown = "".join(sorted(bad))
        raise ValueError(
            f"tenant id contains disallowed characters {shown!r} "
            "(allowed: letters, digits, '.', '_', '-')"
        )
    return value


def _fresh_entry() -> dict[str, Any]:
    return {
        "requests": 0,
        "tokens": 0,
        "finish_reasons": Counter(),
        "kv_bytes_read": 0.0,
        "kv_bytes_written": 0.0,
        "weight_bytes_amortized": 0.0,
        "device_time_s": 0.0,
        "throttled": 0,
    }


class TenantLedger:
    """Per-engine multi-tenant accounting (see module docstring).

    ``fairness`` / ``max_inflight`` are read by the engine's admission
    paths (plain attribute reads — config, not state); the mutable
    counters live in ``_tenants`` under ``_lock``.
    """

    def __init__(
        self,
        *,
        fairness: bool = False,
        max_inflight: int | None = None,
        max_series: int = 20,
        policy: SLOPolicy | None = None,
        clock: Callable[[], float] = time.perf_counter,
    ) -> None:
        if max_inflight is not None and max_inflight < 1:
            raise ValueError(
                f"tenant max_inflight must be >= 1, got {max_inflight}"
            )
        if max_series < 1:
            raise ValueError(
                f"max_series must be >= 1, got {max_series}"
            )
        self.fairness = bool(fairness)
        self.max_inflight = max_inflight
        self.max_series = max_series
        self.policy = policy
        self.clock = clock
        self._lock = threading.Lock()
        self._tenants: dict[str, dict[str, Any]] = {}
        self._slo: dict[str, SLOTracker] = {}

    # -- write (engine tick thread) ------------------------------------
    def _entry(self, tenant: str) -> dict[str, Any]:
        ent = self._tenants.get(tenant)
        if ent is None:
            ent = self._tenants[tenant] = _fresh_entry()
        return ent

    def on_terminal(self, req: Any) -> None:
        """Fold one terminal request into its tenant's ledger — called
        right after ``ServeMetrics.on_finish``/``on_abort`` so the
        per-tenant sums and the global ledgers see the same stream of
        terminals (conservation is test-pinned)."""
        tenant = getattr(req, "tenant", DEFAULT_TENANT)
        with self._lock:
            ent = self._entry(tenant)
            ent["requests"] += 1
            ent["tokens"] += len(req.generated)
            ent["finish_reasons"][req.finish_reason or "unknown"] += 1
            ent["kv_bytes_read"] += req.kv_bytes_read
            ent["kv_bytes_written"] += req.kv_bytes_written
            ent["weight_bytes_amortized"] += req.weight_bytes_amortized
            ent["device_time_s"] += req.device_time_s
            if self.policy is not None:
                tracker = self._slo.get(tenant)
                if tracker is None:
                    tracker = self._slo[tenant] = SLOTracker(
                        self.policy, clock=self.clock)
                tracker.observe(req)

    def on_throttle(self, tenant: str) -> None:
        """Count one per-tenant admission rejection (429)."""
        with self._lock:
            self._entry(tenant)["throttled"] += 1

    # -- admission-control read (engine tick thread) -------------------
    def cost_shares(
        self, live: Iterable[Any], *, use_bytes: bool = False,
    ) -> dict[str, float]:
        """Per-tenant accumulated cost — terminal totals plus the live
        requests' in-progress cost — the fairness sort key.  With
        telemetry attached (``use_bytes``) cost is device bytes + the
        amortized weight stream; otherwise processed tokens stand in
        (prefill progress + generated).  Raw sums, not normalized: the
        caller only orders by them."""
        with self._lock:
            if use_bytes:
                costs = {
                    t: e["kv_bytes_read"] + e["kv_bytes_written"]
                    + e["weight_bytes_amortized"]
                    for t, e in self._tenants.items()
                }
            else:
                costs = {
                    t: float(e["tokens"])
                    for t, e in self._tenants.items()
                }
        for req in live:
            tenant = getattr(req, "tenant", DEFAULT_TENANT)
            if use_bytes:
                cost = (req.kv_bytes_read + req.kv_bytes_written
                        + req.weight_bytes_amortized)
            else:
                cost = float(req.prefill_done + len(req.generated))
            costs[tenant] = costs.get(tenant, 0.0) + cost
        return costs

    # -- read (scrape / debug endpoints, any thread) -------------------
    def _cost(self, ent: dict[str, Any]) -> float:
        return (ent["kv_bytes_read"] + ent["kv_bytes_written"]
                + ent["weight_bytes_amortized"])

    def snapshot(self) -> dict[str, Any]:
        """Point-in-time per-tenant view: counters, cost fields, cost
        share of the whole ledger, and the SLO snapshot when a policy
        is attached."""
        with self._lock:
            tenants = {
                t: dict(e, finish_reasons=dict(e["finish_reasons"]))
                for t, e in self._tenants.items()
            }
            slo = {t: tr.snapshot() for t, tr in self._slo.items()}
        total_cost = sum(self._cost(e) for e in tenants.values())
        total_tokens = sum(e["tokens"] for e in tenants.values())
        for t, ent in tenants.items():
            cost = self._cost(ent)
            # bytes when telemetry metered them, else token share — the
            # same fallback the fairness sort uses
            ent["cost_share"] = (
                cost / total_cost if total_cost > 0
                else ent["tokens"] / total_tokens if total_tokens > 0
                else 0.0
            )
            if t in slo:
                ent["slo"] = slo[t]
        return {
            "n_tenants": len(tenants),
            "tenants": tenants,
        }

    def slo_trackers(self) -> dict[str, SLOTracker]:
        """Per-tenant trackers (for fleet aggregation)."""
        with self._lock:
            return dict(self._slo)

    # -- Prometheus exposition -----------------------------------------
    def prometheus(self, prefix: str = "llm_serve",
                   const_labels: dict[str, str] | None = None) -> str:
        """Tenant-labeled series, cardinality-bounded: the top
        ``max_series`` tenants by accumulated cost keep their own
        labelsets; everything past that rolls up into
        ``tenant="other"`` (counters still conserve — the rollup sums,
        it never drops)."""
        snap = self.snapshot()["tenants"]
        ranked = sorted(
            snap.items(),
            key=lambda kv: (-self._cost(kv[1]), -kv[1]["tokens"], kv[0]),
        )
        keep = ranked[: self.max_series]
        overflow = ranked[self.max_series:]
        if overflow:
            other = _fresh_entry()
            for _, ent in overflow:
                for key in ("requests", "tokens", "kv_bytes_read",
                            "kv_bytes_written", "weight_bytes_amortized",
                            "device_time_s", "throttled"):
                    other[key] += ent[key]
            keep = keep + [(OTHER_TENANT, other)]

        extra = "".join(
            f',{k}="{v}"' for k, v in (const_labels or {}).items()
        )
        lines: list[str] = []

        def emit(name: str, mtype: str, help_: str,
                 samples: list[tuple[str, float]]) -> None:
            if not samples:
                return
            full = f"{prefix}_{name}"
            lines.append(f"# HELP {full} {help_}")
            lines.append(f"# TYPE {full} {mtype}")
            for tenant, value in samples:
                lines.append(
                    f'{full}{{tenant="{tenant}"{extra}}} {value:.10g}'
                )

        emit("tenant_requests_total", "counter",
             "Terminal requests per tenant",
             [(t, float(e["requests"])) for t, e in keep])
        emit("tenant_tokens_total", "counter",
             "Generated tokens per tenant",
             [(t, float(e["tokens"])) for t, e in keep])
        emit("tenant_device_bytes_total", "counter",
             "Attributed device bytes per tenant (KV read+write + "
             "amortized weight stream)",
             [(t, self._cost(e)) for t, e in keep])
        emit("tenant_device_time_total", "counter",
             "Attributed device seconds per tenant",
             [(t, e["device_time_s"]) for t, e in keep])
        throttled = [(t, float(e["throttled"]))
                     for t, e in keep if e["throttled"]]
        emit("tenant_throttled_total", "counter",
             "Admissions rejected by the per-tenant in-flight cap",
             throttled)
        if self.policy is not None:
            slo_keep = [(t, e["slo"]) for t, e in keep if "slo" in e]
            emit("tenant_slo_ok_total", "counter",
                 "SLO-attaining terminals per tenant",
                 [(t, float(s["slo_ok"])) for t, s in slo_keep])
            emit("tenant_slo_miss_total", "counter",
                 "SLO-missing terminals per tenant",
                 [(t, float(s["slo_miss"])) for t, s in slo_keep])
            emit("tenant_slo_attainment", "gauge",
                 "Fraction of timed terminals meeting the SLO, per "
                 "tenant",
                 [(t, s["slo_attainment"]) for t, s in slo_keep
                  if "slo_attainment" in s])
            emit("tenant_slo_goodput_tokens_total", "counter",
                 "Tokens of SLO-attaining requests per tenant",
                 [(t, float(s["goodput_tokens"])) for t, s in slo_keep])
            for label in ("5m", "1h"):
                key = f"slo_burn_rate_{label}"
                emit(f"tenant_{key}", "gauge",
                     f"Per-tenant SLO error-budget burn rate ({label} "
                     "window)",
                     [(t, s[key]) for t, s in slo_keep if key in s])
        return "\n".join(lines) + "\n" if lines else ""


def aggregate_tenants(
    ledgers: list["TenantLedger | None"],
) -> dict[str, Any]:
    """Fleet aggregation for ``ReplicaSet.snapshot`` and
    ``GET /debug/tenants``: per-tenant counters summed across replicas,
    SLO attainment/burn recomputed from the summed window totals via
    ``aggregate_slo`` (never a mean of per-replica ratios)."""
    live = [led for led in ledgers if led is not None]
    if not live:
        return {}
    merged: dict[str, dict[str, Any]] = {}
    trackers: dict[str, list[SLOTracker]] = {}
    for led in live:
        snap = led.snapshot()["tenants"]
        for tenant, ent in snap.items():
            agg = merged.get(tenant)
            if agg is None:
                agg = merged[tenant] = _fresh_entry()
                agg["finish_reasons"] = {}
            for key in ("requests", "tokens", "kv_bytes_read",
                        "kv_bytes_written", "weight_bytes_amortized",
                        "device_time_s", "throttled"):
                agg[key] += ent[key]
            for reason, n in ent["finish_reasons"].items():
                agg["finish_reasons"][reason] = (
                    agg["finish_reasons"].get(reason, 0) + n
                )
        for tenant, tracker in led.slo_trackers().items():
            trackers.setdefault(tenant, []).append(tracker)
    total_cost = sum(
        e["kv_bytes_read"] + e["kv_bytes_written"]
        + e["weight_bytes_amortized"] for e in merged.values()
    )
    total_tokens = sum(e["tokens"] for e in merged.values())
    for tenant, ent in merged.items():
        cost = (ent["kv_bytes_read"] + ent["kv_bytes_written"]
                + ent["weight_bytes_amortized"])
        ent["cost_share"] = (
            cost / total_cost if total_cost > 0
            else ent["tokens"] / total_tokens if total_tokens > 0
            else 0.0
        )
        per_tenant = trackers.get(tenant)
        if per_tenant:
            slo = aggregate_slo(list(per_tenant))
            slo.pop("policy", None)
            ent["slo"] = slo
    return {
        "n_tenants": len(merged),
        "tenants": merged,
    }
