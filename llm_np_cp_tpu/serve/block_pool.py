"""Paged KV cache: fixed-size blocks in one preallocated slab per layer.

A contiguous ``KVCache`` reserves ``max_seq_len`` slots per request up
front — at serving concurrency most of that is empty tail.  The pool
instead preallocates ONE slab of ``num_blocks`` fixed-size blocks per
layer and hands requests blocks on demand through a free list; a
request's cache is its *block table* (list of block ids), so fragments
left by finished requests are reusable immediately and admission control
reduces to counting free blocks.

Layout (the contiguous cache's [L, B, S, K, D] with S factored into
pages):

    k, v: [num_layers, num_blocks, block_size, kv_heads, head_dim]

Block 0 is RESERVED as a scratch block and never allocated: inactive
decode slots in the engine's fixed-width batch point their tables at it,
so the packed decode step can write unconditionally (no data-dependent
shapes) and garbage lands somewhere harmless.

int8 mode mirrors ``KVCache``'s quantized slabs: per-token-per-head
absmax scales (cache.quantize_kv layout) ride in parallel
``[L, NB, BS, K]`` f32 pages.

The allocator is host-side Python (a free list) — allocation happens at
scheduling time, between device steps, never under jit.  Blocks are
REFCOUNTED so prompt-prefix blocks can be shared across requests
(serve/prefix_cache.py): ``free`` is a decref and only a block's last
holder returns it to the free list.  The device-side pages are a pytree
(``PagedKV``) threaded through the engine's jitted steps and donated, so
slabs update in place.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from llm_np_cp_tpu.config import ModelConfig


class FreeList:
    """LIFO free-list allocator over block ids ``1..num_blocks-1``, with
    per-block refcounts for prefix sharing.

    Block 0 is the reserved scratch block (see module docstring).  LIFO
    reuse keeps recently-freed blocks hot (their slab pages are most
    likely still in cache on real hardware).  ``alloc`` hands out blocks
    at refcount 1; ``incref`` adds a sharer; ``free`` is a DECREF — a
    block returns to the free list only when its last reference drops,
    so a shared prefix block survives any one request's finish or
    eviction.  Pure Python so scheduler policies are testable without
    any device arrays.
    """

    def __init__(self, num_blocks: int) -> None:
        if num_blocks < 2:
            raise ValueError(
                f"need at least 2 blocks (1 reserved scratch), got {num_blocks}"
            )
        self.num_blocks = num_blocks
        self._free: list[int] = list(range(num_blocks - 1, 0, -1))
        self._ref: dict[int, int] = {}  # allocated block id → refcount

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        return len(self._ref)

    @property
    def capacity(self) -> int:
        """Allocatable blocks (excludes the reserved scratch block)."""
        return self.num_blocks - 1

    def refcount(self, block_id: int) -> int:
        """Current references on ``block_id`` (0 if free/unknown)."""
        return self._ref.get(block_id, 0)

    def alloc(self, n: int) -> list[int] | None:
        """Pop ``n`` blocks at refcount 1, or None (and no change) if
        not enough free."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        for i in ids:
            self._ref[i] = 1
        return ids

    def incref(self, ids: list[int]) -> None:
        """Add one reference per block (a new sharer of a prefix block).
        Only allocated blocks can gain references."""
        for i in ids:
            if i not in self._ref:
                raise ValueError(f"incref on unallocated block id {i}")
        for i in ids:
            self._ref[i] += 1

    def free(self, ids: list[int]) -> None:
        """Drop one reference per block; blocks whose count hits zero
        return to the free list.  Releasing a block with no references
        is still a hard error (double free)."""
        for i in ids:
            if i not in self._ref:
                raise ValueError(f"double free or foreign block id {i}")
            self._ref[i] -= 1
            if self._ref[i] == 0:
                del self._ref[i]
                self._free.append(i)


class PagedKV(NamedTuple):
    """Device-side pages: the pytree the engine's jitted steps thread
    through (and donate).  Scales are None for float pools."""

    k: jnp.ndarray  # [L, NB, BS, K, D]
    v: jnp.ndarray  # [L, NB, BS, K, D]
    k_scale: jnp.ndarray | None = None  # [L, NB, BS, K] f32 (int8 mode)
    v_scale: jnp.ndarray | None = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[2]


class BlockPool:
    """Free-list allocator + the device slabs it allocates from.

    ``pages`` is rebound by the engine after every donated step; the
    pool object itself is host-side bookkeeping only.
    """

    def __init__(
        self,
        config: ModelConfig,
        num_blocks: int,
        block_size: int,
        dtype: jnp.dtype = jnp.bfloat16,
        enable_prefix_cache: bool = False,
        shardings: "PagedKV | None" = None,
    ) -> None:
        if block_size < 8 or block_size % 8:
            # Mosaic's second-minor alignment rule for the decode kernels;
            # also keeps gathered views compatible with select_block_s
            raise ValueError(f"block_size must be a multiple of 8, got {block_size}")
        self.config = config
        self.block_size = block_size
        self.dtype = jnp.dtype(dtype)
        self.free_list = FreeList(num_blocks)
        if enable_prefix_cache:
            from llm_np_cp_tpu.serve.prefix_cache import PrefixCache

            self.prefix_cache: PrefixCache | None = PrefixCache(self.free_list)
        else:
            self.prefix_cache = None
        shape = (
            config.num_hidden_layers,
            num_blocks,
            block_size,
            config.num_key_value_heads,
            config.head_dim,
        )
        quantized = self.dtype == jnp.int8
        self.pages = PagedKV(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            k_scale=jnp.zeros(shape[:-1], jnp.float32) if quantized else None,
            v_scale=jnp.zeros(shape[:-1], jnp.float32) if quantized else None,
        )
        # mesh-sharded mode: a PagedKV of NamedShardings (kv-head axis on
        # "model", see parallel/sharding.paged_kv_specs) commits the slabs
        # onto the mesh; the FREE LIST stays global — allocation is a
        # host-side decision and every shard holds the same block ids,
        # only a head-slice of each block's K/V
        self.shardings = shardings
        if shardings is not None:
            import jax

            self.pages = jax.tree.map(jax.device_put, self.pages, shardings)

    # -- accounting (delegates; the scheduler talks to these) ----------
    @property
    def num_blocks(self) -> int:
        return self.free_list.num_blocks

    @property
    def num_free(self) -> int:
        """Blocks available for allocation: the free list plus prefix-
        cache entries whose only reference is the cache's own (reclaimed
        on demand by ``alloc``) — shared blocks never double-count
        against pool capacity."""
        n = self.free_list.num_free
        if self.prefix_cache is not None:
            n += self.prefix_cache.n_reclaimable
        return n

    @property
    def capacity(self) -> int:
        return self.free_list.capacity

    @property
    def occupancy(self) -> float:
        """Fraction of allocatable blocks currently held by requests —
        the complement of ``num_free``, so cache-only (reclaimable)
        prefix blocks count as free here too, keeping the two admission
        metrics mutually consistent."""
        return (self.capacity - self.num_free) / max(self.capacity, 1)

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache slots."""
        return -(-n_tokens // self.block_size)

    def stats(self) -> dict[str, int]:
        """Point-in-time accounting for scrapes and tests: raw free-list
        state plus the prefix-cache split (``cache_only`` blocks are held
        solely by the cache's own reference and are reclaimable on
        demand).  ``request_held = allocated - cache_only`` is the number
        of blocks live requests actually pin — the quantity abort tests
        assert returns to zero."""
        allocated = self.free_list.num_allocated
        cache_only = (
            self.prefix_cache.n_reclaimable
            if self.prefix_cache is not None else 0
        )
        out = {
            "capacity": self.capacity,
            "free": self.free_list.num_free,
            "allocated": allocated,
            "cache_only": cache_only,
            "request_held": allocated - cache_only,
        }
        out.update(self.shard_stats())
        return out

    def shard_stats(self) -> dict[str, int]:
        """Per-shard KV slab accounting for scrapes and the serve banner.

        ``kv_bytes_shard`` is what ONE device holds (the whole slab when
        unsharded/replicated; a kv-head slice under TP); ``kv_shards`` is
        the number of distinct shards the slabs split into (1 when not
        sharded — replication is not a split).  Occupancy needs no
        per-shard variant: the free list is global and every shard holds
        the same block ids, so per-shard occupancy IS ``occupancy`` by
        construction — that invariant is the whole point of replicated
        block tables."""
        import math

        if self.pages is None:  # supervisor yanked the dead engine's slabs
            return {"kv_bytes_total": 0, "kv_bytes_shard": 0, "kv_shards": 1}
        arrs = [a for a in self.pages if a is not None]
        total = sum(a.nbytes for a in arrs)
        shard = 0
        for a in arrs:
            try:
                shape = a.sharding.shard_shape(a.shape)
            except (AttributeError, TypeError):
                shape = a.shape
            shard += math.prod(shape) * a.dtype.itemsize
        return {
            "kv_bytes_total": int(total),
            "kv_bytes_shard": int(shard),
            "kv_shards": max(int(round(total / shard)), 1) if shard else 1,
        }

    def alloc(self, n: int) -> list[int] | None:
        if (
            self.prefix_cache is not None
            and n > self.free_list.num_free
        ):
            # evict LRU cache-only entries to cover the shortfall
            self.prefix_cache.release(n - self.free_list.num_free)
        return self.free_list.alloc(n)

    def free(self, ids: list[int]) -> None:
        self.free_list.free(ids)
