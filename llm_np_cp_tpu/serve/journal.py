"""Durable request journal: survive ``kill -9``, not just engine death.

PR 4's supervised recovery replays in-flight requests token-identically,
but only from an IN-PROCESS ledger — an OOM-kill, a ``kill -9``, or a
rolling deploy still loses every in-flight stream (ROADMAP item 5).
This module is the crash-safe record that closes the gap: an
append-only, CRC-framed, fsync'd journal of what the engine admitted
and delivered, written OFF the tick thread, replayed on server start
through the existing teacher-forced ``ServeEngine.recover`` path.  The
deterministic (seed, content-position) sampling keys make the replayed
continuation provably token-identical, so the journal does not need a
synchronous fsync per token: ANY durable prefix of the delivered-token
stream resumes the exact same stream — lost tail tokens are simply
regenerated, bit-for-bit.

Three record types (JSON payloads in a ``[u32 len][u32 crc32]`` frame):

- **admission** (``adm``) — request id, prompt token ids, sampling
  params (seed, max_tokens), the absolute deadline converted to WALL
  time (engine clocks are process-local; wall time is the only clock a
  restart can resume a remaining budget against), and any pre-seeded
  tokens (a recovery re-admission journals its teacher-forced state, so
  a SECOND crash replays from the latest admission).
- **delivery watermark** (``wm``) — one record per TICK, not per token:
  ``[request id, delivered-through index, new token ids]`` rows for
  every request whose count advanced that tick.
- **terminal** (``fin``) — finish reason; a terminated request leaves
  the replay set (a clean SIGTERM drain aborts every straggler, so a
  clean shutdown leaves an EMPTY replay set).

Plus an ``epoch`` record per journal open (monotonic across restarts —
the restart count an operator can read straight off the file) and
periodic COMPACTION: when appended bytes since the last compaction pass
``compact_bytes``, the writer thread rewrites the file as one admission
record per live request (tokens folded in), so the journal's size is
bounded by the live set, not the traffic history.

Torn writes: a ``kill -9`` can land mid-record.  Replay verifies each
frame's length and CRC and stops at the first bad one; reopening
truncates the file back to the valid prefix before appending.

THREADING (machine-checked by tools/lint R3): the engine tick thread
owns the enqueue side (``admit``/``end_tick``/``terminal`` and the
``_mark`` delivered-count index); the WRITER THREAD (its own R3
domain) owns the file handle and the live-request mirror it compacts
from (``_wfile``/``_wlive``/``_wsince``); the pending queue and the
stats counters are shared under ``_lock``.

ZERO-OVERHEAD WHEN OFF (the FaultInjector/TraceRecorder discipline,
pinned by tools/lint R4): nothing constructs a journal unless
``--journal PATH`` is given, and every engine hook is a single
``is None`` check.

Chaos sites (serve/faults.py): ``journal_write`` / ``journal_fsync``
fail the corresponding IO deterministically — a journal IO error is a
DURABILITY degradation, never an outage: the batch is dropped, counted
in ``stats()``, and serving continues.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
from typing import Any, Callable, Iterator

import numpy as np

_HDR = struct.Struct("<II")  # payload length, crc32(payload)
_MAX_RECORD = 64 << 20  # sanity bound: a bigger "length" is torn garbage


def _crc(payload: bytes) -> int:
    import zlib

    return zlib.crc32(payload) & 0xFFFFFFFF


def _frame(rec: dict) -> bytes:
    payload = json.dumps(rec, separators=(",", ":")).encode()
    return _HDR.pack(len(payload), _crc(payload)) + payload


def _iter_frames(data: bytes) -> Iterator[tuple[dict, int]]:
    """Decode the valid frame prefix → ``(record, end offset)`` pairs,
    stopping at the first torn or corrupt frame.  The ONE framing
    decoder behind both ``iter_records`` and ``scan_journal`` — a
    framing change applied to one but not the other would make replay
    and the debug reader disagree about where the valid prefix ends."""
    off = 0
    while off + _HDR.size <= len(data):
        ln, crc = _HDR.unpack_from(data, off)
        if ln > _MAX_RECORD or off + _HDR.size + ln > len(data):
            return
        payload = data[off + _HDR.size: off + _HDR.size + ln]
        if _crc(payload) != crc:
            return
        try:
            rec = json.loads(payload)
        except ValueError:
            return
        off += _HDR.size + ln
        yield rec, off


def iter_records(path: str) -> Iterator[dict]:
    """Decode the journal's valid frame prefix (stops at the first torn
    or corrupt record — exactly the records replay would apply).  For
    tests and operator debugging; replay itself uses ``scan_journal``."""
    try:
        data = open(path, "rb").read()
    except FileNotFoundError:
        return
    for rec, _ in _iter_frames(data):
        yield rec


def _apply(state: dict[int, dict], rec: dict) -> int | None:
    """Fold one record into the live-request state; returns the epoch
    for ``epoch`` records.  The ONE state machine shared by replay and
    the writer's compaction mirror, so they cannot drift."""
    t = rec.get("t")
    if t == "epoch":
        return int(rec.get("n", 0))
    if t == "adm":
        # an admission OVERWRITES: a recovery re-admission carries the
        # full teacher-forced token state, superseding older records
        state[int(rec["rid"])] = {
            "rid": int(rec["rid"]),
            "prompt": list(rec["prompt"]),
            "max_tokens": int(rec["max_tokens"]),
            "seed": int(rec.get("seed", 0)),
            "deadline_wall": rec.get("deadline_wall"),
            "tokens": list(rec.get("tokens", ())),
            # trace continuity + survival lineage: a replay continues
            # the request's W3C trace and its replays/drains counters
            # (the canonical request log reports them)
            "trace": rec.get("trace"),
            "replays": int(rec.get("replays", 0)),
            "drains": int(rec.get("drains", 0)),
            # the request's speculative opt-in: a replay onto a
            # spec-enabled engine resumes drafting (tokens are identical
            # either way — this only preserves the throughput mode)
            "spec": bool(rec.get("spec", False)),
            # the weight version the request was ADMITTED under: a
            # replay (possibly onto a rolled engine) keeps reporting
            # the version that actually served the stream
            "wv": int(rec.get("wv", 0)),
            # tenancy survives kill -9: the replay re-admits under the
            # tenant that submitted it, so the bill lands on the right
            # ledger row after the crash too
            "tenant": rec.get("tenant", "default"),
        }
    elif t == "wm":
        for rid, n, toks in rec["rows"]:
            ent = state.get(int(rid))
            if ent is not None:
                ent["tokens"].extend(int(x) for x in toks)
                # defensive: the watermark names the authoritative count
                del ent["tokens"][int(n):]
    elif t == "fin":
        state.pop(int(rec["rid"]), None)
    return None


def scan_journal(path: str) -> tuple[dict[int, dict], int, int]:
    """→ ``(live unterminated requests by rid, valid byte prefix,
    last epoch)``.  Replay stops at the first torn/corrupt frame; the
    byte offset is where a reopening journal truncates to."""
    state: dict[int, dict] = {}
    epoch = 0
    try:
        data = open(path, "rb").read()
    except FileNotFoundError:
        return state, 0, 0
    off = 0
    for rec, end in _iter_frames(data):
        e = _apply(state, rec)
        if e is not None:
            epoch = max(epoch, e)
        off = end
    return state, off, epoch


class RequestJournal:
    """One journal file + one writer thread.

    Engine-thread API (every call is enqueue-only — no IO on the tick
    thread): ``admit(req, now)``, ``end_tick(requests)``,
    ``terminal(rid, reason)``.  Control: ``replay()`` (the unterminated
    state found at open), ``flush()`` (barrier: everything enqueued so
    far is written AND fsynced), ``close()``, ``stats()``.
    """

    def __init__(
        self,
        path: str,
        *,
        clock: Callable[[], float] = time.perf_counter,
        compact_bytes: int = 4 << 20,
        fsync: bool = True,
        sync_admissions: bool = False,
        fault_injector: Any = None,
    ) -> None:
        self.path = path
        self.clock = clock
        self.compact_bytes = compact_bytes
        self.fsync = fsync
        # strict mode (`serve --journal-sync admission`): ``admit``
        # blocks on a writer-thread flush barrier, so the admission
        # record is written AND fsynced before the 202/stream starts —
        # closing the async-fsync window where an admission accepted
        # milliseconds before a kill -9 could vanish (clients retry, so
        # the default async mode tolerates it; strict mode is for
        # operators who would rather pay one fsync of admission latency)
        self.sync_admissions = sync_admissions
        self.faults = fault_injector
        # -- open: scan the existing file, truncate the torn tail, note
        # the unterminated state for the caller to replay (single-
        # threaded: the writer thread starts below, after this)
        state, valid_end, epoch = scan_journal(path)
        self._replay_state = state
        self.epoch = epoch + 1
        f = open(path, "ab")
        if f.tell() != valid_end:
            f.truncate(valid_end)
            f.seek(valid_end)
        # writer-thread-owned from here on (R3 "journal" domain): the
        # file handle, the live-request mirror compaction snapshots,
        # and the bytes-since-compaction counter
        self._wfile = f
        self._wlive = {rid: dict(ent, tokens=list(ent["tokens"]))
                       for rid, ent in state.items()}
        self._wsince = 0
        # engine-thread-owned: rid → delivered count already journaled
        # (the watermark hook only records the per-tick delta)
        self._mark: dict[int, int] = {
            rid: len(ent["tokens"]) for rid, ent in state.items()
        }
        # shared under _lock: the pending queue and the stats counters
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: list = []
        self._stopping = False
        self.n_records = 0
        self.bytes_written = 0
        self.n_fsyncs = 0
        self.fsync_s: list[float] = []
        self.n_write_errors = 0
        self.n_fsync_errors = 0
        self.n_compactions = 0
        self._enqueue({"t": "epoch", "n": self.epoch,
                       "wall": time.time()})
        self._thread = threading.Thread(
            target=self._writer_loop, name="serve-journal-writer",
            daemon=True,
        )
        self._thread.start()

    # -- replay --------------------------------------------------------
    def replay(self) -> list[dict]:
        """The unterminated requests found when the journal was opened,
        rid-ascending (original admission order): each is
        ``{rid, prompt (np.int32), max_tokens, seed, deadline_wall,
        tokens}`` — everything ``ServeEngine.recover`` needs to
        teacher-force the stream back."""
        out = []
        for rid in sorted(self._replay_state):
            ent = self._replay_state[rid]
            out.append(dict(
                ent,
                prompt=np.asarray(ent["prompt"], dtype=np.int32),
                tokens=list(ent["tokens"]),
            ))
        return out

    # -- engine-thread hooks (enqueue only, no IO) ---------------------
    def admit(self, req: Any, now: float) -> None:
        """Journal one admission.  ``now`` is the engine clock reading
        the request's absolute deadline compares against; the deadline
        goes to disk as WALL time so a restarted process can resume the
        REMAINING budget (a crash must not grant a fresh window)."""
        deadline_wall = None
        if req.deadline is not None:
            deadline_wall = time.time() + (req.deadline - now)
        self._mark[req.req_id] = len(req.generated)
        rec = {
            "t": "adm",
            "rid": req.req_id,
            "prompt": [int(x) for x in req.prompt],
            "max_tokens": int(req.max_new_tokens),
            "seed": int(req.seed),
            "deadline_wall": deadline_wall,
            "tokens": [int(x) for x in req.generated],
        }
        # trace id + survival lineage ride the admission record so a
        # post-restart replay continues the SAME trace (and the request
        # log's replays/drains counters survive a second crash)
        trace = req.extra.get("trace")
        if trace is not None:
            rec["trace"] = trace
        for key in ("replays", "drains"):
            val = req.extra.get(key)
            if val:
                rec[key] = int(val)
        if getattr(req, "speculative", False):
            rec["spec"] = True
        # the serving weight version (rolling-upgrade tagging): written
        # only when nonzero, so pre-upgrade journals stay byte-stable
        wv = req.extra.get("weights_version")
        if wv:
            rec["wv"] = int(wv)
        # tenant id: written only when non-default, so single-tenant
        # journals stay byte-stable across the tenancy feature
        tenant = getattr(req, "tenant", "default")
        if tenant != "default":
            rec["tenant"] = tenant
        self._enqueue(rec)
        if self.sync_admissions:
            # block the enqueuing (engine) thread until the writer has
            # written AND fsynced this admission; failure degrades
            # (counted), never blocks admission forever
            self.flush(timeout=10.0)

    def end_tick(self, requests: Any) -> None:
        """One watermark record for the whole tick (batched per tick,
        never per token): every live request whose delivered count
        advanced since the last journaled mark contributes one row."""
        rows = []
        for req in requests:
            n = len(req.generated)
            m = self._mark.get(req.req_id, 0)
            if n > m:
                rows.append([req.req_id, n,
                             [int(x) for x in req.generated[m:]]])
                self._mark[req.req_id] = n
        if rows:
            self._enqueue({"t": "wm", "rows": rows})

    def terminal(self, rid: int, reason: str) -> None:
        self._mark.pop(rid, None)
        self._enqueue({"t": "fin", "rid": int(rid), "reason": reason})

    # -- control -------------------------------------------------------
    def _enqueue(self, rec: dict) -> None:
        with self._lock:
            if self._stopping:
                return
            self._pending.append(rec)
            self._cond.notify()

    def flush(self, timeout: float = 10.0) -> bool:
        """Barrier: True once every record enqueued BEFORE this call is
        written and fsynced (tests and the drain path use it)."""
        ev = threading.Event()
        with self._lock:
            if self._stopping and self._thread.is_alive() is False:
                return True
            self._pending.append(("flush", ev))
            self._cond.notify()
        return ev.wait(timeout)

    def close(self, timeout: float = 10.0) -> None:
        """Drain the queue, fsync, and stop the writer thread."""
        with self._lock:
            if self._stopping:
                return
            self._stopping = True
            self._cond.notify()
        self._thread.join(timeout)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            fsync_s = list(self.fsync_s)
            out = {
                "records": self.n_records,
                "bytes_written": self.bytes_written,
                "fsyncs": self.n_fsyncs,
                "write_errors": self.n_write_errors,
                "fsync_errors": self.n_fsync_errors,
                "compactions": self.n_compactions,
                "epoch": self.epoch,
                "replayed": len(self._replay_state),
            }
        out["fsync_p99_s"] = (
            float(np.percentile(np.asarray(fsync_s), 99)) if fsync_s
            else 0.0
        )
        return out

    # -- writer thread (R3 "journal" domain) ---------------------------
    def _writer_loop(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._stopping:
                    self._cond.wait(0.5)
                batch, self._pending = self._pending, []
                stopping = self._stopping
            if batch:
                self._writer_batch(batch)
            if stopping:
                with self._lock:
                    leftover, self._pending = self._pending, []
                if leftover:
                    self._writer_batch(leftover)
                try:
                    self._wfile.close()
                except OSError:
                    pass
                return

    def _writer_batch(self, batch: list) -> None:
        recs = [b for b in batch if isinstance(b, dict)]
        barriers = [b[1] for b in batch if not isinstance(b, dict)]
        if recs:
            blob = b"".join(_frame(r) for r in recs)
            faults = self.faults
            try:
                if (faults is not None
                        and faults.trip("journal_write") is not None):
                    raise OSError("chaos: injected journal write error")
                self._wfile.write(blob)
                self._wfile.flush()
            except OSError:
                # durability degradation, never an outage: the batch is
                # dropped and counted; serving continues
                with self._lock:
                    self.n_write_errors += 1
            else:
                for r in recs:
                    _apply(self._wlive, r)
                self._wsince += len(blob)
                with self._lock:
                    self.n_records += len(recs)
                    self.bytes_written += len(blob)
                if self.fsync:
                    t0 = time.monotonic()
                    try:
                        if (faults is not None
                                and faults.trip("journal_fsync") is not None):
                            raise OSError(
                                "chaos: injected journal fsync error")
                        os.fsync(self._wfile.fileno())
                    except OSError:
                        with self._lock:
                            self.n_fsync_errors += 1
                    else:
                        dt = time.monotonic() - t0
                        with self._lock:
                            self.n_fsyncs += 1
                            self.fsync_s.append(dt)
                            if len(self.fsync_s) > 10_000:
                                del self.fsync_s[:5_000]
                if self._wsince >= self.compact_bytes:
                    self._writer_compact()
        for ev in barriers:
            ev.set()

    def _writer_compact(self) -> None:
        """Rewrite the file as epoch + one admission per live request
        (tokens folded in) — replay-equivalent by construction (the same
        ``_apply`` state machine), size bounded by the live set."""
        tmp = self.path + ".compact"
        try:
            with open(tmp, "wb") as f:
                f.write(_frame({"t": "epoch", "n": self.epoch,
                                "wall": time.time()}))
                for rid in sorted(self._wlive):
                    ent = self._wlive[rid]
                    rec = {
                        "t": "adm", "rid": rid,
                        "prompt": ent["prompt"],
                        "max_tokens": ent["max_tokens"],
                        "seed": ent["seed"],
                        "deadline_wall": ent.get("deadline_wall"),
                        "tokens": ent["tokens"],
                    }
                    # trace/lineage survive compaction, or a compacted-
                    # then-replayed request would start a fresh trace
                    if ent.get("trace") is not None:
                        rec["trace"] = ent["trace"]
                    for key in ("replays", "drains"):
                        if ent.get(key):
                            rec[key] = ent[key]
                    if ent.get("spec"):
                        rec["spec"] = True
                    if ent.get("wv"):
                        rec["wv"] = ent["wv"]
                    if ent.get("tenant", "default") != "default":
                        rec["tenant"] = ent["tenant"]
                    f.write(_frame(rec))
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            old = self._wfile
            os.replace(tmp, self.path)
            self._wfile = open(self.path, "ab")
            self._wsince = 0
            try:
                old.close()
            except OSError:
                pass
            with self._lock:
                self.n_compactions += 1
        except OSError:
            with self._lock:
                self.n_write_errors += 1
