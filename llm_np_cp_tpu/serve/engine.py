"""ServeEngine: jit-stable continuous-batching decode over the block pool.

One engine owns params + three jitted programs and drives them from a
host-side scheduler tick loop:

- **prefill** — the chunked ragged prefill step from generate.py
  (``make_ragged_prefill_step``): each admitted request is LEFT-padded to
  a multiple of ``prefill_chunk`` and consumed in fixed-width chunks, so
  every prefill dispatch reuses ONE compiled program regardless of
  prompt length; the resulting contiguous K/V is scattered into the
  request's pool blocks in one jitted copy.
- **decode** — one program over the PACKED slot batch: gather each
  slot's K/V through its block table ([B, MB] int32 → a contiguous
  [L, B, S_max, K, D] view), run the standard forward at per-row offsets
  (the batched-speculative cache discipline: ``length`` is an int32 [B]
  vector), sample per-row (keys derived in-graph from per-request seeds
  + content position, so a preempted request resumes its exact RNG
  stream), then scatter the new token's K/V column back into the pool.
  Every shape is static: batch = ``max_slots``, table width =
  ``max_blocks_per_seq``, pool = ``num_blocks`` — ticks never recompile
  (asserted by tools/compile_counter + tests).
- **sample-after-prefill** — the first token's sampler call.

Inactive slots point their tables at the reserved scratch block 0 and
carry length 0, so the decode step runs branchless at full width; their
outputs are discarded host-side.

The XLA gather materializes the active batch's K/V view each step — the
stated first implementation.  ``decode_attn_impl="flash_decode"`` routes
the gathered attention through the existing Pallas decode kernel (gated,
ops/pallas/support.py).  The block-table-NATIVE kernel that skips the
gather entirely, ops/pallas/decode_attention.paged_decode_attention, is
NOT wired into this forward yet — it has parity tests and a compile
probe (support.py), and bench.run_serve_config records the probe verdict
so the live-TPU round can validate it before the ROADMAP follow-up
integrates it here.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from llm_np_cp_tpu.cache import KVCache
from llm_np_cp_tpu.config import ModelConfig
from llm_np_cp_tpu.generate import IncrementalDetok, make_ragged_prefill_step
from llm_np_cp_tpu.models.transformer import forward
from llm_np_cp_tpu.ops.sampling import Sampler
from llm_np_cp_tpu.serve.block_pool import BlockPool, PagedKV
from llm_np_cp_tpu.serve.metrics import ServeMetrics
from llm_np_cp_tpu.serve.scheduler import Request, Scheduler

Params = dict[str, Any]


def _ceil_to(n: int, g: int) -> int:
    return -(-n // g) * g


def worst_case_slots(prompt_len: int, max_new_tokens: int, chunk: int) -> int:
    """Peak cache slots a request can occupy over its whole lifetime,
    including re-prefills after preemption.

    A re-prefill with ``g`` tokens already generated left-pads the
    content ``p+g`` to whole chunks and the remaining ``m-g`` decode
    steps extend from there, so the peak is
    ``max_g ceil_to(p+g, chunk) + (m-g)`` over ``0 <= g < m``.  That
    maximum is either the uninterrupted path (g=0) or just past a chunk
    boundary (``p+g ≡ 1 mod chunk``), where it equals
    ``p + m + chunk - 1``.  One definition shared by the engine's
    admission check and the pool sizing in bench.py / the serve-bench
    CLI — three hand-rolled copies diverged here once already.
    """
    p, m = prompt_len, max_new_tokens
    worst = _ceil_to(p, chunk) + m
    g_cross = (1 - p) % chunk or chunk  # smallest g>0 with p+g ≡ 1 (mod chunk)
    if g_cross <= m - 1:
        worst = max(worst, p + m + chunk - 1)
    return worst


def pool_geometry(
    prompt_len: int,
    max_new_tokens: int,
    slots: int,
    block_size: int,
    prefill_chunk: int | None = None,
    spare_blocks: int = 2,
) -> tuple[int, int, int]:
    """Size a pool for a worst-case trace: ``(blocks_per_seq, num_blocks,
    max_seq_len)``.

    The ONE sizing recipe shared by the serve-bench CLI and
    bench.run_serve_config (their hand-rolled copies diverged once
    already): every slot can hold a worst-case request (incl. preemption
    re-prefills, see worst_case_slots) plus ``spare_blocks`` of headroom
    for the scratch block and the scheduler's decode reserve.
    ``prefill_chunk=None`` means the engine default (``block_size``).
    """
    chunk = prefill_chunk or block_size
    worst = worst_case_slots(prompt_len, max_new_tokens, chunk)
    blocks_per_seq = -(-worst // block_size)
    num_blocks = slots * blocks_per_seq + spare_blocks
    return blocks_per_seq, num_blocks, blocks_per_seq * block_size


class ServeEngine:
    def __init__(
        self,
        params: Params,
        config: ModelConfig,
        *,
        sampler: Sampler | None = None,
        stop_tokens: tuple[int, ...] = (),
        max_slots: int = 4,
        num_blocks: int = 64,
        block_size: int = 64,
        max_seq_len: int = 1024,
        prefill_chunk: int | None = None,
        cache_dtype: jnp.dtype = jnp.bfloat16,
        decode_attn_impl: str = "xla",
        tokenizer: Any = None,
        clock: Callable[[], float] = time.perf_counter,
    ) -> None:
        if decode_attn_impl not in ("xla", "flash_decode"):
            raise ValueError(
                f"decode_attn_impl must be 'xla' or 'flash_decode', "
                f"got {decode_attn_impl!r}"
            )
        from llm_np_cp_tpu.ops.pallas.support import gate_attn_impl

        decode_attn_impl = gate_attn_impl(
            decode_attn_impl, int8_cache=jnp.dtype(cache_dtype) == jnp.int8
        )
        self.params = params
        self.config = config
        self.sampler = sampler or Sampler(kind="greedy")
        self.stop_tokens = tuple(stop_tokens)
        self.tokenizer = tokenizer
        self.clock = clock
        self.cache_dtype = jnp.dtype(cache_dtype)
        self.block_size = block_size
        self.prefill_chunk = prefill_chunk or block_size
        # per-request cache ceiling, in whole blocks (fixes the decode
        # gather width S_max = max_blocks_per_seq * block_size)
        self.max_seq_len = _ceil_to(max_seq_len, block_size)
        self.max_blocks_per_seq = self.max_seq_len // block_size

        self.pool = BlockPool(config, num_blocks, block_size, dtype=cache_dtype)
        self.scheduler = Scheduler(
            self.pool,
            max_slots=max_slots,
            block_size=block_size,
            blocks_for_prefill=lambda req: self.pool.blocks_for(
                self._prefill_width(req)
            ),
        )
        self.metrics = ServeMetrics(clock=clock)
        self._next_id = 0
        self._detok: dict[int, IncrementalDetok] = {}

        # -- jitted programs (fixed set; tick loop never adds more) ----
        self._prefill_step = make_ragged_prefill_step(config)
        self._decode_step = self._make_decode_step(decode_attn_impl)
        self._sample_first = self._make_sample_first()
        self._scatter_prefill = self._make_scatter_prefill()

    # ------------------------------------------------------------------
    def _prefill_width(self, req: Request) -> int:
        """Left-padded prefill width: the request's content rounded up to
        a whole number of chunks (ONE compiled chunk program for every
        prompt length)."""
        return _ceil_to(req.total_len, self.prefill_chunk)

    def compile_counts(self) -> dict[str, int]:
        """Compiled-program count per jitted step (the static-shape
        contract: decode/prefill/sample stay at 1; scatter grows once per
        distinct prefill block count).  tools/compile_counter.py wraps
        this for the CI check."""

        def size(fn: Any) -> int:
            get = getattr(fn, "_cache_size", None)
            return int(get()) if get is not None else -1

        return {
            "prefill_step": size(self._prefill_step),
            "decode_step": size(self._decode_step),
            "sample_first": size(self._sample_first),
            "scatter_prefill": size(self._scatter_prefill),
        }

    # ------------------------------------------------------------------
    # Jitted step builders
    # ------------------------------------------------------------------
    def _make_sample_first(self) -> Callable:
        sampler = self.sampler

        @jax.jit
        def sample_first(logits: jnp.ndarray, seed: jnp.ndarray, pos: jnp.ndarray):
            key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
            return sampler(key, logits)

        return sample_first

    def _make_scatter_prefill(self) -> Callable:
        quantized = self.cache_dtype == jnp.int8
        bs = self.block_size

        @partial(jax.jit, donate_argnums=(0,))
        def scatter_prefill(pages: PagedKV, cache: KVCache, ids: jnp.ndarray):
            # cache: batch-1 contiguous prefill cache at the FIXED temp
            # capacity (max_seq_len); only the first nb*bs slots hold
            # this request's content
            nb = ids.shape[0]

            def put(slab, page, trailing):  # slab [L, 1, max_seq_len, *t]
                l = slab.shape[0]
                return page.at[:, ids].set(
                    slab[:, : nb * bs].reshape((l, nb, bs) + trailing)
                )

            kh, d = cache.k.shape[-2:]
            new = PagedKV(
                k=put(cache.k[:, 0], pages.k, (kh, d)),
                v=put(cache.v[:, 0], pages.v, (kh, d)),
                k_scale=(
                    put(cache.k_scale[:, 0], pages.k_scale, (kh,))
                    if quantized else None
                ),
                v_scale=(
                    put(cache.v_scale[:, 0], pages.v_scale, (kh,))
                    if quantized else None
                ),
            )
            return new

        return scatter_prefill

    def _make_decode_step(self, attn_impl: str) -> Callable:
        config, sampler = self.config, self.sampler
        bs = self.block_size
        quantized = self.cache_dtype == jnp.int8

        @partial(jax.jit, donate_argnums=(1,))
        def decode_step(
            params: Params,
            pages: PagedKV,
            tables: jnp.ndarray,   # [B, MB] int32 (scratch-0 padded)
            lengths: jnp.ndarray,  # [B] int32 — cache slots already written
            pads: jnp.ndarray,     # [B] int32 — left pads per row
            toks: jnp.ndarray,     # [B] int32 — current input token
            seeds: jnp.ndarray,    # [B] uint32 — per-request RNG seed
        ):
            l_axis, b = pages.k.shape[0], tables.shape[0]
            kh, d = pages.k.shape[-2:]
            s_max = tables.shape[1] * bs

            def gather(page, trailing):  # [L, NB, bs, *t] → [L, B, S_max, *t]
                return page[:, tables].reshape((l_axis, b, s_max) + trailing)

            pos = jnp.arange(s_max, dtype=jnp.int32)[None, :]
            valid = (pos >= pads[:, None]) & (pos < lengths[:, None])
            cache = KVCache(
                k=gather(pages.k, (kh, d)),
                v=gather(pages.v, (kh, d)),
                valid=valid,
                length=lengths,
                k_scale=gather(pages.k_scale, (kh,)) if quantized else None,
                v_scale=gather(pages.v_scale, (kh,)) if quantized else None,
            )
            logits, cache = forward(
                params, toks[:, None], config, cache, logits_last_only=True,
                pad_offsets=pads, attn_impl=attn_impl,
            )
            # Per-row keys from (request seed, content position): a
            # request resumed after preemption replays the same stream,
            # so stochastic samplers are preemption-transparent too.
            content_pos = lengths - pads
            keys = jax.vmap(
                lambda s, t: jax.random.fold_in(jax.random.PRNGKey(s), t)
            )(seeds, content_pos)
            nxt = jax.vmap(lambda k, lg: sampler(k, lg[None])[0])(
                keys, logits[:, -1]
            )

            # Extract the newly written K/V column (slot ``lengths`` per
            # row) from the gathered view and scatter it into the pool.
            def col(slab):  # [L, B, S_max, ...] → [L, B, ...] at per-row offset
                return jax.vmap(
                    lambda sl, off: lax.dynamic_index_in_dim(
                        sl, off, axis=1, keepdims=False
                    ),
                    in_axes=(1, 0), out_axes=1,
                )(slab, lengths)

            blk = jnp.take_along_axis(tables, (lengths // bs)[:, None], axis=1)[:, 0]
            off = lengths % bs
            # inactive rows all hit (scratch block 0, slot 0); duplicate
            # scatter indices there are harmless — the data is garbage by
            # construction and never gathered through a real table
            new_pages = PagedKV(
                k=pages.k.at[:, blk, off].set(col(cache.k)),
                v=pages.v.at[:, blk, off].set(col(cache.v)),
                k_scale=(
                    pages.k_scale.at[:, blk, off].set(col(cache.k_scale))
                    if quantized else None
                ),
                v_scale=(
                    pages.v_scale.at[:, blk, off].set(col(cache.v_scale))
                    if quantized else None
                ),
            )
            return nxt, new_pages

        return decode_step

    # ------------------------------------------------------------------
    # Request lifecycle
    # ------------------------------------------------------------------
    def submit(
        self,
        prompt_ids: np.ndarray | list[int],
        max_new_tokens: int,
        *,
        request_id: int | None = None,
        seed: int = 0,
        callback: Callable[[Request, int, str | None], None] | None = None,
        arrival_time: float | None = None,
    ) -> Request:
        prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        # peak cache need over the request's lifetime (incl. re-prefills)
        worst = worst_case_slots(prompt.size, max_new_tokens,
                                 self.prefill_chunk)
        if worst > self.max_seq_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) "
                f"needs up to {worst} cache slots > max_seq_len "
                f"{self.max_seq_len}"
            )
        # worst-case ADMISSION need: a re-prefill after preemption can
        # carry up to max_new_tokens-1 already-generated tokens, and the
        # scheduler only admits with need + decode_reserve blocks free —
        # a request whose worst admission can never be satisfied would
        # sit at the queue head forever (strict FIFO), starving
        # everything behind it, so reject at submit
        need_max = self.pool.blocks_for(
            _ceil_to(prompt.size + max_new_tokens - 1, self.prefill_chunk)
        )
        headroom = need_max + self.scheduler.decode_reserve
        if headroom > self.pool.capacity:
            raise ValueError(
                f"request needs up to {need_max} blocks + "
                f"{self.scheduler.decode_reserve} reserve to admit "
                f"> pool capacity {self.pool.capacity}; grow num_blocks or "
                f"shrink the request"
            )
        if request_id is None:
            request_id = self._next_id
        self._next_id = max(self._next_id, request_id) + 1
        req = Request(
            req_id=request_id,
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            seed=seed,
            callback=callback,
            arrival_time=arrival_time if arrival_time is not None else 0.0,
        )
        req.submit_time = self.clock()
        self.scheduler.add(req)
        self.metrics.on_submit(req)
        if self.tokenizer is not None:
            self._detok[req.req_id] = IncrementalDetok(self.tokenizer)
        return req

    def _emit(self, req: Request, token: int) -> None:
        req.generated.append(int(token))
        if req.first_token_time is None:
            req.first_token_time = self.clock()
        self.metrics.on_token(req)
        if req.callback is not None:
            delta = None
            detok = self._detok.get(req.req_id)
            if detok is not None:
                delta = detok.push(token)
            req.callback(req, int(token), delta)

    def _maybe_finish(self, req: Request) -> bool:
        if req.done or (self.stop_tokens and req.generated
                        and req.generated[-1] in self.stop_tokens):
            req.finish_time = self.clock()
            self.scheduler.finish(req)
            self.metrics.on_finish(req)
            self._detok.pop(req.req_id, None)
            return True
        return False

    # ------------------------------------------------------------------
    def _prefill_request(self, req: Request) -> None:
        """Chunked ragged prefill into a temp contiguous cache, scatter
        into the request's blocks, sample + emit the first token."""
        content = req.effective_prompt()
        w = self._prefill_width(req)
        req.pad = w - content.size
        # FIXED temp capacity: a per-bucket cap would retrace the whole
        # model prefill once per prompt-length bucket (a multi-second
        # mid-traffic stall on TPU); only the cheap scatter is allowed
        # to specialize per block count
        cap = self.max_seq_len
        ids = np.zeros((1, w), dtype=np.int32)
        mask = np.zeros((1, w), dtype=bool)
        ids[0, req.pad:] = content
        mask[0, req.pad:] = True
        pads = jnp.asarray([req.pad], dtype=jnp.int32)
        ids_d, mask_d = jnp.asarray(ids), jnp.asarray(mask)

        cache = KVCache.init(self.config, 1, cap, dtype=self.cache_dtype)
        last = None
        for off in range(0, w, self.prefill_chunk):
            end = off + self.prefill_chunk
            last, cache = self._prefill_step(
                self.params, ids_d[:, off:end], cache, mask_d[:, off:end], pads
            )
        self.pool.pages = self._scatter_prefill(
            self.pool.pages, cache,
            jnp.asarray(np.asarray(req.block_ids, dtype=np.int32)),
        )
        tok = self._sample_first(
            last,
            jnp.uint32(req.seed),
            jnp.int32(content.size - 1),
        )
        self._emit(req, int(np.asarray(tok)[0]))

    # ------------------------------------------------------------------
    def step(self) -> bool:
        """One scheduler tick: admissions (+prefill) then one packed
        decode dispatch.  Returns True while work remains."""
        for req in self.scheduler.admit():
            self._prefill_request(req)
            self._maybe_finish(req)

        # preempted requests are already requeued; slots rebuilt below
        self.scheduler.ensure_decode_blocks()

        running = [
            r for r in self.scheduler.running if r.generated
        ]
        if running:
            b = self.scheduler.max_slots
            mb = self.max_blocks_per_seq
            tables = np.zeros((b, mb), dtype=np.int32)
            lengths = np.zeros((b,), dtype=np.int32)
            pads = np.zeros((b,), dtype=np.int32)
            toks = np.zeros((b,), dtype=np.int32)
            seeds = np.zeros((b,), dtype=np.uint32)
            for r in running:
                tables[r.slot, : len(r.block_ids)] = r.block_ids
                # slots written so far: pads + content minus the latest
                # generated token (this tick's input, written by the step)
                lengths[r.slot] = r.cache_len - 1
                pads[r.slot] = r.pad
                toks[r.slot] = r.generated[-1]
                seeds[r.slot] = np.uint32(r.seed)
            nxt, self.pool.pages = self._decode_step(
                self.params, self.pool.pages,
                jnp.asarray(tables), jnp.asarray(lengths), jnp.asarray(pads),
                jnp.asarray(toks), jnp.asarray(seeds),
            )
            nxt_host = np.asarray(nxt)
            for r in running:
                self._emit(r, int(nxt_host[r.slot]))
                self._maybe_finish(r)

        self.metrics.on_tick(
            queue_depth=self.scheduler.queue_depth,
            occupancy=self.pool.occupancy,
            active_slots=len(running) if running else 0,
            preemptions_total=self.scheduler.n_preemptions,
        )
        return self.scheduler.has_work

    def warmup(
        self, prompt_lens: list[int], max_new_tokens: int = 2,
    ) -> None:
        """Compile every phase program before measuring, then reset
        metrics — so a subsequent replay reports steady-state serving
        numbers, not first-compile stalls (on TPU a model compile is
        multi-second and would dominate TTFT p99).

        prefill/decode/sample each compile once, so one dummy request
        covers them.  The scatter specializes per prefill block count,
        and a preemption re-prefill can produce ANY count up to the
        workload's worst case — warm them all by scattering a zero temp
        cache into the scratch block (garbage there is harmless by
        construction)."""
        if not prompt_lens:
            return
        # two decode tokens compile the decode/sample/column-scatter
        # programs; the workload's full budget only matters for b_max
        self.submit(np.ones(min(prompt_lens), np.int32),
                    min(2, max_new_tokens))
        self.run_until_complete()
        b_max = min(
            self.pool.blocks_for(_ceil_to(
                max(prompt_lens) + max_new_tokens - 1, self.prefill_chunk
            )),
            self.max_blocks_per_seq,
        )
        cache = KVCache.init(
            self.config, 1, self.max_seq_len, dtype=self.cache_dtype
        )
        for nb in range(1, b_max + 1):
            self.pool.pages = self._scatter_prefill(
                self.pool.pages, cache, jnp.zeros((nb,), jnp.int32)
            )
        self.metrics = ServeMetrics(clock=self.clock)

    def run_until_complete(self, max_ticks: int = 100_000) -> None:
        for _ in range(max_ticks):
            if not self.step():
                return
        raise RuntimeError(f"serve loop did not drain within {max_ticks} ticks")

    # ------------------------------------------------------------------
    def replay_trace(
        self,
        trace: list[dict[str, Any]],
        *,
        realtime: bool = False,
        max_ticks: int = 100_000,
    ) -> dict[str, Any]:
        """Replay ``[{"arrival_s", "prompt", "max_new_tokens", "seed"?}]``.

        realtime=False (default, and what tests/bench use on CPU):
        arrivals are released by a virtual clock that advances to the
        next arrival whenever the engine is idle — the schedule stress
        is preserved without wall-clock sleeps.  realtime=True sleeps
        until each arrival (live serving simulation).
        """
        pending = sorted(trace, key=lambda t: t["arrival_s"])
        t0 = self.clock()
        virtual_now = 0.0
        for _ in range(max_ticks):
            now = self.clock() - t0 if realtime else virtual_now
            while pending and pending[0]["arrival_s"] <= now:
                item = pending.pop(0)
                req = self.submit(
                    item["prompt"], item["max_new_tokens"],
                    seed=item.get("seed", 0),
                    callback=item.get("callback"),
                    arrival_time=item["arrival_s"],
                )
                if realtime:
                    # wall arrival: TTFT then counts the wait between
                    # arrival and the tick loop noticing the request
                    req.extra["arrival_wall"] = t0 + item["arrival_s"]
            had_work = self.step()
            if not had_work and pending:
                nxt = pending[0]["arrival_s"]
                if realtime:
                    time.sleep(max(0.0, nxt - (self.clock() - t0)))
                else:
                    virtual_now = nxt
            elif not had_work and not pending:
                return self.metrics.snapshot()
            if not realtime:
                virtual_now = max(virtual_now, self.clock() - t0)
        raise RuntimeError(f"trace replay did not drain within {max_ticks} ticks")
