"""ServeEngine: jit-stable continuous-batching decode over the block pool.

One engine owns params + three jitted programs and drives them from a
host-side scheduler tick loop:

- **prefill** — the chunked ragged prefill step from generate.py
  (``make_ragged_prefill_step``): each admitted request is LEFT-padded to
  a multiple of ``prefill_chunk`` and consumed in fixed-width chunks, so
  every prefill dispatch reuses ONE compiled program regardless of
  prompt length; the resulting contiguous K/V is scattered into the
  request's pool blocks in one jitted copy.
- **decode** — one program over the PACKED slot batch: gather each
  slot's K/V through its block table ([B, MB] int32 → a contiguous
  [L, B, S_max, K, D] view), run the standard forward at per-row offsets
  (the batched-speculative cache discipline: ``length`` is an int32 [B]
  vector), sample per-row (keys derived in-graph from per-request seeds
  + content position, so a preempted request resumes its exact RNG
  stream), then scatter the new token's K/V column back into the pool.
  Every shape is static: batch = ``max_slots``, table width =
  ``max_blocks_per_seq``, pool = ``num_blocks`` — ticks never recompile
  (asserted by tools/compile_counter + tests).
- **sample-after-prefill** — the first token's sampler call.

Inactive slots point their tables at the reserved scratch block 0 and
carry length 0, so the decode step runs branchless at full width; their
outputs are discarded host-side.

Decode attention impls (``decode_attn_impl``, gated by the hardware
compile probes in ops/pallas/support.py with XLA as the fallback):

- ``"xla"`` — the materialized-gather path above.
- ``"flash_decode"`` — same gather, attention through the mask-driven
  Pallas decode kernel.
- ``"paged"`` — ZERO-GATHER: the per-layer scan threads the pool slabs
  themselves and ops/pallas/decode_attention.paged_decode_attention
  reads K/V straight through the scalar-prefetched block tables, so the
  [L, B, S_max] view never materializes and per-token HBM traffic scales
  with each row's visible blocks instead of the padded table width
  (asserted structurally via jaxpr inspection in tests).  int8 pools
  stream quantized blocks + scale pages through the kernel.

Prefix sharing (``enable_prefix_cache``): at admission the prompt's
fully-filled leading blocks are looked up in a refcounted registry
(serve/prefix_cache.py); hits are claimed into the request's block table
and their prefill chunks are SKIPPED — only the shared K/V is copied
into the temp prefill cache so the remaining chunks attend correctly.

Mesh-sharded serving (``mesh_plan=MeshPlan(model=N)``): the engine
builds a ``jax.sharding.Mesh`` over its device slice, tensor-parallels
the params via ``parallel/sharding.param_specs`` and the pool slabs via
``paged_kv_specs`` (kv-head-partitioned K/V pages, int8 scale pages
included), and commits every per-tick operand — block tables above all
— FULLY REPLICATED, so the scalar-prefetch kernels walk per-shard-
identical indices over their head-slice of the slabs.  With kv heads
divisible by the model axis the Pallas ``ragged_paged_attention`` /
``paged_decode_attention`` kernels run UNMODIFIED inside ``shard_map``
(``_shard_attn``); otherwise (the TP+GQA hard part) the engine holds
the partitionable XLA paths.  Step in-avals are pinned — replicated
operands, ``normalize_specs``-spelled slab/temp-cache shardings,
``with_sharding_constraint`` on every returned ``PagedKV`` — so each
program still compiles once per shape bucket and NEVER per tick under
the mesh.  The engine is TP-only by design; data parallelism is N
engine replicas behind a prefix-affinity router (serve/replica.py),
each on its own mesh slice.

Unified tick (``mixed_step="on"/"auto"``): the phase-split pipeline
above collapses into ONE jit-stable ``mixed_step`` dispatch per tick —
a packed ragged batch of prefill chunk slices and decode rows runs
through a single layer scan that threads the pool slabs, scatters every
token's K/V straight into its pool block (NO temp prefill cache, NO
``gather_prefix`` copy program — shared prefix blocks are attended
in place through the block table), and attends via
``ragged_paged_attention`` (probe-gated; XLA gather fallback).  The
scheduler's token-budget planner (``Scheduler.plan_tick``) co-schedules
chunked prefill with decode under ``tick_token_budget`` tokens per tick
— decode rows first, so a long prefill can no longer stall the decoding
batch (the PR-5 trace finding).  The packed width is bucketed
(``mixed_buckets``), so the program compiles once per bucket and NEVER
per tick, whatever the prefill:decode row mix (compile-counter lint).

Speculative serving (``spec_k=K``, unified tick only): per-request
HOST-SIDE prompt-lookup draft streams (serve/spec.py) propose up to K
tokens per tick, packed as ragged verify slices of width ≤ K+1 into the
same one dispatch; the step samples at every packed position with the
deterministic (seed, content-pos) keys, so the accept walk emits the
longest draft prefix matching the samples plus the first correction —
token-identical to plain decode, up to K+1 tokens per HBM sweep.
Requests opt in per-submit (``speculative=True``) and fall back
per-request when rolling acceptance collapses; the verify lanes are a
static [slots, K+1] extension of the step, so zero-recompiles survives.
"""

from __future__ import annotations

import contextlib
import math
import time
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from llm_np_cp_tpu.cache import KVCache, quantize_kv
from llm_np_cp_tpu.config import ModelConfig
from llm_np_cp_tpu.generate import IncrementalDetok, make_ragged_prefill_step
from llm_np_cp_tpu.models.transformer import (
    embed_inputs,
    final_logits,
    forward,
    run_decoder_layer,
    scan_unroll,
)
from llm_np_cp_tpu.ops.activations import ACT2FN
from llm_np_cp_tpu.ops.rope import rope_cos_sin
from llm_np_cp_tpu.ops.sampling import Sampler
from llm_np_cp_tpu.serve.block_pool import BlockPool, PagedKV
from llm_np_cp_tpu.serve.faults import FaultInjected, FaultInjector
from llm_np_cp_tpu.serve.metrics import ServeMetrics
from llm_np_cp_tpu.serve.prefix_cache import prefix_block_keys
from llm_np_cp_tpu.serve.request_log import request_record
from llm_np_cp_tpu.serve.scheduler import (
    QueueFull,
    Request,
    RequestState,
    Scheduler,
    TenantThrottled,
)
from llm_np_cp_tpu.serve.telemetry import (
    mixed_tick_kv_read,
    split_tick_kv_read,
)
from llm_np_cp_tpu.serve.tracing import TraceRecorder, gen_trace_id

Params = dict[str, Any]

# Shared no-op context for the tracing-off branch of the profiler-scope
# hooks: ``nullcontext()`` per tick would be a per-tick allocation on
# the hot path — exactly what the tracing-off discipline forbids.
_NULL_CTX = contextlib.nullcontext()


def _ceil_to(n: int, g: int) -> int:
    return -(-n // g) * g


def _stop_hits(samples: jnp.ndarray,
               stop_tokens: tuple[int, ...]) -> jnp.ndarray:
    """[.., W] bool — which sampled tokens are stop tokens (the static
    stop set is tiny, so this is a handful of fused compares)."""
    hit = jnp.zeros(samples.shape, jnp.bool_)
    for t in stop_tokens:
        hit = hit | (samples == jnp.int32(t))
    return hit


def _pack_sync(
    samples: jnp.ndarray,       # [R, W] int32 sampled tokens
    stop_hit: jnp.ndarray,      # [R, W] bool
    accept: jnp.ndarray,        # [R] int32 leading draft matches
) -> jnp.ndarray:
    """The one-fetch host-sync contract: pack the tick's whole outcome
    into ONE int32 array so ``host_sync`` is a single device→host
    transfer.  Columns: ``[0:W)`` the sampled tokens, ``W`` a stop-hit
    bitmask over those columns, ``W+1`` the advance watermark (tokens
    the accept walk will emit this tick, pre-budget: up to the first
    stop inside the accepted prefix, else accept+1), ``W+2`` the
    accept length.  The split tick is the degenerate W=1 case
    ([R, 4]: token, finished, watermark, accept).

    The deliver walk reads the token and accept columns; finish/budget
    semantics stay host-side in ``_maybe_finish`` (one source of
    truth), so the stop-mask and watermark columns are currently
    redundant with it — they ride along because the packed row IS the
    contract (a consumer that wants the tick outcome without replaying
    host logic — journal watermark batching, a future async deliver —
    reads it from the same fetch), and three extra fused int32 ops per
    row cost nothing next to the transfer they share."""
    w = samples.shape[1]
    bits = jnp.asarray([1 << j for j in range(w)], jnp.int32)
    stop_mask = jnp.sum(
        jnp.where(stop_hit, bits[None, :], 0), axis=1, dtype=jnp.int32
    )
    kcol = jnp.arange(w, dtype=jnp.int32)[None, :]
    cand = stop_hit & (kcol <= accept[:, None])
    advance = jnp.where(
        jnp.any(cand, axis=1),
        jnp.argmax(cand, axis=1).astype(jnp.int32) + 1,
        accept + 1,
    )
    return jnp.concatenate(
        [samples, stop_mask[:, None], advance[:, None],
         accept[:, None]], axis=1,
    )


def _roofline_targs(tel: dict) -> dict:
    """The roofline slice of a tick's trace args (callers hold the
    tracer guard): what tools/summarize_trace.py's roofline section and
    a Perfetto tick click read."""
    return {
        "roofline_gbps": round(tel["achieved_gbps"], 3),
        "roofline_util": round(tel["roofline_util"], 6),
        "mfu": round(tel["mfu"], 6),
        "device_time_s": round(tel["device_time_s"], 6),
        "kv_read_bytes": int(tel["kv_read_bytes"]),
        "kv_write_bytes": int(tel["kv_write_bytes"]),
        "weight_bytes": int(tel["weight_bytes"]),
    }


def worst_case_slots(prompt_len: int, max_new_tokens: int, chunk: int) -> int:
    """Peak cache slots a request can occupy over its whole lifetime,
    including re-prefills after preemption.

    A re-prefill with ``g`` tokens already generated left-pads the
    content ``p+g`` to whole chunks and the remaining ``m-g`` decode
    steps extend from there, so the peak is
    ``max_g ceil_to(p+g, chunk) + (m-g)`` over ``0 <= g < m``.  That
    maximum is either the uninterrupted path (g=0) or just past a chunk
    boundary (``p+g ≡ 1 mod chunk``), where it equals
    ``p + m + chunk - 1``.  One definition shared by the engine's
    admission check and the pool sizing in bench.py / the serve-bench
    CLI — three hand-rolled copies diverged here once already.
    """
    p, m = prompt_len, max_new_tokens
    worst = _ceil_to(p, chunk) + m
    g_cross = (1 - p) % chunk or chunk  # smallest g>0 with p+g ≡ 1 (mod chunk)
    if g_cross <= m - 1:
        worst = max(worst, p + m + chunk - 1)
    return worst


def pool_geometry(
    prompt_len: int,
    max_new_tokens: int,
    slots: int,
    block_size: int,
    prefill_chunk: int | None = None,
    spare_blocks: int = 2,
) -> tuple[int, int, int]:
    """Size a pool for a worst-case trace: ``(blocks_per_seq, num_blocks,
    max_seq_len)``.

    The ONE sizing recipe shared by the serve-bench CLI and
    bench.run_serve_config (their hand-rolled copies diverged once
    already): every slot can hold a worst-case request (incl. preemption
    re-prefills, see worst_case_slots) plus ``spare_blocks`` of headroom
    for the scratch block and the scheduler's decode reserve.
    ``prefill_chunk=None`` means the engine default (``block_size``).
    """
    chunk = prefill_chunk or block_size
    worst = worst_case_slots(prompt_len, max_new_tokens, chunk)
    blocks_per_seq = -(-worst // block_size)
    num_blocks = slots * blocks_per_seq + spare_blocks
    return blocks_per_seq, num_blocks, blocks_per_seq * block_size


class ServeEngine:
    def __init__(
        self,
        params: Params,
        config: ModelConfig,
        *,
        sampler: Sampler | None = None,
        stop_tokens: tuple[int, ...] = (),
        max_slots: int = 4,
        num_blocks: int = 64,
        block_size: int = 64,
        max_seq_len: int = 1024,
        prefill_chunk: int | None = None,
        cache_dtype: jnp.dtype = jnp.bfloat16,
        decode_attn_impl: str = "xla",
        enable_prefix_cache: bool = False,
        max_queue: int | None = None,
        tokenizer: Any = None,
        clock: Callable[[], float] = time.perf_counter,
        fault_injector: FaultInjector | None = None,
        tracer: TraceRecorder | None = None,
        mixed_step: str = "off",
        sample_epilogue: str = "auto",
        tick_token_budget: int | None = None,
        mesh_plan: Any = None,
        mesh_devices: list | None = None,
        journal: Any = None,
        request_log: Any = None,
        sentinel: Any = None,
        actions: Any = None,
        telemetry: Any = None,
        weights_version: int = 0,
        host_tier: Any = None,
        tenants: Any = None,
        spec_k: int = 0,
        spec_ngram: int = 3,
        spec_min_accept: float = 0.1,
        spec_window: int = 64,
    ) -> None:
        if decode_attn_impl not in ("xla", "flash_decode", "paged"):
            raise ValueError(
                f"decode_attn_impl must be 'xla', 'flash_decode' or "
                f"'paged', got {decode_attn_impl!r}"
            )
        if mixed_step not in ("auto", "on", "off"):
            raise ValueError(
                f"mixed_step must be 'auto', 'on' or 'off', got "
                f"{mixed_step!r}"
            )
        if sample_epilogue not in ("auto", "on", "off"):
            raise ValueError(
                f"sample_epilogue must be 'auto', 'on' or 'off', got "
                f"{sample_epilogue!r}"
            )
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        if spec_k > 30:
            # the one-fetch packed sync carries a per-row stop-hit
            # BITMASK over the spec_k+1 sample columns in one int32
            raise ValueError(
                f"spec_k must be <= 30 (the packed host-sync stop mask "
                f"is an int32 bitmask over spec_k+1 columns), got {spec_k}"
            )
        if spec_k and spec_ngram < 2:
            # fail at construction, not at the first draft tick inside
            # the supervised tick thread (DraftState requires
            # ngram_min <= ngram_max and its lookup floor is 2)
            raise ValueError(
                f"spec_ngram must be >= 2, got {spec_ngram}"
            )
        if spec_k and mixed_step == "off":
            raise ValueError(
                "speculative serving (spec_k > 0) rides the unified "
                "tick's batched verifier; it cannot run with "
                "mixed_step='off'"
            )
        if host_tier is not None and not enable_prefix_cache:
            raise ValueError(
                "host_tier requires enable_prefix_cache=True: the tier "
                "is keyed by the prefix cache's chained content hashes"
            )
        from llm_np_cp_tpu.ops.pallas.support import (
            gate_attn_impl,
            kernel_error,
            ragged_kernel_name,
        )

        int8_cache = jnp.dtype(cache_dtype) == jnp.int8
        decode_attn_impl = gate_attn_impl(
            decode_attn_impl, int8_cache=int8_cache
        )
        # -- mesh-sharded mode (ROADMAP item 1): params tensor-parallel
        # over "model" via param_specs, pool slabs kv-head-partitioned
        # via paged_kv_specs, block tables / per-tick operands committed
        # REPLICATED so every jitted step's in-avals (shardings included)
        # are identical tick after tick — zero recompiles under the mesh
        # is the same static-shape contract, extended to placement.  The
        # engine is TP-only by design: data parallelism is N engine
        # replicas behind a router (serve/replica.py), each on its own
        # mesh slice, not a batch axis inside one engine.
        self.mesh_plan = mesh_plan
        self._mesh_devices = mesh_devices
        self.mesh = None
        self._rep_sharding = None
        self._pool_shardings = None
        self._temp_cache_shardings = None
        self._kv_sharded = False
        # model=1 with an explicit device slice is the DP-without-TP
        # placement: a one-device mesh pins this replica's params, pool
        # and operands onto ITS chip instead of the process default
        if mesh_plan is not None and (
            mesh_plan.num_devices > 1 or mesh_devices is not None
        ):
            from jax.sharding import NamedSharding, PartitionSpec as P

            from llm_np_cp_tpu.parallel.sharding import (
                cache_specs,
                kv_heads_shardable,
                make_mesh,
                normalize_specs,
                paged_kv_specs,
                shard_params,
                to_shardings,
            )

            for axis in ("data", "seq", "pipe", "expert"):
                if getattr(mesh_plan, axis) != 1:
                    raise ValueError(
                        f"ServeEngine meshes are tensor-parallel only "
                        f"(model axis); got {axis}={getattr(mesh_plan, axis)}"
                        " — use serve/replica.py ReplicaSet for data "
                        "parallelism"
                    )
            mesh_plan.validate(config)
            self.mesh = make_mesh(mesh_plan, mesh_devices)
            params = shard_params(params, config, mesh_plan, self.mesh)
            self._rep_sharding = NamedSharding(self.mesh, P())
            self._kv_sharded = kv_heads_shardable(config, mesh_plan)
            self._pool_shardings = to_shardings(
                self.mesh, paged_kv_specs(config, mesh_plan,
                                          quantized=int8_cache)
            )
            self._temp_cache_shardings = to_shardings(
                self.mesh, normalize_specs(
                    cache_specs(config, mesh_plan, quantized=int8_cache)
                )
            )
            if mesh_plan.model > 1 and decode_attn_impl == "flash_decode":
                # the mask-driven decode kernel has no shard_map harness;
                # under a real TP mesh GSPMD would replicate its custom
                # call — worse than the partitionable gather math it
                # wraps (a one-device placement mesh is unaffected)
                decode_attn_impl = "xla"
            if (
                mesh_plan.model > 1
                and decode_attn_impl == "paged"
                and not self._kv_sharded
            ):
                # kv heads don't divide the model axis (TP + GQA hard
                # part): the slabs are replicated and the shard_map
                # harness (which splits the head axes) does not apply —
                # the partitionable gather path is the honest impl
                decode_attn_impl = "xla"
        self.decode_attn_impl = decode_attn_impl  # post-gate (tests/CLI)
        # -- unified-tick gate: "on" forces the unified tick (XLA ragged
        # fallback if Mosaic rejects the kernel), "auto" takes it only
        # when the ragged kernel probe passes (conservative: a broken
        # Mosaic toolchain keeps the battle-tested phase-split path),
        # "off" is the phase-split engine
        self.mixed_step_mode = mixed_step
        self.ragged_attn_impl: str | None = None
        if mixed_step == "off":
            self.mixed = False
        else:
            err = kernel_error(ragged_kernel_name(int8_cache))
            if err is None:
                self.mixed, self.ragged_attn_impl = True, "pallas"
            elif mixed_step == "on":
                import logging

                logging.getLogger("llm_np_cp_tpu").warning(
                    "mixed_step='on' with the ragged kernel unavailable "
                    "(%s); the unified tick will use the XLA gather "
                    "fallback attention", err,
                )
                self.mixed, self.ragged_attn_impl = True, "xla"
            else:
                self.mixed = False
        # -- speculative serving (draft-then-verify in the unified tick):
        # per-request host-side prompt-lookup draft streams propose up to
        # spec_k tokens; the mixed step packs each speculating request as
        # a ragged verify slice of width <= spec_k+1 and samples at EVERY
        # packed position with the (seed, content-pos) keys, so the
        # longest draft prefix matching those samples is accepted and the
        # stream stays token-identical to plain decode.  spec_k fixes the
        # verify-lane width of the compiled step ([R, spec_k+1] sample
        # operands), so it is an engine build parameter; requests opt in
        # per-submit and fall back per-request when rolling acceptance
        # collapses.
        if spec_k and not self.mixed:
            # mixed_step='auto' resolved to the phase-split engine (the
            # ragged probe failed): speculation has no verifier to ride —
            # serve plain rather than fail, and say so
            import logging

            logging.getLogger("llm_np_cp_tpu").warning(
                "spec_k=%d requested but the unified tick is unavailable "
                "(ragged kernel probe failed under mixed_step='auto'); "
                "speculative serving disabled, requests decode plain",
                spec_k,
            )
            spec_k = 0
        self.spec_k = spec_k
        self.spec_ngram = spec_ngram
        self.spec_min_accept = spec_min_accept
        self.spec_window = spec_window
        # per-request draft streams (serve/spec.DraftState) by req_id;
        # entries leave with the request (finish/abort), rebuilt lazily
        # after recovery from prompt + generated
        self._draft_states: dict[int, Any] = {}
        if (
            self.mixed and self.mesh is not None
            and self.mesh_plan.model > 1 and not self._kv_sharded
        ):
            # replicated kv heads under real TP: no shard_map harness for
            # the ragged kernel — the XLA ragged attention partitions
            # under GSPMD (one-device placement meshes keep the kernel)
            self.ragged_attn_impl = "xla"
        # seeded chaos schedule (serve/faults.py); None = every injection
        # point is a single is-None check (zero overhead)
        self.faults = fault_injector
        # request/tick trace recorder (serve/tracing.py); None = every
        # hook is a single is-None check, same discipline as faults
        # (pinned by tools/compile_counter.assert_tracing_hooks_guarded)
        self.tracer = tracer
        # durable request journal (serve/journal.py): admissions,
        # per-tick delivery watermarks, and terminals go to an fsync'd
        # file a restarted PROCESS replays through recover(); same
        # is-None zero-overhead discipline as faults/tracer
        self.journal = journal
        # canonical request log (serve/request_log.py): one wide-event
        # JSON line per terminal, written off the tick thread; same
        # is-None zero-overhead discipline
        self.request_log = request_log
        # tick anomaly sentinel (serve/slo.py TickSentinel): rolling
        # per-phase EWMA baselines over the tick-phase slices; rides
        # the tracer's phase timestamps, so it observes only when a
        # tracer is attached.  Same is-None discipline
        self.sentinel = sentinel
        # lifecycle auto-actions (serve/lifecycle.ActionPolicy): the
        # sentinel's host_sync verdicts and the SLO burn rate feed it
        # once per tick; its shed-prefill verdict caps the planner's
        # budget and its shed-load verdict flips HTTP admission to
        # 503-first.  Same is-None zero-overhead discipline
        self.actions = actions
        # device roofline telemetry (serve/telemetry.TelemetryModel):
        # an analytic per-tick byte/FLOP bill combined with the
        # measured dispatch→host-sync wall → achieved GB/s vs the HBM
        # roofline, an MFU estimate, and per-request cost attribution.
        # Host-side arithmetic only — attaching it adds zero dispatches
        # and zero recompiles (compile-counter telemetry section).
        # Same is-None zero-overhead discipline as faults/tracer
        self.telemetry = telemetry
        # multi-tenant accounting ledger (serve/tenants.TenantLedger):
        # per-tenant requests/tokens/cost/SLO folded in at terminals,
        # the fairness sort for plan_tick, and the per-tenant in-flight
        # cap.  Host-side bookkeeping over existing tick outputs — zero
        # dispatches, zero host syncs, zero recompiles.  Same is-None
        # zero-overhead discipline as faults/tracer
        self.tenants = tenants
        # which checkpoint these params came from: stamped onto every
        # request at admission (journal/request-log carry it), bumped
        # by a rolling upgrade's clone_fresh(params=..., ...)
        if weights_version < 0:
            raise ValueError(
                f"weights_version must be >= 0, got {weights_version}"
            )
        self.weights_version = int(weights_version)
        # reason string once the paged decode step faulted at dispatch
        # and the engine fell back to the gather impl (None = healthy)
        self.decode_degraded: str | None = None
        self.params = params
        self.config = config
        self.sampler = sampler or Sampler(kind="greedy")
        self.stop_tokens = tuple(stop_tokens)
        self.tokenizer = tokenizer
        self.clock = clock
        self.cache_dtype = jnp.dtype(cache_dtype)
        self.block_size = block_size
        self.prefill_chunk = prefill_chunk or block_size
        # per-request cache ceiling, in whole blocks (fixes the decode
        # gather width S_max = max_blocks_per_seq * block_size)
        self.max_seq_len = _ceil_to(max_seq_len, block_size)
        self.max_blocks_per_seq = self.max_seq_len // block_size
        # prefix-share granularity in BLOCKS: shared prefixes must cover
        # whole blocks (pool granularity) AND whole prefill chunks (so
        # skipped prefill work is exactly the shared region — a partial
        # chunk would re-prefill and re-WRITE a shared block)
        self._share_unit = (
            math.lcm(self.block_size, self.prefill_chunk) // self.block_size
        )

        self.pool = BlockPool(
            config, num_blocks, block_size, dtype=cache_dtype,
            enable_prefix_cache=enable_prefix_cache,
            shardings=self._pool_shardings,
        )
        self.scheduler = Scheduler(
            self.pool,
            max_slots=max_slots,
            block_size=block_size,
            blocks_for_prefill=lambda req: self.pool.blocks_for(
                self._prefill_width(req)
            ),
            prefill_plan=self._prefill_plan,
            max_queue=max_queue,
        )
        self.metrics = ServeMetrics(clock=clock)
        # -- host-RAM KV block tier (serve/host_tier.HostTier): spilled
        # prefix blocks keyed by the SAME chained content hash the
        # prefix cache uses, restored at admission as ordinary claimed
        # pool blocks.  None = every hook is a single is-None check
        # (tools/lint R4 `host_tier`), zero dispatches, zero recompiles.
        self.host_tier = host_tier
        # bytes one pool block holds across all layers (K+V + int8
        # scale pages) — the unit every tier ledger counts in
        self._block_nbytes = int(sum(
            a.nbytes // a.shape[1] for a in self.pool.pages
            if a is not None
        ))
        # per-tick tier observables (engine-thread-owned, reset at tick
        # start, reported in the tick trace args when the tier is on)
        self._tier_spill_bytes = 0
        self._tier_restore_bytes = 0
        self._tier_restore_us = 0.0
        if self.pool.prefix_cache is not None:
            # LRU reclaim is no longer silent: the callback counts the
            # eviction (llm_serve_prefix_evicted_total), traces it, and
            # — with the tier attached — spills the block instead of
            # just dropping it
            self.pool.prefix_cache.on_reclaim = self._on_prefix_reclaim
        if host_tier is not None:
            self._restore_block: Callable | None = (
                self._make_restore_block()
            )
            self._slice_block: Callable | None = self._make_slice_block()
            # startup breakeven measurements: host→device bandwidth
            # from a block-sized device_put probe; the recompute side
            # seeds from the analytic telemetry model when attached and
            # is refined by measured prefill rates every dispatching
            # tick (HostTier.note_prefill_rate)
            shape = self.pool.pages.k.shape
            blk_shape = (shape[0],) + shape[2:]
            probes = [(blk_shape, self.cache_dtype)] * 2
            if self.pool.pages.quantized:
                probes += [(blk_shape[:-1], jnp.float32)] * 2
            host_tier.ensure_probe(probes)
            if telemetry is not None:
                w = telemetry.weight_bytes(self.prefill_chunk, 1)
                host_tier.note_prefill_rate(
                    self.prefill_chunk / (w / (telemetry.hbm_gbps * 1e9))
                )
            self.metrics.on_tier_gauge(
                resident_bytes=host_tier.resident_bytes,
                breakeven=host_tier.breakeven_ratio(self.block_size),
            )
        else:
            self._restore_block = None
            self._slice_block = None
        self._next_id = 0
        self._detok: dict[int, IncrementalDetok] = {}
        # live (queued or running) requests by id — the abort/deadline
        # index; entries leave on finish and abort
        self._requests: dict[int, Request] = {}
        # device dispatches issued by this engine (every jitted-step
        # call) — the CPU-measurable observable for the unified tick's
        # "strictly fewer dispatches per tick" claim
        self.n_dispatches = 0

        # -- fused sampling epilogue gate (tick-tail fusion): the step's
        # final-norm → lm_head → sample chain runs as ONE Pallas kernel
        # over vocab tiles (ops/pallas/sample_epilogue.py) so the
        # [rows, V] logits never materialize in HBM.  Fused only when
        # the probe passes AND the draw is bit-identical to the XLA
        # oracle — today that is the greedy sampler over a float or
        # int8-"q" head on an unsharded (or placement-only) mesh; every
        # other combination keeps the final_logits+Sampler tail, which
        # remains the fallback/oracle everywhere ("off" forces it).
        self.sample_epilogue_mode = sample_epilogue
        self.epilogue_impl = "xla"
        if sample_epilogue != "off":
            from llm_np_cp_tpu.models.transformer import (
                epilogue_gate_error,
            )

            if self.mesh is not None and self.mesh_plan.model > 1:
                epi_err = ("model-sharded mesh (the kernel streams the "
                           "full lm head; a TP-aware epilogue is open "
                           "work)")
            else:
                epi_err = epilogue_gate_error(
                    params, config, self.sampler.kind
                )
            if epi_err is None:
                self.epilogue_impl = "fused"
            elif sample_epilogue == "on":
                import logging

                logging.getLogger("llm_np_cp_tpu").warning(
                    "sample_epilogue='on' but the fused epilogue "
                    "cannot serve this engine (%s); using the XLA "
                    "logits tail", epi_err,
                )

        if self.mixed:
            # -- unified tick: ONE jitted program, bucketed packed width.
            # The temp prefill cache, scatter_prefill, gather_prefix and
            # sample_first programs of the phase-split path do not exist
            # in this mode — prefill K/V goes straight into pool blocks
            # and sampling happens inside the mixed step.
            from llm_np_cp_tpu.ops.pallas.decode_attention import (
                RAGGED_Q_TILE,
            )

            self._q_tile = RAGGED_Q_TILE
            # verify-lane width of the compiled step: every row carries
            # spec_k+1 sample slots ([R, W] last_idx/sample_pos operands
            # and an [R, W] token return) — plain rows use column 0 and
            # the rest are discarded host-side, so the shape is static
            # whatever each tick's draft widths turn out to be
            self._spec_w = self.spec_k + 1
            # spec engines get verify headroom in the default budget:
            # drafts only ever spend budget prefill left over, so
            # without the extra room a busy admission window would trim
            # every draft to nothing and speculation would never engage
            budget = tick_token_budget or (
                max_slots * (1 + self.spec_k) + 2 * self.prefill_chunk
            )
            if budget < max_slots:
                raise ValueError(
                    f"tick_token_budget ({budget}) must be >= max_slots "
                    f"({max_slots}): every decode row needs one token per "
                    "tick before prefill fills the remainder"
                )
            self.tick_token_budget = budget
            self.mixed_buckets = self._make_buckets(budget, max_slots)
            self._mixed_step = self._make_mixed_step()
        else:
            self.tick_token_budget = 0
            self.mixed_buckets: tuple[int, ...] = ()
            # -- jitted programs (fixed set; tick loop never adds more)
            self._prefill_step = make_ragged_prefill_step(config)
            self._decode_step = self._make_decode_step(decode_attn_impl)
            self._sample_first = self._make_sample_first()
            self._scatter_prefill = self._make_scatter_prefill()
            self._gather_prefix = self._make_gather_prefix()
        # one-fetch ledger, initialized after the step builders: the
        # tick loops bump it at their single packed host_sync transfer
        # and the tick trace args carry the per-tick count
        self.n_host_fetches = 0

    def _make_buckets(self, budget: int, max_slots: int) -> tuple[int, ...]:
        """Packed-width buckets for the mixed step: a doubling ladder of
        q-tile multiples capped by the worst aligned total (every planned
        token plus per-row tile padding).  The mixed step compiles once
        per bucket actually used — never per tick, never per
        prefill:decode composition (compile-counter lint)."""
        qb = self._q_tile
        # each of up to max_slots segments wastes < qb lanes to alignment
        a_max = _ceil_to(budget + max_slots * (qb - 1), qb)
        buckets = []
        t = qb
        while t < a_max:
            buckets.append(t)
            t *= 2
        buckets.append(a_max)
        return tuple(sorted(set(buckets)))

    def _pick_bucket(self, n: int) -> int:
        for t in self.mixed_buckets:
            if t >= n:
                return t
        raise AssertionError(
            f"planner produced {n} aligned tokens > largest bucket "
            f"{self.mixed_buckets[-1]} — budget accounting is broken"
        )

    # ------------------------------------------------------------------
    # Mesh helpers (all no-ops on a single chip)
    # ------------------------------------------------------------------
    @property
    def mesh_desc(self) -> str | None:
        """Operator-readable mesh topology for the serve banner and
        ``/healthz`` (None on a single chip)."""
        if self.mesh is None:
            return None
        dev = next(iter(self.mesh.devices.flat))
        if self.mesh_plan.model == 1:
            # DP-without-TP placement mesh: one device, nothing sharded
            return f"pinned to {dev.platform} device {dev.id}"
        kv = "kv-sharded" if self._kv_sharded else "kv-replicated"
        return (f"tp={self.mesh_plan.model} over "
                f"{self.mesh_plan.num_devices} {dev.platform} devices "
                f"({kv})")

    def _put(self, a: Any) -> jnp.ndarray:
        """Per-tick operand placement.  Under a mesh every host-built
        operand (block tables, packed metadata, token ids) is committed
        FULLY REPLICATED, so each dispatch's in-avals — shardings
        included — are identical tick after tick: the zero-recompile
        contract extended to placement.  Replicated tables are also what
        keeps the scalar-prefetch kernels correct per shard: every
        device walks the same block ids over its head-slice of the
        slabs."""
        if self._rep_sharding is None:
            return jnp.asarray(a)
        return jax.device_put(a, self._rep_sharding)

    def _constrain_pages(self, pages: PagedKV) -> PagedKV:
        """Pin the slabs' sharding on a jitted step's OUTPUT (inside the
        jaxpr).  The pages a step returns re-enter the next dispatch, so
        their placement must be a fixed point of the program — GSPMD is
        free to choose output shardings otherwise, and a drifting choice
        would retrace every tick."""
        if self._pool_shardings is None:
            return pages
        return jax.tree.map(lax.with_sharding_constraint, pages,
                            self._pool_shardings)

    def _make_temp_cache(self) -> KVCache:
        cache = KVCache.init(self.config, 1, self.max_seq_len,
                             dtype=self.cache_dtype)
        if self._temp_cache_shardings is not None:
            cache = jax.tree.map(jax.device_put, cache,
                                 self._temp_cache_shardings)
        return cache

    def _repin_temp_cache(self, cache: KVCache) -> KVCache:
        """Re-commit a chunk-step output cache to the pinned temp-cache
        shardings (a no-op transfer when GSPMD already kept them): every
        ``prefill_step`` call must see identical in-avals or its
        ONE-compile contract breaks on the second chunk."""
        if self._temp_cache_shardings is None:
            return cache
        return jax.tree.map(jax.device_put, cache,
                            self._temp_cache_shardings)

    def _shard_attn(self, fn: Callable, *, quantized: bool, n_meta: int,
                    q_head_axis: int) -> Callable:
        """Wrap a per-layer paged-attention callable for the mesh.

        With kv heads sharded, the Pallas scalar-prefetch kernels (and
        their XLA fallbacks) run UNMODIFIED inside ``shard_map`` over the
        model axis: each device sees its head-slice of q
        (``q_head_axis`` names the head dim) and of the pool slabs
        (+ int8 scale pages), while tables / lengths / pads / window
        metadata arrive replicated — GQA's kv-major head order makes the
        local group math identical to the global one.  Softmax is
        per-head, so no cross-shard collective is needed; check_rep is
        off because the kernel's gathers defeat rep inference.

        Off-mesh (or kv-replicated) the callable runs as-is.  Calling
        convention: ``wrapped(q, k_pages, v_pages, [k_scale, v_scale,]
        *meta)`` — scales positional only in quantized mode, so None
        never crosses a shard_map boundary."""
        if quantized:
            def call(q, kp, vp, ks, vs, *meta):
                return fn(q, kp, vp, *meta, k_scale=ks, v_scale=vs)
        else:
            def call(q, kp, vp, *meta):
                return fn(q, kp, vp, *meta, k_scale=None, v_scale=None)
        if self.mesh is None or not self._kv_sharded:
            return call
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from llm_np_cp_tpu.parallel.sharding import MODEL_AXIS

        # no trailing Nones anywhere: unspecified trailing dims are
        # unsharded, and the normalized spelling is the one jit's
        # compile cache expects (tools/lint R1)
        qs = P(*([None] * q_head_axis), MODEL_AXIS)
        kvs = P(None, None, MODEL_AXIS)
        ss = P(None, None, MODEL_AXIS)
        rep = P()
        in_specs = (qs, kvs, kvs) + ((ss, ss) if quantized else ())
        in_specs += (rep,) * n_meta
        return shard_map(call, mesh=self.mesh, in_specs=in_specs,
                         out_specs=qs, check_rep=False)

    # ------------------------------------------------------------------
    def _prefill_width(self, req: Request) -> int:
        """Left-padded prefill width: the request's content rounded up to
        a whole number of chunks (ONE compiled chunk program for every
        prompt length)."""
        return _ceil_to(req.total_len, self.prefill_chunk)

    def _prefill_plan(self, req: Request) -> tuple[list[int], int]:
        """Admission plan: ``(claimed shared block ids, fresh blocks
        needed)``.  With the prefix cache on, the prompt's fully-filled
        leading blocks are hashed and the longest registered chain is
        CLAIMED (one reference per block); the fresh need excludes them,
        so shared blocks don't double-count against pool capacity.  The
        shareable span is capped at ``width - prefill_chunk``: the LAST
        chunk always re-prefills because the first token's logits come
        out of it, and the cap also guarantees decode writes land
        strictly past every shared block.

        With the host tier attached, keys the device cache misses are
        looked up host-side as well: a hit above the measured
        restore-vs-recompute breakeven allocates ordinary pool blocks
        for the span NOW and stages the restore after admission (the
        plan only DECIDES — no restore job exists until the admission
        sticks, so a backed-off plan frees the blocks with nothing in
        flight to write into them).  Below breakeven the span
        re-prefills (counted)."""
        w = self._prefill_width(req)
        total = self.pool.blocks_for(w)
        cache = self.pool.prefix_cache
        # a backed-off admission freed its planned restore blocks; the
        # stale plan must not survive into this attempt
        req.extra.pop("tier_restore", None)
        if cache is None:
            return [], total
        unit = self._share_unit
        n_keys = ((w - self.prefill_chunk) // (unit * self.block_size)) * unit
        if n_keys <= 0:
            return [], total
        # a request stuck at the queue head is re-planned EVERY tick —
        # reuse the hashes while its content (hence width) is unchanged
        # instead of re-running SHA-256 over the prompt each attempt
        keys = req.extra.get("prefix_keys")
        if keys is None or req.extra.get("prefix_keys_width") != w:
            content = req.effective_prompt()
            keys = prefix_block_keys(
                content, w - content.size, self.block_size, n_keys
            )
            req.extra["prefix_keys"] = keys
            req.extra["prefix_keys_width"] = w
        # only whole prefill chunks can be skipped — truncate the match
        # to share-unit multiples before claiming
        n_shared = (len(cache.match(keys)) // unit) * unit
        shared = cache.claim(keys[:n_shared]) if n_shared else []
        restore_ids: list[int] = []
        if self.host_tier is not None and n_shared < len(keys):
            # combined coverage walk: LRU reclaim evicts a chain entry
            # at a time, so a prefix routinely ends up SPLIT — some
            # keys spilled host-side, some still registered device-side
            # (in either interleaving).  Each covered position is
            # either a host hit (restore into a fresh block) or a
            # device hit (claim in place); the walk stops at the first
            # key neither side holds, and the covered span truncates to
            # whole share units like the device match above.
            span: list[tuple[bytes, int | None]] = []
            for key in keys[n_shared:]:
                # device first: a dual-resident key (spilled copy still
                # host-side AND re-registered device-side — routine
                # after ship-spills and evict-restore cycles) claims in
                # place for free instead of paying a block alloc + a
                # host→device copy
                dev = cache.match([key])
                if dev:
                    span.append((key, dev[0]))
                    continue
                if self.host_tier.contains(key):
                    span.append((key, None))
                    continue
                break
            span = span[: (len(span) // unit) * unit]
            n_host = sum(1 for _, b in span if b is None)
            if n_host and self.host_tier.should_restore(
                n_host, self.block_size
            ):
                # claim the span's device entries FIRST: their increfs
                # pin them against the LRU reclaim the restore-target
                # allocs below may trigger (an evicted-then-reused id
                # would corrupt the span)
                for key, dev_blk in span:
                    if dev_blk is not None:
                        cache.claim([key])
                plan: list[tuple[bytes, int, bool]] = []
                ordered: list[int] = []
                complete = True
                for key, dev_blk in span:
                    if dev_blk is not None:
                        ordered.append(dev_blk)
                        plan.append((key, dev_blk, False))
                        continue
                    ids = self.pool.alloc(1)
                    if ids is None:
                        complete = False
                        break
                    ordered.append(ids[0])
                    plan.append((key, ids[0], True))
                if complete:
                    restore_ids = ordered
                    req.extra["tier_restore"] = plan
                else:
                    # roll the partial span back: decref the claimed
                    # device entries, free the allocated targets —
                    # nothing was enqueued, so nothing dangles
                    self.pool.free(ordered)
                    for key, dev_blk in span[len(ordered):]:
                        if dev_blk is not None:
                            self.pool.free([dev_blk])
            elif n_host:
                # measured breakeven says re-prefilling is cheaper than
                # restoring this span — fall back, visibly
                self.host_tier.note_skip(n_host)
        return shared + restore_ids, total - len(shared) - len(restore_ids)

    def compile_counts(self) -> dict[str, int]:
        """Compiled-program count per jitted step (the static-shape
        contract: decode/prefill/sample stay at 1; scatter grows once per
        distinct prefill block count).  tools/compile_counter.py wraps
        this for the CI check.

        Unified-tick engines report ONE program — ``mixed_step``, one
        compile per packed-width bucket — and none of the phase-split
        programs exist (the ``gather_prefix`` copy in particular is
        deleted, pinned by the lint)."""

        def size(fn: Any) -> int:
            get = getattr(fn, "_cache_size", None)
            return int(get()) if get is not None else -1

        if self.mixed:
            out = {"mixed_step": size(self._mixed_step)}
        else:
            out = {
                "prefill_step": size(self._prefill_step),
                "decode_step": size(self._decode_step),
                "sample_first": size(self._sample_first),
                "scatter_prefill": size(self._scatter_prefill),
                "gather_prefix": size(self._gather_prefix),
            }
        if self._restore_block is not None:
            # the host tier's two programs: block id is traced and the
            # staged/sliced layout fixed, so each must stay at ONE
            # compile however many blocks spill or restore
            out["restore_block"] = size(self._restore_block)
            out["slice_block"] = size(self._slice_block)
        return out

    # ------------------------------------------------------------------
    # Jitted step builders
    # ------------------------------------------------------------------
    def _make_sample_first(self) -> Callable:
        sampler = self.sampler

        @jax.jit
        def sample_first(logits: jnp.ndarray, seed: jnp.ndarray, pos: jnp.ndarray):
            key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
            return sampler(key, logits)

        return sample_first

    def _make_scatter_prefill(self) -> Callable:
        quantized = self.cache_dtype == jnp.int8
        bs = self.block_size
        constrain_pages = self._constrain_pages

        @partial(jax.jit, donate_argnums=(0,))
        def scatter_prefill(
            pages: PagedKV, cache: KVCache, ids: jnp.ndarray,
            start: jnp.ndarray,
        ):
            # cache: batch-1 contiguous prefill cache at the FIXED temp
            # capacity (max_seq_len); the nb*bs slots from block offset
            # ``start`` (traced — prefix hits shift it without a
            # retrace) hold this request's freshly prefilled content.
            # Shared prefix blocks before ``start`` are NEVER written.
            nb = ids.shape[0]

            def put(slab, page, trailing):  # slab [L, 1, max_seq_len, *t]
                l = slab.shape[0]
                fresh = lax.dynamic_slice_in_dim(slab, start * bs, nb * bs, 1)
                return page.at[:, ids].set(
                    fresh.reshape((l, nb, bs) + trailing)
                )

            kh, d = cache.k.shape[-2:]
            new = PagedKV(
                k=put(cache.k[:, 0], pages.k, (kh, d)),
                v=put(cache.v[:, 0], pages.v, (kh, d)),
                k_scale=(
                    put(cache.k_scale[:, 0], pages.k_scale, (kh,))
                    if quantized else None
                ),
                v_scale=(
                    put(cache.v_scale[:, 0], pages.v_scale, (kh,))
                    if quantized else None
                ),
            )
            return constrain_pages(new)

        return scatter_prefill

    def _make_gather_prefix(self) -> Callable:
        """(temp cache, pages, shared ids [H], pad) → temp cache with the
        shared blocks' K/V copied into slots [0, H*bs) and validity/
        length restored — the state a full prefill of those chunks would
        have left, so the remaining chunks attend correctly.  One small
        copy program per distinct shared-block count (same compile class
        as the scatter), instead of re-running the model over the shared
        chunks."""
        quantized = self.cache_dtype == jnp.int8
        bs = self.block_size
        cap = self.max_seq_len

        @partial(jax.jit, donate_argnums=(0,))
        def gather_prefix(
            cache: KVCache, pages: PagedKV, ids: jnp.ndarray,
            pad: jnp.ndarray,
        ):
            h = ids.shape[0]
            l = pages.k.shape[0]

            def get(page, trailing):  # [L, NB, bs, *t] → [L, 1, h*bs, *t]
                return page[:, ids].reshape((l, 1, h * bs) + trailing)

            def put(slab, page, trailing):
                return slab.at[:, :, : h * bs].set(get(page, trailing))

            kh, d = pages.k.shape[-2:]
            pos = jnp.arange(cap, dtype=jnp.int32)[None, :]
            valid = (pos >= pad) & (pos < h * bs)
            return KVCache(
                k=put(cache.k, pages.k, (kh, d)),
                v=put(cache.v, pages.v, (kh, d)),
                valid=valid,
                length=jnp.full((), h * bs, jnp.int32),
                k_scale=(
                    put(cache.k_scale, pages.k_scale, (kh,))
                    if quantized else None
                ),
                v_scale=(
                    put(cache.v_scale, pages.v_scale, (kh,))
                    if quantized else None
                ),
            )

        return gather_prefix

    def _make_restore_block(self) -> Callable:
        """(pages, blk, k, v[, ks, vs]) → pages with one staged
        host-tier block written at pool block ``blk`` — the landing
        step of a restore.  ``blk`` arrives as a traced device scalar
        and the staged arrays have the block's fixed [L, BS, K, D]
        layout, so the program compiles ONCE for the process however
        many blocks restore (the tier's zero-new-recompiles contract,
        compile_counter tiered section)."""
        quantized = self.cache_dtype == jnp.int8
        constrain_pages = self._constrain_pages

        if quantized:
            @partial(jax.jit, donate_argnums=(0,))
            def restore_block(pages: PagedKV, blk: jnp.ndarray,
                              k: jnp.ndarray, v: jnp.ndarray,
                              ks: jnp.ndarray, vs: jnp.ndarray):
                new = PagedKV(
                    k=pages.k.at[:, blk].set(k),
                    v=pages.v.at[:, blk].set(v),
                    k_scale=pages.k_scale.at[:, blk].set(ks),
                    v_scale=pages.v_scale.at[:, blk].set(vs),
                )
                return constrain_pages(new)
        else:
            @partial(jax.jit, donate_argnums=(0,))
            def restore_block(pages: PagedKV, blk: jnp.ndarray,
                              k: jnp.ndarray, v: jnp.ndarray):
                new = PagedKV(
                    k=pages.k.at[:, blk].set(k),
                    v=pages.v.at[:, blk].set(v),
                )
                return constrain_pages(new)
        return restore_block

    def _make_slice_block(self) -> Callable:
        """(pages, blk) → one block's per-layer K/V (+ scale pages) as
        standalone device arrays — the spill path's read.  The block id
        is a TRACED scalar: an eager ``pages.k[:, blk]`` would bake
        each Python-int index into its jaxpr and compile once per
        distinct block id as spills churn (caught by the
        compile-counter tiered section); this program compiles once,
        full stop.  NOT donated — the pool keeps its pages; the slices
        are the copies the tier's writer thread syncs to host."""
        quantized = self.cache_dtype == jnp.int8

        @jax.jit
        def slice_block(pages: PagedKV, blk: jnp.ndarray):
            def take(a):
                return lax.dynamic_index_in_dim(a, blk, axis=1,
                                                keepdims=False)

            out = (take(pages.k), take(pages.v))
            if quantized:
                out += (take(pages.k_scale), take(pages.v_scale))
            return out

        return slice_block

    # ------------------------------------------------------------------
    # Host-RAM KV tier (serve/host_tier.py)
    # ------------------------------------------------------------------
    def _on_prefix_reclaim(self, key: bytes, blk: int) -> None:
        """One prefix-cache entry is about to be LRU-reclaimed (its
        block returns to the free list).  Always counted and traced —
        reclaim used to be silent, so drop-vs-spill behavior was
        invisible on the scrape — and, with the host tier attached,
        the block's K/V is sliced for the writer thread BEFORE the id
        frees: the eager per-block slice is an async device op ordered
        ahead of any later overwrite, so the spill copy is race-free by
        dispatch order and the tick thread never blocks on it."""
        nbytes = self._block_nbytes
        spilled = False
        if self.host_tier is not None:
            # snapshot the pages: a supervisor rebuild yanks the dead
            # engine's slabs from ITS thread, and a zombie tick racing
            # that yank must degrade to plain drop, not crash inside
            # PrefixCache.release with the entry half-reclaimed
            pages = self.pool.pages
            if pages is not None:
                spilled = True
                try:
                    arrs = self._slice_block(
                        pages, self._put(np.int32(blk))
                    )
                except Exception:  # noqa: BLE001 — dead-pool slice = drop
                    spilled = False
                else:
                    # the tier dedupes resident AND queued keys; the
                    # LEDGERS count only blocks it actually accepted —
                    # a re-eviction or a ship-spill race moves no bytes
                    # and must not inflate the spill counters past the
                    # tier's own accounting
                    if self.host_tier.enqueue_spill(key, *arrs):
                        self._tier_spill_bytes += nbytes
                        self.metrics.on_tier_spill(blocks=1,
                                                   nbytes=nbytes)
        self.metrics.on_prefix_evicted(blocks=1, nbytes=nbytes)
        if self.tracer is not None:
            self.tracer.instant("prefix-evict", cat="kv_tier", args={
                "blocks": 1, "bytes": nbytes, "spilled": spilled,
            })

    def _enqueue_tier_restores(self, req: Request) -> None:
        """Stage the admission plan's host-tier hits: one writer-thread
        ``jax.device_put`` job per block (replicated under a mesh so
        the restore write's in-avals stay placement-stable).  Runs only
        AFTER the admission stuck — the planned blocks are now owned by
        ``req``, so a job can never target a free-listed id."""
        plan = req.extra.get("tier_restore")
        if not plan or self.host_tier is None:
            return
        req.extra["tier_tickets"] = [
            self.host_tier.enqueue_restore(key, blk, self._rep_sharding)
            for key, blk, is_restore in plan if is_restore
        ]

    def _apply_tier_restores(self, reqs: list[Request]) -> None:
        """Land staged restores as ordinary pool blocks BEFORE the
        covering dispatch (the planner pre-covered them, so they must
        hold real K/V by then; ``host_sync`` never waits on a tier
        transfer).  A miss — the host entry raced a capacity eviction,
        or staging failed — un-covers the tail of the span: those
        blocks stay allocated and ordinary prefill writes them, so the
        stream is correct either way, just slower.  Successful spans
        register in the device prefix cache immediately: they ARE valid
        registered prefix blocks again, so LATER admissions hit them
        device-side.  (Siblings admitted in the SAME admit() batch all
        planned before any registration landed, so each restores its
        own copy — wasteful for one batch but correct; deduping at plan
        time would make a sibling depend on a peer's not-yet-landed
        restore, whose failure path re-writes the block inside the very
        dispatch the sibling attends it in.)"""
        if self.host_tier is None:
            return
        for req in reqs:
            plan = req.extra.pop("tier_restore", None)
            tickets = req.extra.pop("tier_tickets", None)
            if not plan or tickets is None:
                continue
            results = iter(self.host_tier.take_restored(tickets))
            n_dev = req.n_shared_blocks - len(plan)
            quantized = self.cache_dtype == jnp.int8
            ok = 0
            n_restored = 0
            lat = 0.0
            pages = self.pool.pages
            for key, blk, is_restore in plan:
                if not is_restore:
                    ok += 1  # device-claimed in place: already valid
                    continue
                res = next(results)
                if res is None:
                    break  # coverage is prefix-contiguous: stop here
                _, staged, dt = res
                args = (staged.k, staged.v)
                if quantized:
                    args += (staged.k_scale, staged.v_scale)
                self.n_dispatches += 1
                pages = self._restore_block(
                    pages, self._put(np.int32(blk)), *args
                )
                ok += 1
                n_restored += 1
                lat = max(lat, dt)
            self.pool.pages = pages
            unit = self._share_unit
            ok = (ok // unit) * unit  # coverage in whole share units
            if ok < len(plan):
                # re-prefill the un-covered tail: shrink the covered
                # span; the tail blocks stay in req.block_ids and the
                # prefill writes them — a device-claimed block rounded
                # out of the span is rewritten with BIT-IDENTICAL
                # content (a slot's K/V depends only on its token and
                # position), so sharers are unaffected
                req.n_shared_blocks = n_dev + ok
                req.prefill_done = min(
                    req.prefill_done,
                    max(req.n_shared_blocks * self.block_size - req.pad,
                        0),
                )
            pc = self.pool.prefix_cache
            for key, blk, is_restore in plan[:ok]:
                # restored blocks ARE valid registered prefix blocks
                # again — register immediately so a same-tick sibling
                # admission hits them device-side (device-claimed
                # entries are registered already; register only
                # LRU-touches them)
                if is_restore and pc is not None:
                    pc.register([key], [blk])
            if n_restored:
                nbytes = n_restored * self._block_nbytes
                self._tier_restore_bytes += nbytes
                self._tier_restore_us += lat * 1e6
                self.metrics.on_tier_restore(
                    blocks=n_restored, nbytes=nbytes, latency_s=lat,
                )
                if self.tracer is not None:
                    self.tracer.request_instant(
                        req.req_id, "kv-restore", args=self._targs(
                            req, blocks=n_restored, bytes=nbytes,
                            restore_us=round(lat * 1e6, 1),
                        ))

    def spill_prefix_blocks(self, keys: list | None = None) -> int:
        """Ship registered prefix blocks into the host tier WITHOUT
        dropping them — the fleet's block-shipping primitive: a drain/
        re-home (or a router spill verdict) copies the source replica's
        prefix K/V host-side so the DESTINATION replica restores the
        prefix instead of re-prefilling it (serve/replica.py wires
        this into drain-to-peer, remove_replica and rolling upgrades).

        ``keys=None`` ships every registered entry (a draining
        replica's whole prefix set); passing a key chain ships just the
        matched prefix.  Safe from any thread: a REGISTERED full prefix
        block is never rewritten while registered (decode and suffix
        prefill write strictly past shared blocks), so the eager
        per-block device slices are stable whatever the tick thread is
        doing, and the tier's writer thread pays the actual copies.
        Returns the number of blocks enqueued."""
        if self.host_tier is None or self.pool.prefix_cache is None \
                or self.pool.pages is None:
            return 0
        if keys is None:
            pairs = self.pool.prefix_cache.items()
        else:
            ids = self.pool.prefix_cache.match(list(keys))
            pairs = list(zip(keys, ids))
        n = 0
        for key, blk in pairs:
            if self.host_tier.contains(key):
                continue  # fast path; the enqueue dedupe is authoritative
            pages = self.pool.pages
            if pages is None:
                break  # supervisor yanked the slabs mid-walk
            try:
                arrs = self._slice_block(pages, self._put(np.int32(blk)))
            except Exception:  # noqa: BLE001 — crashed-engine drains ship
                # what they can: a faulted donated dispatch may have
                # consumed the dead pool's buffers, in which case the
                # un-shipped prefixes just re-prefill (the tier-less
                # behavior), never break the drain itself
                break
            if self.host_tier.enqueue_spill(key, *arrs):
                self.metrics.on_tier_spill(blocks=1,
                                           nbytes=self._block_nbytes)
                n += 1
        return n

    def _make_decode_step(self, attn_impl: str) -> Callable:
        if attn_impl == "paged":
            return self._make_paged_decode_step()
        config, sampler = self.config, self.sampler
        bs = self.block_size
        quantized = self.cache_dtype == jnp.int8
        use_epilogue = self.epilogue_impl == "fused"
        stop_tokens = self.stop_tokens
        constrain_pages = self._constrain_pages

        @partial(jax.jit, donate_argnums=(1,))
        def decode_step(
            params: Params,
            pages: PagedKV,
            tables: jnp.ndarray,   # [B, MB] int32 (scratch-0 padded)
            lengths: jnp.ndarray,  # [B] int32 — cache slots already written
            pads: jnp.ndarray,     # [B] int32 — left pads per row
            toks: jnp.ndarray,     # [B] int32 — current input token
            seeds: jnp.ndarray,    # [B] uint32 — per-request RNG seed
        ):
            l_axis, b = pages.k.shape[0], tables.shape[0]
            kh, d = pages.k.shape[-2:]
            s_max = tables.shape[1] * bs

            def gather(page, trailing):  # [L, NB, bs, *t] → [L, B, S_max, *t]
                return page[:, tables].reshape((l_axis, b, s_max) + trailing)

            pos = jnp.arange(s_max, dtype=jnp.int32)[None, :]
            valid = (pos >= pads[:, None]) & (pos < lengths[:, None])
            cache = KVCache(
                k=gather(pages.k, (kh, d)),
                v=gather(pages.v, (kh, d)),
                valid=valid,
                length=lengths,
                k_scale=gather(pages.k_scale, (kh,)) if quantized else None,
                v_scale=gather(pages.v_scale, (kh,)) if quantized else None,
            )
            content_pos = lengths - pads
            if use_epilogue:
                # fused tail (greedy-exact — see _make_mixed_step): the
                # [B, 1, V] logits never materialize
                from llm_np_cp_tpu.models.transformer import (
                    sample_epilogue_tail,
                )

                hid, cache = forward(
                    params, toks[:, None], config, cache,
                    logits_last_only=True, pad_offsets=pads,
                    attn_impl=attn_impl, skip_logits=True,
                )
                nxt = sample_epilogue_tail(params, hid[:, -1], config)
            else:
                logits, cache = forward(
                    params, toks[:, None], config, cache,
                    logits_last_only=True, pad_offsets=pads,
                    attn_impl=attn_impl,
                )
                # Per-row keys from (request seed, content position): a
                # request resumed after preemption replays the same
                # stream, so stochastic samplers are
                # preemption-transparent too.
                keys = jax.vmap(
                    lambda s, t: jax.random.fold_in(
                        jax.random.PRNGKey(s), t
                    )
                )(seeds, content_pos)
                nxt = jax.vmap(lambda k, lg: sampler(k, lg[None])[0])(
                    keys, logits[:, -1]
                )

            # Extract the newly written K/V column (slot ``lengths`` per
            # row) from the gathered view and scatter it into the pool.
            def col(slab):  # [L, B, S_max, ...] → [L, B, ...] at per-row offset
                return jax.vmap(
                    lambda sl, off: lax.dynamic_index_in_dim(
                        sl, off, axis=1, keepdims=False
                    ),
                    in_axes=(1, 0), out_axes=1,
                )(slab, lengths)

            blk = jnp.take_along_axis(tables, (lengths // bs)[:, None], axis=1)[:, 0]
            off = lengths % bs
            # inactive rows all hit (scratch block 0, slot 0); duplicate
            # scatter indices there are harmless — the data is garbage by
            # construction and never gathered through a real table
            new_pages = PagedKV(
                k=pages.k.at[:, blk, off].set(col(cache.k)),
                v=pages.v.at[:, blk, off].set(col(cache.v)),
                k_scale=(
                    pages.k_scale.at[:, blk, off].set(col(cache.k_scale))
                    if quantized else None
                ),
                v_scale=(
                    pages.v_scale.at[:, blk, off].set(col(cache.v_scale))
                    if quantized else None
                ),
            )
            # one-fetch contract, W=1 degenerate case: [B, 4] packed
            # (token, stop-hit, watermark, accept)
            packed = _pack_sync(
                nxt[:, None], _stop_hits(nxt[:, None], stop_tokens),
                jnp.zeros_like(nxt),
            )
            return packed, constrain_pages(new_pages)

        return decode_step

    def _make_paged_decode_step(self) -> Callable:
        """The zero-gather decode step: the layer scan threads the pool
        slabs themselves ([L, NB, BS, K, D] xs), each layer scatters the
        new token's K/V column straight into its slab and attends with
        ``paged_decode_attention`` through the scalar-prefetched block
        tables — no [L, B, S_max] view ever materializes (pinned by a
        jaxpr-inspection test).  Shapes are identical to the gather
        step's host contract, so the tick loop is impl-agnostic."""
        from llm_np_cp_tpu.ops.pallas.decode_attention import (
            paged_decode_attention,
        )

        config, sampler = self.config, self.sampler
        bs = self.block_size
        quantized = self.cache_dtype == jnp.int8
        win = config.sliding_window
        num_layers = config.num_hidden_layers
        use_epilogue = self.epilogue_impl == "fused"
        stop_tokens = self.stop_tokens
        constrain_pages = self._constrain_pages
        attn_call = self._shard_attn(
            partial(
                paged_decode_attention,
                scale=config.attn_scale,
                logit_softcap=config.attn_logit_softcapping,
            ),
            quantized=quantized, n_meta=3, q_head_axis=2,
        )

        @partial(jax.jit, donate_argnums=(1,))
        def decode_step(
            params: Params,
            pages: PagedKV,
            tables: jnp.ndarray,   # [B, MB] int32 (scratch-0 padded)
            lengths: jnp.ndarray,  # [B] int32 — cache slots already written
            pads: jnp.ndarray,     # [B] int32 — left pads per row
            toks: jnp.ndarray,     # [B] int32 — current input token
            seeds: jnp.ndarray,    # [B] uint32 — per-request RNG seed
        ):
            # this tick writes slot ``lengths`` per row; attention then
            # sees slots [pads, lengths+1) — causality is positional
            # (the query IS the newest token), so no mask tensor exists
            blk = jnp.take_along_axis(
                tables, (lengths // bs)[:, None], axis=1
            )[:, 0]
            off = lengths % bs
            vis = lengths + 1
            content_pos = lengths - pads

            x = embed_inputs(params, toks[:, None], config)
            cos, sin = rope_cos_sin(
                content_pos[:, None], config, dtype=jnp.float32
            )
            act = ACT2FN[config.hidden_act]
            is_sliding = jnp.array(
                [config.layer_is_sliding(i) for i in range(num_layers)],
                dtype=jnp.bool_,
            )

            def layer_step(x: jnp.ndarray, xs: tuple) -> tuple:
                if quantized:
                    w, kp, vp, ksp, vsp, sliding = xs
                else:
                    w, kp, vp, sliding = xs

                def kv_update(k, v):  # fresh projections [B, 1, K, D]
                    # inactive rows all write (scratch block 0, slot 0);
                    # duplicate scatter indices there are harmless —
                    # garbage by construction, never visible
                    if quantized:
                        kq, ks = quantize_kv(k)
                        vq, vs = quantize_kv(v)
                        return (
                            (kp.at[blk, off].set(kq[:, 0]),
                             ksp.at[blk, off].set(ks[:, 0])),
                            (vp.at[blk, off].set(vq[:, 0]),
                             vsp.at[blk, off].set(vs[:, 0])),
                        )
                    return (
                        kp.at[blk, off].set(k[:, 0]),
                        vp.at[blk, off].set(v[:, 0]),
                    )

                def attn_fn(q, k_att, v_att, sliding_l):
                    if quantized:
                        (kp2, ksp2), (vp2, vsp2) = k_att, v_att
                    else:
                        kp2, vp2 = k_att, v_att
                        ksp2 = vsp2 = None
                    row_pads = pads
                    if win is not None:
                        # the single query sits at slot ``vis - 1``; a
                        # sliding layer sees slots > vis-1-win, i.e. an
                        # effective left pad of vis - win
                        row_pads = jnp.where(
                            sliding_l, jnp.maximum(pads, vis - win), pads
                        )
                    scales = (ksp2, vsp2) if quantized else ()
                    return attn_call(
                        q, kp2, vp2, *scales, tables, vis, row_pads,
                    )

                x, kv_att, _, _ = run_decoder_layer(
                    w, x, config=config, act=act, cos=cos, sin=sin,
                    sliding=sliding, kv_update=kv_update, attn_fn=attn_fn,
                )
                if quantized:
                    (kp2, ksp2), (vp2, vsp2) = kv_att
                    return x, (kp2, vp2, ksp2, vsp2)
                return x, kv_att

            xs: tuple = (params["layers"], pages.k, pages.v)
            if quantized:
                xs += (pages.k_scale, pages.v_scale)
            xs += (is_sliding,)
            x, ys = lax.scan(layer_step, x, xs, unroll=scan_unroll(config))
            new_pages = PagedKV(
                k=ys[0], v=ys[1],
                k_scale=ys[2] if quantized else None,
                v_scale=ys[3] if quantized else None,
            )
            new_pages = constrain_pages(new_pages)
            if use_epilogue:
                # fused tail (greedy-exact — see _make_mixed_step)
                from llm_np_cp_tpu.models.transformer import (
                    sample_epilogue_tail,
                )

                nxt = sample_epilogue_tail(params, x[:, -1], config)
            else:
                logits = final_logits(params, x, config, last_only=True)
                # same (seed, content position) key derivation as the
                # gather step — the RNG stream is impl- and
                # preemption-invariant
                keys = jax.vmap(
                    lambda s, t: jax.random.fold_in(
                        jax.random.PRNGKey(s), t
                    )
                )(seeds, content_pos)
                nxt = jax.vmap(lambda k, lg: sampler(k, lg[None])[0])(
                    keys, logits[:, -1]
                )
            packed = _pack_sync(
                nxt[:, None], _stop_hits(nxt[:, None], stop_tokens),
                jnp.zeros_like(nxt),
            )
            return packed, new_pages

        return decode_step

    def _make_mixed_step(self) -> Callable:
        """The unified-tick program: ONE dispatch runs a packed ragged
        batch of prefill chunk slices (q_len up to ``prefill_chunk``)
        and decode rows (q_len 1) through the layer scan, scattering
        every token's K/V straight into its pool block and attending
        through the block tables — no temp prefill cache, no
        ``gather_prefix`` copy (shared prefix blocks are read in place),
        no separate sample dispatch (logits are gathered at each row's
        last packed token and sampled in-graph with the SAME
        (seed, content position) key derivation as both split-path
        samplers, so tokens are impl- and preemption-invariant).

        Shapes are static per packed-width bucket: [T] token-level
        operands, [T/q_tile] tile metadata for the ragged kernel,
        [max_slots] row-level operands.  One compile per bucket, zero
        per tick (tools/compile_counter lint)."""
        from llm_np_cp_tpu.ops.pallas.decode_attention import (
            ragged_paged_attention,
            ragged_paged_attention_xla,
        )

        config, sampler = self.config, self.sampler
        quantized = self.cache_dtype == jnp.int8
        win = config.sliding_window
        num_layers = config.num_hidden_layers
        use_kernel = self.ragged_attn_impl == "pallas"
        use_epilogue = self.epilogue_impl == "fused"
        stop_tokens = self.stop_tokens
        big_win = jnp.int32(1 << 30)
        constrain_pages = self._constrain_pages
        attn_call = self._shard_attn(
            partial(
                ragged_paged_attention if use_kernel
                else ragged_paged_attention_xla,
                scale=config.attn_scale,
                logit_softcap=config.attn_logit_softcapping,
            ),
            quantized=quantized, n_meta=6, q_head_axis=1,
        )

        @partial(jax.jit, donate_argnums=(1,))
        def mixed_step(
            params: Params,
            pages: PagedKV,
            tokens: jnp.ndarray,      # [T] int32 packed input ids
            positions: jnp.ndarray,   # [T] int32 content positions (RoPE)
            tok_blk: jnp.ndarray,     # [T] int32 pool block per token
            tok_off: jnp.ndarray,     # [T] int32 in-block slot per token
            tok_row: jnp.ndarray,     # [T] int32 owning engine row
            tok_slot: jnp.ndarray,    # [T] int32 cache slot per token
            tok_live: jnp.ndarray,    # [T] bool (False = packing lane)
            tile_row: jnp.ndarray,    # [T/QB] int32
            tile_qpos0: jnp.ndarray,  # [T/QB] int32
            tile_qlen: jnp.ndarray,   # [T/QB] int32
            tables: jnp.ndarray,      # [R, MB] int32 (scratch-0 padded)
            pads: jnp.ndarray,        # [R] int32
            last_idx: jnp.ndarray,    # [R, W] int32 packed sample indices
            sample_pos: jnp.ndarray,  # [R, W] int32 content pos of each
            seeds: jnp.ndarray,       # [R] uint32
            verify_len: jnp.ndarray,  # [R] int32 live sample slots per row
        ):
            x = embed_inputs(params, tokens[None, :], config)  # [1, T, H]
            cos, sin = rope_cos_sin(
                positions[None, :], config, dtype=jnp.float32
            )
            act = ACT2FN[config.hidden_act]
            is_sliding = jnp.array(
                [config.layer_is_sliding(i) for i in range(num_layers)],
                dtype=jnp.bool_,
            )

            def layer_step(x: jnp.ndarray, xs: tuple) -> tuple:
                if quantized:
                    w, kp, vp, ksp, vsp, sliding = xs
                else:
                    w, kp, vp, sliding = xs

                def kv_update(k, v):  # fresh projections [1, T, K, D]
                    # dead lanes all write (scratch block 0, slot 0) —
                    # duplicate scatter indices there are harmless
                    if quantized:
                        kq, ks = quantize_kv(k)
                        vq, vs = quantize_kv(v)
                        return (
                            (kp.at[tok_blk, tok_off].set(kq[0]),
                             ksp.at[tok_blk, tok_off].set(ks[0])),
                            (vp.at[tok_blk, tok_off].set(vq[0]),
                             vsp.at[tok_blk, tok_off].set(vs[0])),
                        )
                    return (
                        kp.at[tok_blk, tok_off].set(k[0]),
                        vp.at[tok_blk, tok_off].set(v[0]),
                    )

                def attn_fn(q, k_att, v_att, sliding_l):
                    if quantized:
                        (kp2, ksp2), (vp2, vsp2) = k_att, v_att
                    else:
                        kp2, vp2 = k_att, v_att
                        ksp2 = vsp2 = None
                    win_eff = (
                        jnp.where(sliding_l, jnp.int32(win), big_win)
                        if win is not None else big_win
                    )
                    scales = (ksp2, vsp2) if quantized else ()
                    if use_kernel:
                        out = attn_call(
                            q[0], kp2, vp2, *scales, tables, tile_row,
                            tile_qpos0, tile_qlen, pads, win_eff,
                        )
                    else:
                        out = attn_call(
                            q[0], kp2, vp2, *scales, tables, tok_row,
                            tok_slot, tok_live, pads, win_eff,
                        )
                    return out[None]

                x, kv_att, _, _ = run_decoder_layer(
                    w, x, config=config, act=act, cos=cos, sin=sin,
                    sliding=sliding, kv_update=kv_update, attn_fn=attn_fn,
                )
                if quantized:
                    (kp2, ksp2), (vp2, vsp2) = kv_att
                    return x, (kp2, vp2, ksp2, vsp2)
                return x, kv_att

            xs: tuple = (params["layers"], pages.k, pages.v)
            if quantized:
                xs += (pages.k_scale, pages.v_scale)
            xs += (is_sliding,)
            x, ys = lax.scan(layer_step, x, xs, unroll=scan_unroll(config))
            new_pages = PagedKV(
                k=ys[0], v=ys[1],
                k_scale=ys[2] if quantized else None,
                v_scale=ys[3] if quantized else None,
            )
            new_pages = constrain_pages(new_pages)
            # sampling ONLY at each row's sample slots — [R, W] packed
            # indices: column 0 is the plain sample (decode rows and
            # completing prefill segments), columns 1..k' are a
            # speculating row's verify positions; unused slots point at
            # packed index 0 and their draw is discarded host-side.
            # Keys derive from (seed, content position) per slot, so a
            # verify sample at position p is BIT-IDENTICAL to the plain
            # decode draw at p — the accept walk's whole parity story.
            xr = x[0][last_idx]  # [R, W, H]
            r_rows, w_cols = xr.shape[0], xr.shape[1]
            if use_epilogue:
                # fused tail: norm → lm_head → greedy sample streamed
                # over vocab tiles — the [R, W, V] logits never exist
                # (pinned by a jaxpr-inspection test).  Greedy ignores
                # the RNG keys, so the draw is bit-identical to the
                # oracle branch below.
                from llm_np_cp_tpu.models.transformer import (
                    sample_epilogue_tail,
                )

                nxt = sample_epilogue_tail(
                    params, xr.reshape(r_rows * w_cols, -1), config
                ).reshape(r_rows, w_cols)
            else:
                logits = final_logits(params, xr, config)  # [R, W, V]
                keys = jax.vmap(
                    lambda s, ps: jax.vmap(
                        lambda t: jax.random.fold_in(
                            jax.random.PRNGKey(s), t
                        )
                    )(ps)
                )(seeds, sample_pos)
                nxt = jax.vmap(
                    jax.vmap(lambda k, lg: sampler(k, lg[None])[0])
                )(keys, logits)
            # in-graph accept walk + stop detection, so host_sync is ONE
            # packed transfer: a verify slice's draft tokens ARE the
            # packed input tokens at columns 1..k', so the longest
            # matching prefix is computable without a host round-trip
            drafts = tokens[last_idx[:, 1:]]  # [R, W-1]
            jpos = jnp.arange(
                max(w_cols - 1, 0), dtype=jnp.int32
            )[None, :]
            live = jpos < (verify_len[:, None] - 1)
            lead = jnp.cumprod(
                ((drafts == nxt[:, :-1]) & live).astype(jnp.int32),
                axis=1,
            )
            accept = jnp.sum(lead, axis=1, dtype=jnp.int32)
            packed = _pack_sync(
                nxt, _stop_hits(nxt, stop_tokens), accept
            )
            return packed, new_pages

        return mixed_step

    # ------------------------------------------------------------------
    # Request lifecycle
    # ------------------------------------------------------------------
    def submit(
        self,
        prompt_ids: np.ndarray | list[int],
        max_new_tokens: int,
        *,
        request_id: int | None = None,
        seed: int = 0,
        callback: Callable[[Request, int, str | None], None] | None = None,
        on_event: Callable[[Request, str], None] | None = None,
        deadline_s: float | None = None,
        arrival_time: float | None = None,
        trace_id: str | None = None,
        speculative: bool = False,
        tenant: str = "default",
        _recovered: bool = False,
    ) -> Request:
        prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        # peak cache need over the request's lifetime (incl. re-prefills)
        worst = worst_case_slots(prompt.size, max_new_tokens,
                                 self.prefill_chunk)
        if worst > self.max_seq_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) "
                f"needs up to {worst} cache slots > max_seq_len "
                f"{self.max_seq_len}"
            )
        # worst-case ADMISSION need: a re-prefill after preemption can
        # carry up to max_new_tokens-1 already-generated tokens, and the
        # scheduler only admits with need + decode_reserve blocks free —
        # a request whose worst admission can never be satisfied would
        # sit at the queue head forever (strict FIFO), starving
        # everything behind it, so reject at submit
        need_max = self.pool.blocks_for(
            _ceil_to(prompt.size + max_new_tokens - 1, self.prefill_chunk)
        )
        headroom = need_max + self.scheduler.decode_reserve
        if headroom > self.pool.capacity:
            raise ValueError(
                f"request needs up to {need_max} blocks + "
                f"{self.scheduler.decode_reserve} reserve to admit "
                f"> pool capacity {self.pool.capacity}; grow num_blocks or "
                f"shrink the request"
            )
        if request_id is None:
            request_id = self._next_id
        self._next_id = max(self._next_id, request_id) + 1
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        # per-tenant in-flight cap: counted over the LIVE ledger (queued
        # + running), stateless so recovery replays and drains can never
        # leak a count.  Recovered work is exempt like the queue cap —
        # the cap must not orphan a request the engine already accepted.
        if self.tenants is not None and not _recovered:
            cap = self.tenants.max_inflight
            if cap is not None:
                n_live = sum(
                    1 for r in self._requests.values()
                    if r.tenant == tenant
                )
                if n_live >= cap:
                    self.tenants.on_throttle(tenant)
                    self.metrics.on_reject()
                    if self.tracer is not None:
                        self.tracer.instant(
                            "tenant-throttled", cat="request",
                            args={"tenant": tenant, "inflight": n_live,
                                  "cap": cap},
                        )
                    raise TenantThrottled(tenant, n_live, cap)
        req = Request(
            req_id=request_id,
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            seed=seed,
            callback=callback,
            on_event=on_event,
            arrival_time=arrival_time if arrival_time is not None else 0.0,
            # the opt-in survives even on a non-spec engine (inert
            # there) so a journal replay onto a spec-enabled rebuild
            # resumes drafting
            speculative=bool(speculative),
            tenant=tenant,
        )
        req.submit_time = self.clock()
        if deadline_s is not None:
            req.deadline = req.submit_time + deadline_s
        # distributed trace identity: accept the caller's W3C trace id
        # (the HTTP layer parses/generates `traceparent`), else mint one
        # when some instrument will record it — with everything off this
        # stays a pair of is-None checks, no id is ever generated
        if trace_id is None and (
            self.tracer is not None or self.request_log is not None
        ):
            trace_id = gen_trace_id()
        if trace_id is not None:
            req.extra["trace"] = trace_id
        # the weight version serving this request, stamped at admission:
        # journal admission records and request-log lines carry it, so a
        # stream that survives a mid-roll drain still reports the ONE
        # version it was admitted under (recover() overrides the stamp
        # with the original admission's version)
        req.extra["weights_version"] = self.weights_version
        try:
            # supervisor replays of already-admitted work are exempt from
            # the queue cap, like preemption requeues — the cap must not
            # orphan a request the engine had already accepted
            self.scheduler.add(req, exempt_cap=_recovered)
        except QueueFull:
            # backpressure, not a client error: count the reject so the
            # 429s the HTTP layer returns are visible in /metrics
            self.metrics.on_reject()
            raise
        if _recovered:
            # counted at its ORIGINAL submit (the metrics object survives
            # the restart); record the recovery itself instead
            self.metrics.on_recover()
        else:
            self.metrics.on_submit(req)
        if self.tracer is not None:
            self.tracer.request_phase(req.req_id, "queued", args=self._targs(
                req, prompt_len=req.prompt_len,
                max_new_tokens=max_new_tokens,
            ))
            if _recovered:
                # the LINK instant: a replay/drain continues the same
                # trace id — merged timelines connect through it
                self.tracer.request_instant(
                    req.req_id, "recovery-replay", args=self._targs(req))
        self._requests[req.req_id] = req
        if self.journal is not None and not _recovered:
            # recovered resubmits are re-journaled from recover() AFTER
            # their teacher-forced tokens are seeded, so a second crash
            # replays from the latest full state
            self.journal.admit(req, now=self.clock())
        if self.tokenizer is not None:
            self._detok[req.req_id] = IncrementalDetok(self.tokenizer)
        return req

    def recover(
        self,
        prompt_ids: np.ndarray | list[int],
        max_new_tokens: int,
        *,
        request_id: int,
        seed: int = 0,
        generated: list[int] | tuple[int, ...] = (),
        callback: Callable[[Request, int, str | None], None] | None = None,
        on_event: Callable[[Request, str], None] | None = None,
        deadline_s: float | None = None,
        deadline_at: float | None = None,
        trace_id: str | None = None,
        lineage: dict | None = None,
        speculative: bool = False,
        tenant: str = "default",
        weights_version: int | None = None,
    ) -> Request:
        """Resubmit a request that was in flight when a previous engine
        instance died, with its already-delivered tokens teacher-forced.

        ``trace_id`` continues the request's ORIGINAL W3C trace (a
        replay is a link in the same trace, never a fresh one);
        ``lineage`` carries the survival counters the canonical request
        log reports (``replays`` — supervised-restart/journal
        recoveries including this one, ``drains`` — adoptions by a live
        peer after a replica died).

        This is the evict-requeue discipline applied across an engine
        rebuild: ``generated`` pre-seeds the request, so its first
        prefill runs over prompt+generated (``effective_prompt``) and the
        decode RNG keys derive from (seed, content position) — the
        continuation is token-identical to an uninterrupted run, and the
        pre-seeded tokens are NOT re-emitted through the callback.

        Deadlines resume the REMAINING budget: ``deadline_at`` is the
        original absolute deadline on the engine clock (clone_fresh
        shares the clock, so it stays comparable across rebuilds) — a
        request promised N seconds at submit is not silently granted a
        fresh window by every crash (a crash loop would otherwise make
        its deadline unenforceable).  A deadline that expired while the
        engine was down is swept (aborted) on the first tick, exactly as
        if the engine had lived.  ``deadline_s`` (a fresh window from
        now) remains for callers that genuinely want a restart.  The
        caller filters requests that were already terminal (``generated``
        at budget, or ending in a stop token) — those need only their
        lost finish event, not a resubmit.
        """
        if deadline_s is not None and deadline_at is not None:
            raise ValueError("pass deadline_s or deadline_at, not both")
        if len(generated) >= max_new_tokens:
            raise ValueError(
                f"request {request_id} already generated "
                f"{len(generated)}/{max_new_tokens} tokens; deliver its "
                "finish event instead of recovering it"
            )
        req = self.submit(
            prompt_ids, max_new_tokens, request_id=request_id, seed=seed,
            callback=callback, on_event=on_event, deadline_s=deadline_s,
            trace_id=trace_id, speculative=speculative, tenant=tenant,
            _recovered=True,
        )
        if deadline_at is not None:
            req.deadline = deadline_at
        req.generated = [int(t) for t in generated]
        if weights_version is not None:
            # the ORIGINAL admission's weight version, not this engine's:
            # a drain onto an already-rolled peer must keep reporting
            # the version the request was admitted (and served) under
            req.extra["weights_version"] = int(weights_version)
        if lineage:
            # before the journal re-admission below, so a SECOND crash
            # replays the lineage along with the token state
            req.extra.update({
                k: int(v) for k, v in lineage.items()
                if k in ("replays", "drains")
            })
        if self.journal is not None:
            self.journal.admit(req, now=self.clock())
        detok = self._detok.get(req.req_id)
        if detok is not None:
            # advance the detokenizer over the replayed tokens so the
            # next delta continues the client's text exactly; the deltas
            # themselves were already delivered pre-crash
            for tok in req.generated:
                detok.push(tok)
        return req

    def finish_recovered(
        self,
        prompt_ids: np.ndarray | list[int],
        max_new_tokens: int,
        *,
        request_id: int,
        generated: list[int] | tuple[int, ...],
        reason: str,
        trace_id: str | None = None,
        lineage: dict | None = None,
        tenant: str = "default",
        weights_version: int | None = None,
    ) -> str | None:
        """Terminal bookkeeping for a request that was recovered ALREADY
        complete (every token generated pre-crash; only its finish event
        was lost) or that recovery had to drop: counts the finish/abort
        in metrics — which survive the rebuild, so submitted must keep
        balancing finished+aborted+live — without re-running anything.
        Returns the detokenizer's held-back tail text (a fresh detok
        replayed over the tokens yields the same delta sequence the
        original emitted, so what its flush holds is exactly what the
        lost finish event would have carried) for the caller to deliver.
        The companion to ``recover`` for the supervisor's replay path."""
        req = Request(
            req_id=request_id,
            prompt=np.asarray(prompt_ids, dtype=np.int32).reshape(-1),
            max_new_tokens=max_new_tokens,
        )
        req.generated = [int(t) for t in generated]
        req.finish_reason = reason
        req.tenant = tenant
        if trace_id is not None:
            req.extra["trace"] = trace_id
        req.extra["weights_version"] = int(
            weights_version if weights_version is not None
            else self.weights_version
        )
        if lineage:
            req.extra.update({
                k: int(v) for k, v in lineage.items()
                if k in ("replays", "drains")
            })
        if self.journal is not None:
            self.journal.terminal(request_id, reason)
        if reason == "aborted":
            self.metrics.on_abort(req)
        else:
            self.metrics.on_finish(req)
        if self.tenants is not None:
            # the tenant's bill survives the crash too: the recovered
            # terminal charges whatever cost fields the replay carried
            # (usually zero — the device time died with the old process)
            self.tenants.on_terminal(req)
        # the canonical log still gets its line (phases empty — the
        # timestamps died with the old process; the SLO verdict reports
        # it untimed rather than guessing)
        self._log_request(req, reason)
        if self.tracer is not None:
            # close whatever span the pre-crash engine left open so the
            # span-vs-metrics parity (finish instants == terminal
            # counters) holds across recoveries too
            self.tracer.request_end(request_id, reason, args=self._targs(
                req, recovered_terminal=True))
        if self.tokenizer is None or not req.generated:
            return None
        detok = IncrementalDetok(self.tokenizer)
        for tok in req.generated:
            detok.push(tok)
        return detok.flush() or None

    def clone_fresh(self, *, params: Params | None = None,
                    weights_version: int | None = None) -> "ServeEngine":
        """A fresh engine with the same params/config/geometry and a
        zeroed block pool — what a supervisor restart rebuilds after a
        crash.  The compiled step programs are SHARED with this engine
        (identical geometry → identical jaxprs), so a restart never
        re-traces or recompiles (pinned by tools/compile_counter.py), and
        the metrics object carries over so operator counters survive.

        ``params``/``weights_version`` override the weights — the
        rolling-upgrade rebuild (serve/replica.py): the jitted steps
        take params as a call ARGUMENT, so a swap to same-shaped
        weights reuses every warm compile, and a swap that changes the
        param avals re-traces once per shared callable — once per
        FLEET, because rolled peers adopt the first rebuilt replica's
        callables via ``share_compiled_steps``."""
        eng = ServeEngine(
            params if params is not None else self.params, self.config,
            sampler=self.sampler,
            stop_tokens=self.stop_tokens,
            max_slots=self.scheduler.max_slots,
            num_blocks=self.pool.num_blocks,
            block_size=self.block_size,
            max_seq_len=self.max_seq_len,
            prefill_chunk=self.prefill_chunk,
            cache_dtype=self.cache_dtype,
            decode_attn_impl=self.decode_attn_impl,
            enable_prefix_cache=self.pool.prefix_cache is not None,
            max_queue=self.scheduler.max_queue,
            tokenizer=self.tokenizer,
            clock=self.clock,
            fault_injector=self.faults,
            tracer=self.tracer,
            mixed_step=self.mixed_step_mode,
            sample_epilogue=self.sample_epilogue_mode,
            tick_token_budget=self.tick_token_budget or None,
            mesh_plan=self.mesh_plan,
            mesh_devices=self._mesh_devices,
            journal=self.journal,
            request_log=self.request_log,
            sentinel=self.sentinel,
            actions=self.actions,
            telemetry=self.telemetry,
            weights_version=(
                weights_version if weights_version is not None
                else self.weights_version
            ),
            host_tier=self.host_tier,
            # the ledger rides the rebuild like metrics: a restart is the
            # same replica, so tenant bills must keep accumulating
            tenants=self.tenants,
            spec_k=self.spec_k,
            spec_ngram=self.spec_ngram,
            spec_min_accept=self.spec_min_accept,
            spec_window=self.spec_window,
        )
        eng.metrics = self.metrics
        eng.decode_degraded = self.decode_degraded
        eng._next_id = self._next_id
        if self._restore_block is not None and eng._restore_block is not None:
            # the tier rides the rebuild (host entries survive the
            # crash — the zeroed pool restores instead of re-prefilling)
            # and identical geometry means identical tier jaxprs
            eng._restore_block = self._restore_block
            eng._slice_block = self._slice_block
        if self.mixed:
            if (
                eng.mixed
                and eng.ragged_attn_impl == self.ragged_attn_impl
                and eng.epilogue_impl == self.epilogue_impl
            ):
                # same resolution → identical jaxpr; a runtime-degraded
                # process (disable_kernel) rebuilds on the XLA fallback
                # and compiles it once there, not per restart
                eng._mixed_step = self._mixed_step
            return eng
        names = ["_prefill_step", "_sample_first", "_scatter_prefill",
                 "_gather_prefix"]
        if (
            eng.decode_attn_impl == self.decode_attn_impl
            and eng.epilogue_impl == self.epilogue_impl
        ):
            # the gate can downgrade the clone (e.g. the paged kernel was
            # runtime-disabled between builds) — share the decode step
            # only when both engines resolved to the same impls (the
            # attention AND the sampling epilogue live in its jaxpr)
            names.append("_decode_step")
        for name in names:
            setattr(eng, name, getattr(self, name))
        return eng

    def share_compiled_steps(self, src: "ServeEngine") -> None:
        """Adopt ``src``'s jitted step callables (geometry-identical
        engines only — the fleet's homogeneity check guarantees it).
        A rolling upgrade calls this on every rolled replica after the
        first, so new-weight avals are traced/compiled once per FLEET,
        not once per replica; an elastic ``add_replica`` clone uses it
        the same way.

        Placement-guarded: the step closures pin output shardings to
        the BUILDING engine's mesh (``_constrain_pages``), so engines
        on different device slices (DP placement meshes — one chip per
        replica) must keep their own callables; adopting a peer's
        would pin this replica's pages to the peer's devices and fault
        at dispatch.  Those fleets compile once per device slice —
        still once per set of identical placements, never per roll."""
        if not self._same_placement(src):
            return
        if self._restore_block is not None \
                and src._restore_block is not None:
            self._restore_block = src._restore_block
            self._slice_block = src._slice_block
        if self.mixed and src.mixed \
                and self.ragged_attn_impl == src.ragged_attn_impl \
                and self.epilogue_impl == src.epilogue_impl:
            self._mixed_step = src._mixed_step
            return
        if not self.mixed and not src.mixed:
            for name in ("_prefill_step", "_sample_first",
                         "_scatter_prefill", "_gather_prefix"):
                setattr(self, name, getattr(src, name))
            if self.decode_attn_impl == src.decode_attn_impl \
                    and self.epilogue_impl == src.epilogue_impl:
                self._decode_step = src._decode_step

    def _same_placement(self, src: "ServeEngine") -> bool:
        """Do both engines place params/pool/operands on the same
        device set?  (Sharing compiled steps across placements is a
        correctness error, not an optimization miss.)"""
        if self.mesh is None and src.mesh is None:
            return True
        if self.mesh is None or src.mesh is None:
            return False
        return list(self.mesh.devices.flat) == list(src.mesh.devices.flat)

    def _targs(self, req: Request, **kw: Any) -> dict:
        """Span args with the request's W3C trace id merged in (when it
        has one) — what lets ``summarize_trace --merge`` stitch the
        per-replica fragments of one request back together.  Callers
        hold the tracer is-None guard; with tracing off this never
        runs."""
        tid = req.extra.get("trace")
        if tid is not None:
            kw["trace"] = tid
        if req.tenant != "default":
            kw["tenant"] = req.tenant
        return kw

    def _log_request(self, req: Request, reason: str) -> None:
        """Emit the canonical wide-event line for a terminal request
        (enqueue only — the request-log writer thread does the IO)."""
        if self.request_log is None:
            return
        tracker = getattr(self.metrics, "slo", None)
        self.request_log.emit(request_record(
            req, reason=reason,
            policy=tracker.policy if tracker is not None else None,
            clock=self.clock,
        ))

    def _sentinel_observe(
        self, phases: tuple[tuple[str, float, float], ...],
    ) -> list[dict]:
        """Feed one tick's phase slices to the anomaly sentinel; an
        outlier stamps a trace instant naming the guilty phase and
        bumps the per-phase anomaly counter.  Returns the outliers —
        the tick's ``_actions_tick`` hands them to the ActionPolicy."""
        sent = self.sentinel
        if sent is None:
            return []
        outliers = sent.observe(phases)
        if not outliers:
            return []
        for o in outliers:
            self.metrics.on_anomaly(str(o["phase"]))
        guilty = outliers[0]
        if self.tracer is not None:
            self.tracer.instant("anomaly", cat="sentinel", args={
                "phase": guilty["phase"],
                "dur_us": round(float(guilty["dur_us"]), 1),
                "baseline_us": round(float(guilty["baseline_us"]), 1),
                "tick": sent.ticks,
            })
        return outliers

    def _tick_budget(self) -> int:
        """This tick's token budget: the configured budget, capped by
        the ActionPolicy's shed-prefill verdict (decode rows are never
        shed — the floor is max_slots)."""
        if self.actions is None:
            return self.tick_token_budget
        return self.actions.plan_budget(
            self.tick_token_budget, self.scheduler.max_slots
        )

    def _actions_tick(self, outliers: list[dict]) -> None:
        """Feed one tick's sentinel verdicts + SLO burn to the
        ActionPolicy; count and trace every action flip (the
        ``llm_serve_lifecycle_actions_total{action=}`` series and the
        ``lifecycle-action`` trace instants the auto-action e2e reads).
        ``self.actions`` is re-read per hook like tracer/metrics — the
        supervisor mutes a zombie engine by clearing it."""
        if self.actions is None:
            return
        for action in self.actions.on_tick(
            outliers, getattr(self.metrics, "slo", None)
        ):
            self.metrics.on_lifecycle_action(action)
            if self.tracer is not None and self.actions is not None:
                self.tracer.instant(
                    "lifecycle-action", cat="lifecycle",
                    args={"action": action, **self.actions.state_args()},
                )

    def _emit(self, req: Request, token: int) -> None:
        req.generated.append(int(token))
        if req.first_token_time is None:
            req.first_token_time = self.clock()
        self.metrics.on_token(req)
        if req.callback is not None:
            delta = None
            detok = self._detok.get(req.req_id)
            if detok is not None:
                delta = detok.push(token)
            req.callback(req, int(token), delta)

    def _emit_event(self, req: Request, event: str) -> None:
        if req.on_event is not None:
            req.on_event(req, event)

    def _flush_detok(self, req: Request) -> None:
        """Pop the request's detokenizer and park any held-back tail text
        (mid-UTF-8 merge) in ``req.extra['final_text_delta']`` — terminal
        events carry it so streams don't lose their last characters."""
        detok = self._detok.pop(req.req_id, None)
        if detok is not None:
            tail = detok.flush()
            if tail:
                req.extra["final_text_delta"] = tail

    def _maybe_finish(self, req: Request) -> bool:
        if req.state is not RequestState.RUNNING:
            # aborted out from under us (e.g. from a token callback) —
            # already unwound, nothing left to finish
            return True
        hit_stop = bool(
            self.stop_tokens and req.generated
            and req.generated[-1] in self.stop_tokens
        )
        if req.done or hit_stop:
            # a stop token on the last budgeted step still reports
            # "stop": the model chose to end, the budget merely agreed
            req.finish_reason = "stop" if hit_stop else "length"
            req.finish_time = self.clock()
            self.scheduler.finish(req)
            self._requests.pop(req.req_id, None)
            self._draft_states.pop(req.req_id, None)
            self._flush_detok(req)
            self.metrics.on_finish(req)
            if self.tenants is not None:
                self.tenants.on_terminal(req)
            if self.journal is not None:
                # flush the final delivery delta (the finishing tick's
                # token would otherwise be missed — the request leaves
                # the live set before the tick's watermark), then mark
                # terminal so the replay set stays exact
                self.journal.end_tick((req,))
                self.journal.terminal(req.req_id, req.finish_reason)
            self._log_request(req, req.finish_reason)
            if self.tracer is not None:
                self.tracer.request_end(req.req_id, req.finish_reason,
                                        args=self._targs(req))
            self._emit_event(req, req.finish_reason)
            return True
        return False

    def abort(self, request_id: int) -> bool:
        """Cancel a live request — queued, prefilled, or mid-decode.

        Its decode slot frees, its block references drop (refcounted
        decref: prefix blocks shared with other requests survive, and
        blocks this request registered in the prefix cache stay
        registered under the cache's own reference), and the terminal
        ``"aborted"`` event fires.  Returns False when the id is unknown
        or already terminal — an abort racing a natural finish is a
        no-op, not an error (the HTTP layer aborts on every client
        disconnect, including disconnects after [DONE]).

        NOT thread-safe, like every other engine entry point: callers
        off the tick thread go through the HTTP runner's command queue.
        """
        req = self._requests.pop(request_id, None)
        if req is None:
            return False
        self._draft_states.pop(request_id, None)
        self.scheduler.abort(req)
        req.finish_reason = "aborted"
        req.finish_time = self.clock()
        self._flush_detok(req)
        self.metrics.on_abort(req)
        if self.tenants is not None:
            # aborted work is still billed work: whatever device cost the
            # request accrued before cancellation lands on its tenant
            self.tenants.on_terminal(req)
        if self.journal is not None:
            self.journal.end_tick((req,))
            self.journal.terminal(req.req_id, "aborted")
        self._log_request(req, "aborted")
        if self.tracer is not None:
            self.tracer.request_end(req.req_id, "aborted",
                                    args=self._targs(req))
        self._emit_event(req, "aborted")
        return True

    def _sweep_deadlines(self) -> None:
        """Abort every live request past its deadline (checked once per
        tick — a deadline can overshoot by at most one tick)."""
        now = self.clock()
        expired = [
            r.req_id
            for r in self._requests.values()
            if r.deadline is not None and now >= r.deadline
        ]
        for rid in expired:
            self.abort(rid)

    def _fair_prefill_order(self, running: list[Request]) -> list[Request]:
        """Fair-share prefill ordering (``--tenant-fairness``): rank the
        running list by each tenant's accumulated cost share — terminal
        charges plus live work-so-far, byte-based when the telemetry
        roofline is attached, token-based otherwise — so the tick's
        prefill budget fills smallest-share-first.  The sort is STABLE
        over the scheduler's admission-ordered running list, so within a
        tenant requests stay oldest-first, and with one tenant (or the
        hook off) every key ties and the order is byte-identical to
        fairness-off.  Decode rows are untouched: ``plan_tick`` only
        consults this for the prefill fill, so running decodes are never
        starved by a cheaper tenant's arrivals."""
        if self.tenants is None:
            return running
        share = self.tenants.cost_shares(
            running, use_bytes=self.telemetry is not None,
        )
        return sorted(running, key=lambda r: share.get(r.tenant, 0.0))

    # ------------------------------------------------------------------
    def _prefill_request(self, req: Request) -> None:
        """Chunked ragged prefill into a temp contiguous cache, scatter
        into the request's blocks, sample + emit the first token.

        Prefix-cache hits (``req.n_shared_blocks`` leading blocks claimed
        at admission) SKIP their prefill chunks entirely: the shared K/V
        is copied from the pool into the temp cache (bit-identical to
        what those chunks would have computed — a slot's K/V depends only
        on its token and position) and the remaining chunks run from that
        offset.  Only the fresh blocks are scattered back; shared blocks
        are never written."""
        if self.faults is not None and self.faults.trip("prefill") is not None:
            raise FaultInjected("prefill")
        # host-tier hits land FIRST: the claimed blocks must hold real
        # K/V before gather_prefix copies them into the temp cache (a
        # miss un-covers the tail, which then prefills as fresh blocks)
        self._enqueue_tier_restores(req)
        self._apply_tier_restores([req])
        t_tel = self.clock() if self.telemetry is not None else 0.0
        content = req.effective_prompt()
        w = self._prefill_width(req)
        req.pad = w - content.size
        n_shared = req.n_shared_blocks
        shared_slots = n_shared * self.block_size
        # FIXED temp capacity: a per-bucket cap would retrace the whole
        # model prefill once per prompt-length bucket (a multi-second
        # mid-traffic stall on TPU); only the cheap scatter/gather is
        # allowed to specialize per block count
        cap = self.max_seq_len
        ids = np.zeros((1, w), dtype=np.int32)
        mask = np.zeros((1, w), dtype=bool)
        ids[0, req.pad:] = content
        mask[0, req.pad:] = True
        pads = self._put(np.asarray([req.pad], dtype=np.int32))
        ids_d, mask_d = self._put(ids), self._put(mask)

        cache = self._make_temp_cache()
        if n_shared:
            self.n_dispatches += 1
            cache = self._gather_prefix(
                cache, self.pool.pages,
                self._put(np.asarray(req.block_ids[:n_shared], np.int32)),
                self._put(np.int32(req.pad)),
            )
            cache = self._repin_temp_cache(cache)
        t_pf = self.clock() if self.host_tier is not None else 0.0
        last = None
        for off in range(shared_slots, w, self.prefill_chunk):
            end = off + self.prefill_chunk
            # self.tracer re-read per hook, like step(): the supervisor
            # mutes a zombie engine by clearing the attribute
            t_chunk = (self.tracer.now_us()
                       if self.tracer is not None else -1.0)
            self.n_dispatches += 1
            with (jax.profiler.TraceAnnotation("serve.prefill_chunk")
                  if self.tracer is not None else _NULL_CTX):
                last, cache = self._prefill_step(
                    self.params, ids_d[:, off:end], cache,
                    mask_d[:, off:end], pads,
                )
                cache = self._repin_temp_cache(cache)
            if self.tracer is not None and t_chunk >= 0.0:
                # dispatch time, not device time — async dispatch
                # returns before the chunk computes; the device side
                # lives in the --jax-profile capture under the
                # TraceAnnotation scope above
                self.tracer.complete(
                    "prefill_chunk", t_chunk, cat="prefill", args={
                        "rid": req.req_id, "offset": off,
                        "width": end - off,
                    })
        self.n_dispatches += 1
        self.pool.pages = self._scatter_prefill(
            self.pool.pages, cache,
            self._put(np.asarray(req.block_ids[n_shared:], dtype=np.int32)),
            self._put(np.int32(n_shared)),
        )
        pc = self.pool.prefix_cache
        keys = req.extra.pop("prefix_keys", None)
        req.extra.pop("prefix_keys_width", None)
        if pc is not None and keys:
            # register this prefill's fully-filled prompt blocks so the
            # NEXT matching prompt hits (claimed blocks are already
            # registered — register only LRU-touches them)
            pc.register(keys, req.block_ids[: len(keys)])
            self.metrics.on_prefix(requested=len(keys), hits=n_shared)
        self.n_dispatches += 1
        tok = self._sample_first(
            last,
            self._put(np.uint32(req.seed)),
            self._put(np.int32(content.size - 1)),
        )
        # lint: disable=R2 -- the phase-split design emits the first
        # token inside the prefill phase (its wall time is accounted to
        # prefill_s); the unified tick retired this extra sync
        tok_host = int(np.asarray(tok)[0])
        if self.host_tier is not None and w > shared_slots:
            # measured prefill rate over the fresh chunks (the sync
            # above closed the window) — the breakeven's recompute side
            dt = self.clock() - t_pf
            if dt > 0:
                self.host_tier.note_prefill_rate((w - shared_slots) / dt)
        if self.telemetry is not None:
            # the chunk dispatches are per-request by construction: the
            # whole bill (weights streamed per chunk, fresh K/V written,
            # measured wall — the sync above closed the window) lands on
            # this request, and the totals-only record keeps the metrics
            # ledger conserving.  MUST run before _emit: a token
            # callback may abort(), which zeroes the shared-block state
            # the bill reads and writes the request-log line
            self.metrics.on_telemetry(self.telemetry.prefill_cost(
                self, req, self.clock() - t_tel
            ))
        self._emit(req, tok_host)

    # ------------------------------------------------------------------
    def step(self) -> bool:
        """One scheduler tick; returns True while work remains.  Unified
        engines (``mixed_step``) run the single-dispatch mixed tick,
        phase-split engines the admission→prefill→grow→decode pipeline
        below."""
        if self.mixed:
            return self._step_mixed()
        return self._step_split()

    def _step_split(self) -> bool:
        """One phase-split tick: deadline sweep, admissions (+prefill),
        then one packed decode dispatch.  Returns True while work
        remains.

        With a tracer attached each tick emits one ``tick`` span and its
        phase slices — ``admission`` (sweep + admit), ``prefill``,
        ``grow`` (block growth / eviction), ``decode_dispatch``,
        ``host_sync`` (the device→host token fetch) and ``deliver``
        (callbacks + metrics) — measured at consecutive timestamps so
        the phases sum to the tick span.  Tracing off: every hook is a
        single is-None branch (no allocation, pinned by lint).

        ``self.tracer`` is re-read at EVERY hook (never cached in a
        local for the whole tick) for the same reason engine code reads
        ``self.metrics`` per call: a supervisor restart mutes the dead
        engine by clearing the attribute, and a watchdog-superseded but
        still-running zombie tick must stop writing into the shared
        recorder as soon as that mute lands — a tick-lifetime snapshot
        would keep emitting stale spans into the timeline the rebuilt
        engine now owns.  Timestamps default to -1 so a tick that
        STARTED untraced never emits a garbage span if a tracer is
        attached mid-tick."""
        t0 = self.tracer.now_us() if self.tracer is not None else -1.0
        fetches0 = self.n_host_fetches
        if self.host_tier is not None:
            self._tier_spill_bytes = 0
            self._tier_restore_bytes = 0
            self._tier_restore_us = 0.0
        self._sweep_deadlines()
        admitted = self.scheduler.admit()
        t1 = self.tracer.now_us() if self.tracer is not None else -1.0
        for req in admitted:
            t_req = self.clock()
            if req.admit_time is None:
                req.admit_time = t_req
            if self.tracer is not None:
                self.tracer.request_phase(
                    req.req_id, "prefill", args=self._targs(
                        req, shared_blocks=req.n_shared_blocks,
                        preemptions=req.n_preemptions,
                    ))
            self._prefill_request(req)
            req.prefill_s += self.clock() - t_req
            if not self._maybe_finish(req) and self.tracer is not None:
                self.tracer.request_phase(req.req_id, "decode")
        t2 = self.tracer.now_us() if self.tracer is not None else -1.0

        # preempted requests are already requeued; slots rebuilt below
        for req in self.scheduler.ensure_decode_blocks():
            if self.tracer is not None:
                self.tracer.request_instant(req.req_id, "evicted-requeued")
                self.tracer.request_phase(req.req_id, "queued")
            self._emit_event(req, "evicted-requeued")
        t3 = self.tracer.now_us() if self.tracer is not None else -1.0

        running = [
            r for r in self.scheduler.running if r.generated
        ]
        t4 = t5 = t3
        tel = None
        cost = None
        tdev0 = 0.0
        if running:
            b = self.scheduler.max_slots
            mb = self.max_blocks_per_seq
            tables = np.zeros((b, mb), dtype=np.int32)
            lengths = np.zeros((b,), dtype=np.int32)
            pads = np.zeros((b,), dtype=np.int32)
            toks = np.zeros((b,), dtype=np.int32)
            seeds = np.zeros((b,), dtype=np.uint32)
            for r in running:
                tables[r.slot, : len(r.block_ids)] = r.block_ids
                # slots written so far: pads + content minus the latest
                # generated token (this tick's input, written by the step)
                lengths[r.slot] = r.cache_len - 1
                pads[r.slot] = r.pad
                toks[r.slot] = r.generated[-1]
                seeds[r.slot] = np.uint32(r.seed)
            if self.telemetry is not None:
                # analytic byte bill for this dispatch; the measured
                # wall closes over it after the host sync below
                cost = self.telemetry.split_tick_cost(self, running)
                tdev0 = self.clock()
            with (jax.profiler.TraceAnnotation("serve.decode_dispatch")
                  if self.tracer is not None else _NULL_CTX):
                out, self.pool.pages = self._dispatch_decode(
                    self._put(tables), self._put(lengths),
                    self._put(pads), self._put(toks),
                    self._put(seeds),
                )
            t4 = self.tracer.now_us() if self.tracer is not None else -1.0
            if self.faults is not None:
                # injected host_sync regression: a REAL stall inside
                # the host_sync phase window, attributed by the
                # sentinel to the right phase (ActionPolicy food)
                hang = self.faults.trip("host_sync")
                if hang is not None:
                    time.sleep(hang)
            # THE tick's one device→host transfer: the decode step
            # returns the packed [B, 4] sync rows (token, stop-hit,
            # watermark, accept — the mixed contract's W=1 case); the
            # deliver loop below reads the token column and
            # _maybe_finish re-derives finish host-side (see _pack_sync
            # on the redundant columns)
            out_host = np.asarray(out)
            self.n_host_fetches += 1
            t5 = self.tracer.now_us() if self.tracer is not None else -1.0
            if cost is not None and self.telemetry is not None:
                # attribution lands BEFORE the deliver loop so a
                # finishing request's canonical log line carries its
                # final tick's cost
                tel = self.telemetry.finish(cost, self.clock() - tdev0)
                self.telemetry.attribute(cost, tel["device_time_s"])
                self.metrics.on_telemetry(tel)
            for r in running:
                self._emit(r, int(out_host[r.slot, 0]))
                self._maybe_finish(r)

        if self.journal is not None:
            # ONE delivery-watermark record for the whole tick (rows
            # for every live request whose count advanced) — batched
            # per tick, never per token
            self.journal.end_tick(self._requests.values())
        if self.host_tier is not None and (
            self._tier_spill_bytes or self._tier_restore_bytes
        ):
            self.metrics.on_tier_gauge(
                resident_bytes=self.host_tier.resident_bytes,
                breakeven=self.host_tier.breakeven_ratio(self.block_size),
            )
        self.metrics.on_tick(
            queue_depth=self.scheduler.queue_depth,
            occupancy=self.pool.occupancy,
            active_slots=len(running) if running else 0,
            preemptions_total=self.scheduler.n_preemptions,
            kv_bytes=self._kv_bytes_tick(running) if running else 0,
        )
        outliers: list[dict] = []
        if self.tracer is not None and t0 >= 0.0:
            t6 = self.tracer.now_us()
            targs: dict[str, Any] = {
                "active_slots": len(running) if running else 0,
                "queue_depth": self.scheduler.queue_depth,
                "admitted": len(admitted),
                # tick-tail observables (see _step_mixed): the one-fetch
                # contract covers the DECODE fetch; the phase-split
                # prefill's in-phase first-token sync is accounted to
                # prefill and retired by the unified tick
                "host_sync_us": round(max(t5 - t4, 0.0), 1),
                "host_fetches": self.n_host_fetches - fetches0,
            }
            if self.host_tier is not None:
                targs["tier_spill_bytes"] = self._tier_spill_bytes
                targs["tier_restore_bytes"] = self._tier_restore_bytes
                targs["tier_restore_us"] = round(self._tier_restore_us, 1)
            if tel is not None:
                targs.update(_roofline_targs(tel))
            self.tracer.tick(t0, (
                ("admission", t0, t1), ("prefill", t1, t2),
                ("grow", t2, t3), ("decode_dispatch", t3, t4),
                ("host_sync", t4, t5), ("deliver", t5, t6),
            ), args=targs)
            if self.sentinel is not None:
                # same literal phase tuple the tracer records (R2
                # recovers its exempt spans from the tick() literal, so
                # the tuple cannot be hoisted into a shared local); the
                # roofline deficit rides along as a pseudo-phase so a
                # persistent utilization regression pages like a
                # host_sync one
                outliers = self._sentinel_observe((
                    ("admission", t0, t1), ("prefill", t1, t2),
                    ("grow", t2, t3), ("decode_dispatch", t3, t4),
                    ("host_sync", t4, t5), ("deliver", t5, t6),
                ) + (
                    (("roofline_deficit", 0.0, tel["deficit_us"]),)
                    if tel is not None else ()
                ))
        self._actions_tick(outliers)
        return self.scheduler.has_work

    # ------------------------------------------------------------------
    # Unified tick (mixed_step)
    # ------------------------------------------------------------------
    def _init_mixed_prefill(self, req: Request) -> None:
        """Admission bookkeeping for the unified tick: fix the request's
        left-pad and prefill target, pre-mark prefix-cache-covered
        content as done (covered chunks consume NO tick budget and are
        attended in place through the block table — no gather_prefix
        copy), and stash the teacher-forced content for the packer."""
        content = req.effective_prompt()
        w = self._prefill_width(req)
        req.pad = w - content.size
        shared_slots = req.n_shared_blocks * self.block_size
        req.prefill_target = int(content.size)
        req.prefill_done = max(shared_slots - req.pad, 0)
        req.prefilled = False
        req.extra["prefill_content"] = content

    def _pack_mixed(
        self,
        decode_rows: list[Request],
        prefill_segs: list[tuple[Request, int]],
    ) -> tuple:
        """Build the mixed step's packed operands from the planner's
        verdict.  Each row's token segment lands at consecutive,
        q-tile-aligned packed positions (dead alignment lanes point at
        the scratch block and are masked); the packed width is the
        smallest bucket covering the aligned total, so the dispatch
        reuses a warm compile whatever the prefill:decode mix."""
        qb = self._q_tile
        b = self.scheduler.max_slots
        mb = self.max_blocks_per_seq
        bs = self.block_size
        w_v = self._spec_w
        # segment = (request, tokens, first cache slot, n_verify):
        # n_verify sample slots cover the segment's LAST n_verify tokens
        # — a plain decode row or completing prefill samples 1 (its last
        # token), a speculating row samples its whole verify slice
        # (input + drafts), a mid-prefill chunk samples 0
        segs: list[tuple[Request, np.ndarray, int, int]] = []
        for r in decode_rows:
            toks = [r.generated[-1]]
            if r.draft_len:
                draft = r.extra["spec_draft"]
                toks.extend(int(t) for t in draft[: r.draft_len])
            segs.append((
                r, np.asarray(toks, np.int32),
                r.cache_len - 1, len(toks),
            ))
        for r, n in prefill_segs:
            content = r.extra["prefill_content"]
            toks = np.asarray(
                content[r.prefill_done:r.prefill_done + n], np.int32
            )
            segs.append((
                r, toks, r.pad + r.prefill_done,
                1 if r.prefill_done + n >= r.prefill_target else 0,
            ))
        aligned = sum(_ceil_to(t.size, qb) for _, t, _, _ in segs)
        t_w = self._pick_bucket(max(aligned, qb))
        nt = t_w // qb
        tokens = np.zeros(t_w, np.int32)
        positions = np.zeros(t_w, np.int32)
        tok_blk = np.zeros(t_w, np.int32)
        tok_off = np.zeros(t_w, np.int32)
        tok_row = np.zeros(t_w, np.int32)
        tok_slot = np.zeros(t_w, np.int32)
        tok_live = np.zeros(t_w, bool)
        tile_row = np.zeros(nt, np.int32)
        tile_qpos0 = np.zeros(nt, np.int32)
        tile_qlen = np.zeros(nt, np.int32)
        tables = np.zeros((b, mb), np.int32)
        pads = np.zeros(b, np.int32)
        last_idx = np.zeros((b, w_v), np.int32)
        sample_pos = np.zeros((b, w_v), np.int32)
        seeds = np.zeros(b, np.uint32)
        verify_len = np.zeros(b, np.int32)
        cur = 0
        for r, toks, start_slot, n_verify in segs:
            n = toks.size
            slot = r.slot
            tables[slot, :len(r.block_ids)] = r.block_ids
            pads[slot] = r.pad
            seeds[slot] = np.uint32(r.seed)
            sl = start_slot + np.arange(n, dtype=np.int32)
            tokens[cur:cur + n] = toks
            positions[cur:cur + n] = sl - r.pad
            blocks = np.asarray(r.block_ids, np.int32)
            tok_blk[cur:cur + n] = blocks[sl // bs]
            tok_off[cur:cur + n] = sl % bs
            tok_row[cur:cur + n] = slot
            tok_slot[cur:cur + n] = sl
            tok_live[cur:cur + n] = True
            n_tiles = -(-n // qb)
            ti0 = cur // qb
            for k in range(n_tiles):
                tile_row[ti0 + k] = slot
                tile_qpos0[ti0 + k] = start_slot + k * qb
                tile_qlen[ti0 + k] = min(qb, n - k * qb)
            if n_verify:
                first = n - n_verify  # verify slots = the last n_verify
                verify_len[slot] = n_verify
                for j in range(n_verify):
                    last_idx[slot, j] = cur + first + j
                    sample_pos[slot, j] = start_slot + first + j - r.pad
            cur += n_tiles * qb
        return tuple(self._put(a) for a in (
            tokens, positions, tok_blk, tok_off, tok_row, tok_slot,
            tok_live, tile_row, tile_qpos0, tile_qlen, tables, pads,
            last_idx, sample_pos, seeds, verify_len,
        ))

    def _finish_mixed_prefill(self, req: Request, tok: int) -> None:
        """A row's prefill reached its target this tick: register its
        prompt blocks with the prefix cache (they are already IN the
        pool — direct writes, nothing to copy) and emit the first
        token sampled by the same dispatch."""
        req.prefilled = True
        req.extra.pop("prefill_content", None)
        pc = self.pool.prefix_cache
        keys = req.extra.pop("prefix_keys", None)
        req.extra.pop("prefix_keys_width", None)
        if pc is not None and keys:
            pc.register(keys, req.block_ids[: len(keys)])
            self.metrics.on_prefix(
                requested=len(keys), hits=req.n_shared_blocks
            )
        self._emit(req, tok)
        if not self._maybe_finish(req) and self.tracer is not None:
            self.tracer.request_phase(req.req_id, "decode")

    def _draft_tick(self) -> int:
        """Propose draft tokens for every speculating decode row —
        HOST-SIDE prompt lookup (serve/spec.DraftState), no device work,
        so the whole draft phase costs dictionary probes and the tick
        stays at ~1 dispatch.  Sets ``Request.draft_len`` (the verify
        width the planner budgets and growth covers) and stashes the
        tokens in ``extra['spec_draft']``; returns the proposed count
        for the trace args.  The cap keeps every verify write inside the
        request's cache ceiling and every possible accept inside its
        token budget."""
        if not self.spec_k:
            return 0
        from llm_np_cp_tpu.serve.spec import DraftState

        total = 0
        for r in self.scheduler.running:
            r.draft_len = 0
            if not (r.speculative and r.prefilled and r.generated):
                continue
            if r.extra.get("spec_off"):
                continue
            rem = r.max_new_tokens - len(r.generated)
            cap = min(self.spec_k, rem - 1,
                      self.max_seq_len - r.cache_len)
            if cap <= 0:
                continue
            st = self._draft_states.get(r.req_id)
            if st is None:
                # lazily built (recovery/preemption re-admissions land
                # here too): the stream is prompt + generated, exactly
                # what an uninterrupted request would have indexed
                st = DraftState(self.spec_ngram)
                st.extend(int(t) for t in r.prompt)
                self._draft_states[r.req_id] = st
            st.extend(r.generated[st.size - r.prompt_len:])
            draft = st.propose(cap)
            if draft:
                r.extra["spec_draft"] = draft
                r.draft_len = len(draft)
                total += len(draft)
        return total

    def _spec_feedback(self, req: Request, drafted: int,
                       accepted: int) -> None:
        """One verify round's accounting + the per-request fallback: a
        stream whose rolling acceptance collapses below
        ``spec_min_accept`` stops drafting (plain decode row from then
        on), so cold streams cost at most one wasted verify window —
        never a standing tax on the tick budget."""
        self.metrics.on_spec(drafted=drafted, accepted=accepted)
        st = req.extra.setdefault("spec_acc", [0, 0])
        st[0] += drafted
        st[1] += accepted
        if st[0] < self.spec_window:
            return
        if st[1] < self.spec_min_accept * st[0]:
            req.extra["spec_off"] = True
            self._draft_states.pop(req.req_id, None)
            if self.tracer is not None:
                self.tracer.request_instant(
                    req.req_id, "spec-fallback", args=self._targs(
                        req, drafted=st[0], accepted=st[1],
                    ))
        else:
            st[0] //= 2
            st[1] //= 2

    def _step_mixed(self) -> bool:
        """One unified tick: deadline sweep + admission, draft proposal,
        block growth, token-budget planning, then ONE mixed ragged
        dispatch covering every planned prefill chunk slice, plain
        decode row, and speculative verify slice.  Phase slices
        (``admission`` / ``draft`` / ``grow`` / ``plan`` /
        ``mixed_dispatch`` / ``host_sync`` / ``deliver``,
        serve/tracing.MIXED_TICK_PHASES) keep the
        consecutive-timestamps sum-to-tick invariant; the tick args
        additionally carry the prefill/decode token split — and, on
        spec-enabled engines, the draft/accept token split — so
        tools/summarize_trace.py can report mixed-step utilization.
        ``self.tracer`` is re-read at every hook for the same
        zombie-mute reason as the split tick."""
        t0 = self.tracer.now_us() if self.tracer is not None else -1.0
        fetches0 = self.n_host_fetches
        if self.host_tier is not None:
            self._tier_spill_bytes = 0
            self._tier_restore_bytes = 0
            self._tier_restore_us = 0.0
        self._sweep_deadlines()
        admitted = self.scheduler.admit()
        for req in admitted:
            if req.admit_time is None:
                req.admit_time = self.clock()
            # stage this admission's host-tier hits FIRST so the writer
            # thread's device_puts overlap the rest of the admission
            # loop; they land (_apply_tier_restores below) before any
            # growth/eviction could free a target block and before the
            # covering dispatch attends them
            self._enqueue_tier_restores(req)
            self._init_mixed_prefill(req)
            if self.tracer is not None:
                self.tracer.request_phase(
                    req.req_id, "prefill", args=self._targs(
                        req, shared_blocks=req.n_shared_blocks,
                        preemptions=req.n_preemptions,
                    ))
        self._apply_tier_restores(admitted)
        t1 = self.tracer.now_us() if self.tracer is not None else -1.0

        self._draft_tick()
        td = self.tracer.now_us() if self.tracer is not None else -1.0

        for req in self.scheduler.ensure_decode_blocks():
            if self.tracer is not None:
                self.tracer.request_instant(req.req_id, "evicted-requeued")
                self.tracer.request_phase(req.req_id, "queued")
            self._emit_event(req, "evicted-requeued")
        t2 = self.tracer.now_us() if self.tracer is not None else -1.0

        decode_rows, prefill_segs = self.scheduler.plan_tick(
            self._tick_budget(), self.prefill_chunk,
            prefill_order=(
                self._fair_prefill_order
                if self.tenants is not None and self.tenants.fairness
                else None
            ),
        )
        t3 = self.tracer.now_us() if self.tracer is not None else -1.0

        t4 = t5 = t3
        n_prefill_tok = sum(n for _, n in prefill_segs)
        n_decode_tok = len(decode_rows)
        # drafts actually packed (post-trim) / accepted by the verifier
        n_spec_tok = sum(r.draft_len for r in decode_rows)
        n_spec_acc = 0
        tel = None
        cost = None
        if decode_rows or prefill_segs:
            if self.telemetry is not None:
                # the analytic byte/FLOP bill MUST run before the
                # accept walk below — verify lanes live in draft_len
                # only until then
                cost = self.telemetry.mixed_tick_cost(
                    self, decode_rows, prefill_segs
                )
            args = self._pack_mixed(decode_rows, prefill_segs)
            td0 = self.clock()
            with (jax.profiler.TraceAnnotation("serve.mixed_dispatch")
                  if self.tracer is not None else _NULL_CTX):
                out, self.pool.pages = self._dispatch_mixed(
                    args, bool(prefill_segs)
                )
            t4 = self.tracer.now_us() if self.tracer is not None else -1.0
            if self.faults is not None:
                # injected host_sync regression (the split tick's twin
                # site): a real stall in the host_sync phase window
                hang = self.faults.trip("host_sync")
                if hang is not None:
                    time.sleep(hang)
            # THE tick's one device→host transfer (lint R2 allows
            # exactly this fetch): the step packed samples + stop mask
            # + watermark + accept length into one int32 array; the
            # accept walk below reads the token + accept columns
            # host-side (see _pack_sync on the other two)
            out_host = np.asarray(out)
            self.n_host_fetches += 1
            nxt_host = out_host[:, : self._spec_w]
            accept_host = out_host[:, self._spec_w + 2]
            t5 = self.tracer.now_us() if self.tracer is not None else -1.0
            if cost is not None and self.telemetry is not None:
                # attribution lands BEFORE the deliver walks so a
                # finishing request's canonical log line carries its
                # final tick's cost
                tel = self.telemetry.finish(cost, self.clock() - td0)
                self.telemetry.attribute(cost, tel["device_time_s"])
                self.metrics.on_telemetry(tel)
            if n_prefill_tok:
                # per-request prefill time: the dispatch+sync wall split
                # by token share (the mixed analogue of Request.prefill_s)
                per_tok = (self.clock() - td0) / (
                    n_prefill_tok + n_decode_tok + n_spec_tok
                )
                for r, n in prefill_segs:
                    r.prefill_s += per_tok * n
                if self.host_tier is not None and per_tok > 0:
                    # the recompute side of the restore-vs-recompute
                    # breakeven: a MEASURED prefill token rate, refined
                    # every dispatching tick
                    self.host_tier.note_prefill_rate(1.0 / per_tok)
            for r, n in prefill_segs:
                r.prefill_done += n
                if r.prefill_done >= r.prefill_target:
                    self._finish_mixed_prefill(r, int(nxt_host[r.slot, 0]))
            for r in decode_rows:
                if not r.draft_len:
                    self._emit(r, int(nxt_host[r.slot, 0]))
                    self._maybe_finish(r)
                    continue
                # the accept walk: the verifier sampled every position
                # of this row's slice with the SAME (seed, content-pos)
                # keys plain decode uses, so sample j is THE token the
                # stream emits at that position — walk while the drafts
                # match, stop at the first correction (which is itself
                # a verified emission), a stop token, or the budget.
                # The match count arrived IN the packed fetch (the step
                # compares its own draft inputs against its samples),
                # so the walk reads host-side slices — no recompare.
                # Rejected drafts' K/V writes sit past the new
                # cache_len and are overwritten before ever attended.
                r.extra.pop("spec_draft")
                n_match = int(accept_host[r.slot])
                w = 1 + r.draft_len
                acc = 0
                for j in range(w):
                    tok = int(nxt_host[r.slot, j])
                    self._emit(r, tok)
                    if j < n_match:
                        # the draft paid off even when this token ENDS
                        # the stream (a drafted stop token) — count it
                        # before the finish check, or accepted/rejected
                        # systematically misreport on short extractive
                        # completions
                        acc += 1
                        if self._maybe_finish(r):
                            break  # stop token / budget (abort included)
                    else:
                        # the correction or the bonus slot — the round
                        # is over either way
                        self._maybe_finish(r)
                        break
                n_spec_acc += acc
                drafted = r.draft_len
                r.draft_len = 0
                self._spec_feedback(r, drafted, acc)

        if self.journal is not None:
            # same per-tick watermark batching as the split tick; a
            # verify round's rows carry every ACCEPTED token this tick
            # delivered — rejected drafts never reach req.generated, so
            # they never reach the journal and replay stays exact
            self.journal.end_tick(self._requests.values())
        if self.host_tier is not None and (
            self._tier_spill_bytes or self._tier_restore_bytes
        ):
            self.metrics.on_tier_gauge(
                resident_bytes=self.host_tier.resident_bytes,
                breakeven=self.host_tier.breakeven_ratio(self.block_size),
            )
        active = n_decode_tok + len(prefill_segs)
        self.metrics.on_tick(
            queue_depth=self.scheduler.queue_depth,
            occupancy=self.pool.occupancy,
            active_slots=active,
            preemptions_total=self.scheduler.n_preemptions,
            kv_bytes=(
                self._kv_bytes_tick_mixed(decode_rows, prefill_segs)
                if active else 0
            ),
            prefill_tokens=n_prefill_tok,
            decode_tokens=n_decode_tok,
        )
        outliers: list[dict] = []
        if self.tracer is not None and t0 >= 0.0:
            t6 = self.tracer.now_us()
            targs = {
                "active_slots": active,
                "queue_depth": self.scheduler.queue_depth,
                "admitted": len(admitted),
                "prefill_tokens": n_prefill_tok,
                "decode_tokens": n_decode_tok,
                # the tick-tail observables: host_sync wall (µs) and the
                # number of device→host transfers this tick — the
                # one-fetch contract says the latter is exactly 1 on
                # dispatching ticks (bench + tests pin it)
                "host_sync_us": round(max(t5 - t4, 0.0), 1),
                "host_fetches": self.n_host_fetches - fetches0,
            }
            if self.spec_k:
                # the draft/verify split for summarize_trace and the
                # sentinel: how many verify lanes rode this tick's
                # dispatch and how many paid off
                targs["spec_draft_tokens"] = n_spec_tok
                targs["spec_accept_tokens"] = n_spec_acc
            if self.host_tier is not None:
                # the tier's per-tick byte flow (what summarize_trace's
                # kv_tier section and a Perfetto tick click read)
                targs["tier_spill_bytes"] = self._tier_spill_bytes
                targs["tier_restore_bytes"] = self._tier_restore_bytes
                targs["tier_restore_us"] = round(self._tier_restore_us, 1)
            if tel is not None:
                targs.update(_roofline_targs(tel))
            self.tracer.tick(t0, (
                ("admission", t0, t1), ("draft", t1, td),
                ("grow", td, t2), ("plan", t2, t3),
                ("mixed_dispatch", t3, t4),
                ("host_sync", t4, t5), ("deliver", t5, t6),
            ), args=targs)
            if self.sentinel is not None:
                # same literal tuple as the tick() call above (R2's
                # exempt-span recovery reads the literal there); the
                # roofline deficit rides along as a pseudo-phase so a
                # persistent utilization regression pages like a
                # host_sync one
                outliers = self._sentinel_observe((
                    ("admission", t0, t1), ("draft", t1, td),
                    ("grow", td, t2), ("plan", t2, t3),
                    ("mixed_dispatch", t3, t4),
                    ("host_sync", t4, t5), ("deliver", t5, t6),
                ) + (
                    (("roofline_deficit", 0.0, tel["deficit_us"]),)
                    if tel is not None else ()
                ))
        self._actions_tick(outliers)
        return self.scheduler.has_work

    def _dispatch_mixed(self, args: tuple, has_prefill: bool) -> tuple:
        """One mixed dispatch with the split path's runtime-degradation
        contract: a ragged-kernel dispatch fault permanently falls back
        to the XLA ragged attention for the process and retries the same
        tick; on the XLA fallback there is nothing left to degrade to,
        so faults propagate to the supervisor.  Chaos sites: ``prefill``
        fires when the tick planned prefill tokens, ``decode`` at every
        dispatch (it IS the decode dispatch)."""
        faults = self.faults
        if faults is not None:
            if has_prefill and faults.trip("prefill") is not None:
                raise FaultInjected("prefill")
            if (
                faults.trip("decode") is not None
                and not self._degrade_mixed(
                    "chaos: injected mixed-dispatch fault"
                )
            ):
                raise FaultInjected("decode")
        self.n_dispatches += 1
        try:
            return self._mixed_step(self.params, self.pool.pages, *args)
        except Exception as e:  # noqa: BLE001 — any dispatch fault gates
            if not self._degrade_mixed(f"{type(e).__name__}: {e}"):
                raise
            self.n_dispatches += 1
            # lint: disable=R7 -- same donated-pages caveat as the split
            # path's retry: injected faults fire BEFORE dispatch, so the
            # chaos retry never sees consumed pages; a real post-donation
            # fault raises on the deleted buffers here and the supervisor
            # restart (which rebuilds the pool) takes over
            return self._mixed_step(self.params, self.pool.pages, *args)

    def _degrade_mixed(self, reason: str) -> bool:
        """Pallas → XLA fallback for the unified tick, process-wide
        (the paged decode step's degradation discipline).  The tick is
        ONE program, so its Pallas kernels — ragged attention AND the
        fused sampling epilogue — degrade as a unit: the host cannot
        attribute a dispatch fault to one kernel inside the jaxpr, and
        each has its own XLA sibling.  Returns False when already fully
        on the fallback."""
        if self.ragged_attn_impl == "pallas" or self.epilogue_impl == "fused":
            from llm_np_cp_tpu.ops.pallas.support import (
                disable_kernel,
                epilogue_kernel_name,
                ragged_kernel_name,
            )

            if self.ragged_attn_impl == "pallas":
                disable_kernel(
                    ragged_kernel_name(self.cache_dtype == jnp.int8),
                    reason,
                )
                self.ragged_attn_impl = "xla"
            if self.epilogue_impl == "fused":
                from llm_np_cp_tpu.models.transformer import (
                    head_quant_mode,
                )

                disable_kernel(
                    epilogue_kernel_name(
                        head_quant_mode(self.params, self.config)
                        == "int8"
                    ),
                    reason,
                )
                self.epilogue_impl = "xla"
            self.decode_degraded = reason
            self._mixed_step = self._make_mixed_step()
            return True
        return False

    def _kv_bytes_tick_mixed(
        self,
        decode_rows: list[Request],
        prefill_segs: list[tuple[Request, int]],
    ) -> int:
        """K/V bytes this mixed tick's attention touches.  The ragged
        kernel streams each q tile's visible blocks (window-aware per
        layer); the XLA fallback materializes every token's full padded
        row view, counted as such.  The math lives in serve/telemetry
        (which also yields the per-request split for cost attribution)
        so the metrics gauge and the roofline model can never drift;
        called post-accept-walk, draft_len is 0 and the numbers match
        the historical draft-free accounting exactly."""
        return int(mixed_tick_kv_read(self, decode_rows, prefill_segs,
                                      per_request=False)[0])

    def _warm_mixed_bucket(self, t_w: int) -> None:
        """Compile one packed-width bucket with an all-dead batch: every
        lane points at the scratch block and is fully masked, so the
        only effect is the compile (and a garbage write to scratch)."""
        qb = self._q_tile
        b = self.scheduler.max_slots
        mb = self.max_blocks_per_seq
        zeros = (
            np.zeros(t_w, np.int32), np.zeros(t_w, np.int32),
            np.zeros(t_w, np.int32), np.zeros(t_w, np.int32),
            np.zeros(t_w, np.int32), np.zeros(t_w, np.int32),
            np.zeros(t_w, bool),
            np.zeros(t_w // qb, np.int32), np.zeros(t_w // qb, np.int32),
            np.zeros(t_w // qb, np.int32),
            np.zeros((b, mb), np.int32), np.zeros(b, np.int32),
            np.zeros((b, self._spec_w), np.int32),
            np.zeros((b, self._spec_w), np.int32),
            np.zeros(b, np.uint32), np.zeros(b, np.int32),
        )
        out, self.pool.pages = self._mixed_step(
            self.params, self.pool.pages,
            *(self._put(a) for a in zeros),
        )
        np.asarray(out)  # block until the compile lands

    def _dispatch_decode(self, *args: jnp.ndarray) -> tuple:
        """One decode dispatch with runtime kernel degradation: if the
        paged step faults at dispatch time (an injected chaos fault or a
        real Mosaic/runtime error that the startup probe could not
        foresee), permanently fall back to the gather impl for the whole
        process and retry the SAME tick on it — requests see one slower
        tick, never a failure.  On the gather impls there is nothing left
        to degrade to, so faults propagate (the supervisor's problem)."""
        faults = self.faults
        if (
            faults is not None
            and faults.trip("decode") is not None
            and not self._degrade_decode("chaos: injected decode-dispatch "
                                         "fault")
        ):
            raise FaultInjected("decode")
        self.n_dispatches += 1
        try:
            return self._decode_step(self.params, self.pool.pages, *args)
        except Exception as e:  # noqa: BLE001 — any dispatch fault gates
            if not self._degrade_decode(f"{type(e).__name__}: {e}"):
                raise
            self.n_dispatches += 1
            # lint: disable=R7 -- the paged step donated the pool pages;
            # if the fault struck after they were consumed this retry
            # raises on the deleted buffers and the supervisor restart
            # (which rebuilds the pool) takes over — injected faults
            # fire before dispatch, so the chaos path always retries
            # cleanly
            return self._decode_step(self.params, self.pool.pages, *args)

    def _degrade_decode(self, reason: str) -> bool:
        """Paged attention → gather AND fused epilogue → XLA tail,
        process-wide, as a unit (the step is one program — see
        ``_degrade_mixed``).  Returns False when there is nothing left
        to fall back to (gather impl with the XLA tail)."""
        if self.decode_attn_impl == "paged" or self.epilogue_impl == "fused":
            from llm_np_cp_tpu.ops.pallas.support import (
                disable_kernel,
                epilogue_kernel_name,
                paged_kernel_name,
            )

            # process-wide: a supervisor rebuild (clone_fresh) and any
            # future engine in this process must not re-select the
            # faulted kernel
            if self.decode_attn_impl == "paged":
                disable_kernel(
                    paged_kernel_name(self.cache_dtype == jnp.int8),
                    reason,
                )
                self.decode_attn_impl = "xla"
            if self.epilogue_impl == "fused":
                from llm_np_cp_tpu.models.transformer import (
                    head_quant_mode,
                )

                disable_kernel(
                    epilogue_kernel_name(
                        head_quant_mode(self.params, self.config)
                        == "int8"
                    ),
                    reason,
                )
                self.epilogue_impl = "xla"
            self.decode_degraded = reason
            self._decode_step = self._make_decode_step(
                self.decode_attn_impl
            )
            return True
        return False

    def _kv_bytes_tick(self, running: list[Request]) -> int:
        """K/V bytes this tick's decode attention touches — the
        observable for the gather→paged win.  The gather impls
        materialize the full padded [L, B, S_max] view regardless of
        content; the paged kernel streams only each row's visible blocks
        (first-pad block through the length block — and on sliding-
        window layers only the window's blocks, counted per layer).
        The math lives in serve/telemetry (shared with the roofline
        model's per-request attribution) so the two cannot drift."""
        return int(split_tick_kv_read(self, running, per_request=False)[0])

    def warmup(
        self, prompt_lens: list[int], max_new_tokens: int = 2,
    ) -> None:
        """Compile every phase program before measuring, then reset
        metrics — so a subsequent replay reports steady-state serving
        numbers, not first-compile stalls (on TPU a model compile is
        multi-second and would dominate TTFT p99).

        prefill/decode/sample each compile once, so one dummy request
        covers them.  The scatter and prefix-gather specialize per block
        count, and a preemption re-prefill can produce ANY count up to
        the workload's worst case — warm them all by scattering/gathering
        a zero temp cache against the scratch block (garbage there is
        harmless by construction)."""
        if not prompt_lens:
            return
        # chaos is suspended for the warmup pass: it is compile-only, so
        # its dispatches must not consume deterministic schedule hits
        # (shifting every site's firing point) and a scheduled fault must
        # not fire here, where no supervisor is watching yet.  The tracer
        # is suspended with it — warmup's dummy request is not part of
        # any measured timeline, like the metrics reset below.
        # the journal is suspended with them: warmup's dummy request is
        # compile-only and must not leave admission records a restart
        # would try to replay
        # ...and the request log: warmup's dummy request is not a real
        # terminal, so it must not leave a canonical log line
        faults, self.faults = self.faults, None
        tracer, self.tracer = self.tracer, None
        journal, self.journal = self.journal, None
        request_log, self.request_log = self.request_log, None
        # telemetry too: warmup ticks are compile-only, not device work
        # worth billing or baselining
        telemetry, self.telemetry = self.telemetry, None
        # ...and the host tier: the dummy request's blocks must not
        # spill into (or restore from) the shared host pool, and its
        # wall times must not seed the breakeven's prefill rate
        host_tier, self.host_tier = self.host_tier, None
        # ...and the tenant ledger: the dummy request is nobody's bill
        tenants, self.tenants = self.tenants, None
        # the SLO tracker is suspended the same way (the dummy request
        # must not count as a verdict) and survives _warmup_body's
        # metrics reset — the fresh ServeMetrics gets it back
        slo_tracker = getattr(self.metrics, "slo", None)
        self.metrics.slo = None
        try:
            self._warmup_body(prompt_lens, max_new_tokens)
        finally:
            self.faults = faults
            self.tracer = tracer
            self.journal = journal
            self.request_log = request_log
            self.telemetry = telemetry
            self.host_tier = host_tier
            self.tenants = tenants
            self.metrics.slo = slo_tracker

    def _warmup_body(self, prompt_lens: list[int],
                     max_new_tokens: int) -> None:
        # two decode tokens compile the decode/sample/column-scatter
        # programs; the workload's full budget only matters for b_max
        self.submit(np.ones(min(prompt_lens), np.int32),
                    min(2, max_new_tokens))
        self.run_until_complete()
        if self._restore_block is not None:
            # the host tier's one landing program: warm it against the
            # scratch block (garbage there is harmless by construction)
            # so the first mid-traffic restore never pays a compile
            shape = self.pool.pages.k.shape
            blk_shape = (shape[0],) + shape[2:]
            args = [self._put(jnp.zeros(blk_shape, self.cache_dtype))] * 2
            if self.pool.pages.quantized:
                args += [
                    self._put(jnp.zeros(blk_shape[:-1], jnp.float32))
                ] * 2
            self.pool.pages = self._restore_block(
                self.pool.pages, self._put(np.int32(0)), *args
            )
            # ...and the spill-path slicer (same traced-index contract)
            self._slice_block(self.pool.pages, self._put(np.int32(0)))
        if self.mixed:
            # one compile per packed-width bucket — the dummy request
            # covered whichever buckets its own ticks picked; warm the
            # rest directly so mid-traffic composition churn can never
            # trigger a compile stall
            for t_w in self.mixed_buckets:
                self._warm_mixed_bucket(t_w)
            if self.pool.prefix_cache is not None:
                self.pool.prefix_cache.clear()
            self.scheduler.finished.clear()
            self.metrics = ServeMetrics(clock=self.clock)
            return
        b_max = min(
            self.pool.blocks_for(_ceil_to(
                max(prompt_lens) + max_new_tokens - 1, self.prefill_chunk
            )),
            self.max_blocks_per_seq,
        )
        cache = self._make_temp_cache()
        for nb in range(1, b_max + 1):
            self.pool.pages = self._scatter_prefill(
                self.pool.pages, cache, self._put(np.zeros(nb, np.int32)),
                self._put(np.int32(0)),
            )
        if self.pool.prefix_cache is not None:
            # a prefix hit can cover any share-unit multiple of blocks up
            # to one chunk short of the worst width — warm each gather
            # shape, then drop the dummy request's registered blocks so
            # the measured span starts with a cold cache
            unit = self._share_unit
            h_max = (
                (b_max * self.block_size - self.prefill_chunk)
                // (unit * self.block_size)
            ) * unit
            for h in range(unit, max(h_max, 0) + 1, unit):
                cache = self._make_temp_cache()
                self._gather_prefix(
                    cache, self.pool.pages, self._put(np.zeros(h, np.int32)),
                    self._put(np.int32(0)),
                )
            self.pool.prefix_cache.clear()
        # the dummy request is not part of any measured trace: drop it
        # from the finished ledger along with the metrics it produced
        self.scheduler.finished.clear()
        self.metrics = ServeMetrics(clock=self.clock)

    def run_until_complete(self, max_ticks: int = 100_000) -> None:
        for _ in range(max_ticks):
            if not self.step():
                return
        raise RuntimeError(f"serve loop did not drain within {max_ticks} ticks")

    # ------------------------------------------------------------------
    def replay_trace(
        self,
        trace: list[dict[str, Any]],
        *,
        realtime: bool = False,
        max_ticks: int = 100_000,
    ) -> dict[str, Any]:
        """Replay ``[{"arrival_s", "prompt", "max_new_tokens", "seed"?}]``.

        realtime=False (default, and what tests/bench use on CPU):
        arrivals are released by a virtual clock that advances to the
        next arrival whenever the engine is idle — the schedule stress
        is preserved without wall-clock sleeps.  realtime=True sleeps
        until each arrival (live serving simulation).  The loop itself
        is serve/trace.replay_arrivals, shared with ReplicaSet.
        """
        from llm_np_cp_tpu.serve.trace import replay_arrivals

        return replay_arrivals(
            self, trace, self.metrics.snapshot,
            realtime=realtime, max_ticks=max_ticks,
        )
