"""Data-parallel engine replicas behind one front-end, with
prefix-affinity routing.

Tensor parallelism (``ServeEngine(mesh_plan=...)``) cuts per-token
latency; CAPACITY scales by running N independent engine+pool stacks —
each on its own mesh slice — and routing requests between them.  The
router is where the prefix cache meets the fleet: two requests with the
same prompt prefix only share KV blocks if they land on the SAME
replica, so the router keys on the prefix cache's own chained content
hash (serve/prefix_cache.prefix_block_keys — key equality here IS block
key equality there) and sticks each prefix chain to one replica.
Shared-prompt traffic therefore stays block-local by construction;
unrelated traffic spreads by least-loaded assignment, and queue
pressure spills a request off its affine replica rather than letting
affinity amplify a hot spot.

Three layers, smallest first:

- ``PrefixRouter``   — pure routing policy (sticky prefix→replica map,
  least-loaded assignment, spill-on-pressure, forget-on-death), no
  engine imports, unit-testable in microseconds.
- ``ReplicaSet``     — direct-mode fleet for tests and bench: N engines
  ticked from one loop, ``submit``/``replay_trace`` mirroring the
  single-engine API, plus ``restart_replica`` (clone_fresh + recover,
  the supervisor discipline driven synchronously) so one replica's
  death-and-recovery can be exercised while its peers keep serving.
- ``ReplicaRunner``  — the HTTP-mode fleet: one ``EngineRunner``
  (supervised tick thread, serve/http/server.py) per replica behind the
  runner interface ``HttpServer`` speaks, so abort / drain / supervised
  restart all stay PER REPLICA — one crashed replica degrades the
  server, it does not take it down.

Replicas must be geometry-identical (same pool/slots/chunk): the router
may send any request anywhere, so admission limits cannot differ.
"""

from __future__ import annotations

import hashlib
import itertools
import math
import threading
from typing import Any, Callable

import numpy as np

from llm_np_cp_tpu.serve.prefix_cache import prefix_block_keys
from llm_np_cp_tpu.serve.scheduler import Request


def _ceil_to(n: int, g: int) -> int:
    return -(-n // g) * g


def _fresh_replica_engine(src: Any) -> Any:
    """A warmed NEW replica cloned from ``src`` (elastic
    ``add_replica``): same geometry and params, compiled steps shared
    (``clone_fresh`` + ``share_compiled_steps`` — joining the fleet
    compiles nothing), but share-NOTHING observability: its own
    metrics/SLO tracker/sentinel/ActionPolicy (those are per-tick-thread
    state; the restart path shares them because a restart IS the same
    replica) and no journal (a journal segment is a per-path resource
    the caller wires explicitly)."""
    from llm_np_cp_tpu.serve.metrics import ServeMetrics

    eng = src.clone_fresh()
    eng.share_compiled_steps(src)
    eng.journal = None
    metrics = ServeMetrics(clock=src.clock)
    slo = getattr(src.metrics, "slo", None)
    if slo is not None:
        from llm_np_cp_tpu.serve.slo import SLOTracker

        metrics.slo = SLOTracker(slo.policy, clock=slo.clock)
    eng.metrics = metrics
    sent = src.sentinel
    if sent is not None:
        from llm_np_cp_tpu.serve.slo import TickSentinel

        eng.sentinel = TickSentinel(
            alpha=sent.alpha, threshold=sent.threshold,
            warmup_ticks=sent.warmup_ticks, min_us=sent.min_us,
        )
    eng.actions = None if src.actions is None else src.actions.spawn()
    ledger = getattr(src, "tenants", None)
    if ledger is not None:
        # share-nothing here too: each replica bills its own ledger
        # (same config), and the scrape/debug endpoints aggregate
        from llm_np_cp_tpu.serve.tenants import TenantLedger

        eng.tenants = TenantLedger(
            fairness=ledger.fairness, max_inflight=ledger.max_inflight,
            max_series=ledger.max_series, policy=ledger.policy,
            clock=ledger.clock,
        )
    return eng


class PrefixRouter:
    """Sticky prefix-affinity routing over ``n`` replicas.

    ``affinity_key`` mirrors the engine's admission-time hashing exactly
    (same left-pad, same share-unit truncation, same chained SHA-256),
    so the deepest shareable block key of a prompt is the routing key —
    if two prompts route together here, their leading blocks would have
    matched in a replica's prefix cache, and vice versa.  Prompts too
    short to share any block fall back to a whole-prompt hash: affinity
    still groups exact duplicates, it just cannot promise block reuse.

    Policy:
    - **first sight**: a new key is assigned to the least-loaded alive
      replica and remembered (``routed`` counts every affinity-honoring
      verdict, first sights included).
    - **spill**: when the sticky replica's queue depth is at least
      ``spill_queue_depth`` AND some other alive replica's is strictly
      lower, the request goes to the least-loaded replica instead
      (``spilled``).  The sticky entry is NOT moved — a spill is load
      shedding, not a migration; the prefix blocks still live where the
      entry points.
    - **death**: verdicts never name a dead replica; sticky entries
      pointing at one are dropped on touch, so its prefixes re-home to
      live replicas (their blocks died with the pool anyway).
    """

    def __init__(self, n_replicas: int, *, block_size: int,
                 prefill_chunk: int,
                 spill_queue_depth: int | None = 4) -> None:
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.n = n_replicas
        self.block_size = block_size
        self.prefill_chunk = prefill_chunk
        # share granularity in blocks — must mirror ServeEngine._share_unit
        self._unit = (
            math.lcm(block_size, prefill_chunk) // block_size
        )
        self.spill_queue_depth = spill_queue_depth
        self._sticky: dict[bytes, int] = {}
        self._rr = 0  # rotating tiebreak so equal loads spread
        self.routed = 0
        self.spilled = 0

    def affinity_chain(
        self, prompt_ids: Any,
    ) -> tuple[bytes, tuple[list[bytes], int] | None]:
        """→ ``(routing key, reusable (keys, prefill_width) or None)``.

        The routing key is the DEEPEST shareable prefix-block key of the
        prompt — identical to the last entry of the chain the engine
        registers in its prefix cache — or a whole-prompt hash when no
        block is shareable.  The chain itself is returned so direct-mode
        callers can pre-seed ``Request.extra['prefix_keys']`` and the
        engine's admission plan reuses it instead of re-running the
        SHA-256 chain over the same prompt."""
        content = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        w = _ceil_to(max(content.size, 1), self.prefill_chunk)
        pad = w - content.size
        n_keys = (
            (w - self.prefill_chunk) // (self._unit * self.block_size)
        ) * self._unit
        if n_keys > 0:
            keys = prefix_block_keys(content, pad, self.block_size, n_keys)
            if keys:
                return keys[-1], (keys, w)
        return hashlib.sha256(
            b"whole;" + content.tobytes()
        ).digest(), None

    def affinity_key(self, prompt_ids: Any) -> bytes:
        return self.affinity_chain(prompt_ids)[0]

    def _least_loaded(self, loads: list[int], alive: list[bool]) -> int:
        # ties rotate: an idle fleet's first N distinct prefixes spread
        # over the N replicas instead of piling onto index 0
        idx = min(
            (i for i in range(self.n) if alive[i]),
            key=lambda i: (loads[i], (i - self._rr) % self.n),
        )
        self._rr = (idx + 1) % self.n
        return idx

    def route(self, key: bytes, *, loads: list[int],
              queue_depths: list[int] | None = None,
              alive: list[bool] | None = None) -> tuple[int, bool]:
        """→ ``(replica index, spilled)``.  ``loads`` orders candidates
        for least-loaded assignment (live request counts); spill
        pressure is judged on ``queue_depths`` (defaults to ``loads``) —
        a deep QUEUE means waiting, a full decode batch is just
        utilization."""
        alive = alive if alive is not None else [True] * self.n
        if not any(alive):
            raise RuntimeError("no alive replica to route to")
        qd = queue_depths if queue_depths is not None else loads
        idx = self._sticky.get(key)
        if idx is not None and not alive[idx]:
            del self._sticky[key]  # re-home: the blocks died with the pool
            idx = None
        if idx is None:
            idx = self._least_loaded(loads, alive)
            self._sticky[key] = idx
            self.routed += 1
            return idx, False
        if (
            self.spill_queue_depth is not None
            and qd[idx] >= self.spill_queue_depth
        ):
            spill_to = self._least_loaded(loads, alive)
            if spill_to != idx and qd[spill_to] < qd[idx]:
                self.spilled += 1
                return spill_to, True
        self.routed += 1
        return idx, False

    def sticky_owner(self, key: bytes) -> int | None:
        """The replica a prefix chain is currently sticky to, or None —
        a read-only probe the fleet's block-shipping paths use to find
        WHERE a spilled request's prefix blocks live so the affine
        replica can ship them through the host tier."""
        return self._sticky.get(key)

    def forget_replica(self, idx: int) -> int:
        """Drop every sticky entry pointing at ``idx`` (replica death /
        rebuild with a zeroed pool).  Returns how many were dropped."""
        dead = [k for k, v in self._sticky.items() if v == idx]
        for k in dead:
            del self._sticky[k]
        return len(dead)

    def grow(self, n: int) -> None:
        """Widen the candidate set to ``n`` replicas (elastic
        ``add_replica`` — the new index starts cold and picks up
        traffic first-sight by least-loaded assignment).  Shrinking is
        never an index operation: a removed replica keeps its slot and
        just leaves the ``alive`` mask, so sticky entries and owner
        maps stay valid."""
        if n < self.n:
            raise ValueError(
                f"router cannot shrink ({self.n} -> {n}); removal is "
                "an alive-mask change, not an index change"
            )
        self.n = n


def _check_homogeneous(engines: list) -> None:
    if not engines:
        raise ValueError("need at least one engine")
    e0 = engines[0]
    sig0 = (e0.block_size, e0.prefill_chunk, e0.max_seq_len,
            e0.scheduler.max_slots, e0.pool.num_blocks,
            str(e0.cache_dtype))
    for i, e in enumerate(engines[1:], 1):
        sig = (e.block_size, e.prefill_chunk, e.max_seq_len,
               e.scheduler.max_slots, e.pool.num_blocks,
               str(e.cache_dtype))
        if sig != sig0:
            raise ValueError(
                f"replica {i} geometry {sig} != replica 0 {sig0}: the "
                "router may send any request anywhere, so replicas must "
                "be geometry-identical"
            )


class ReplicaSet:
    """Direct-mode data-parallel fleet: N engines, one tick loop.

    The single-engine ``submit``/``step``/``replay_trace`` surface over
    N replicas — what tests and bench drive (the HTTP path wraps the
    same engines in ``ReplicaRunner`` instead).  Request ids are
    globally unique across the set; ``step()`` ticks every alive
    replica once.
    """

    def __init__(self, engines: list, *,
                 spill_queue_depth: int | None = 4) -> None:
        _check_homogeneous(engines)
        self.engines = list(engines)
        e0 = self.engines[0]
        self.router = PrefixRouter(
            len(self.engines), block_size=e0.block_size,
            prefill_chunk=e0.prefill_chunk,
            spill_queue_depth=spill_queue_depth,
        )
        self.alive = [True] * len(self.engines)
        self._owner: dict[int, int] = {}  # rid → replica index
        self._next_id = max(e._next_id for e in self.engines)
        self.clock = e0.clock

    # -- routing-aware single-engine surface ---------------------------
    def _loads(self) -> list[int]:
        return [len(e._requests) for e in self.engines]

    def _queue_depths(self) -> list[int]:
        return [e.scheduler.queue_depth for e in self.engines]

    def submit(self, prompt_ids, max_new_tokens: int, *,
               seed: int = 0, callback: Callable | None = None,
               on_event: Callable | None = None,
               deadline_s: float | None = None,
               arrival_time: float | None = None,
               trace_id: str | None = None,
               speculative: bool = False,
               tenant: str = "default",
               replica: int | None = None) -> Request:
        """Route (or pin, via ``replica=``) and submit.  The returned
        Request carries its replica in ``extra['replica']`` and the
        router's spill verdict in ``extra['spilled']``."""
        chain = None
        spilled = False
        if replica is None:
            key, chain = self.router.affinity_chain(prompt_ids)
            replica, spilled = self.router.route(
                key, loads=self._loads(),
                queue_depths=self._queue_depths(), alive=self.alive,
            )
        elif not self.alive[replica]:
            raise RuntimeError(f"replica {replica} is dead")
        rid = self._next_id
        self._next_id += 1
        req = self.engines[replica].submit(
            prompt_ids, max_new_tokens, request_id=rid, seed=seed,
            callback=callback, on_event=on_event, deadline_s=deadline_s,
            arrival_time=arrival_time, trace_id=trace_id,
            speculative=speculative, tenant=tenant,
        )
        if spilled:
            req.extra["spilled"] = True
            # fleet block shipping: a spill verdict lands the request
            # OFF its prefix-affine replica — with the shared host tier
            # on, the affine replica ships the chain's blocks host-side
            # so the spill target restores them instead of re-prefilling
            tier = getattr(self.engines[replica], "host_tier", None)
            if tier is not None and chain is not None:
                src = self.router.sticky_owner(key)
                if src is not None and src != replica and self.alive[src]:
                    self.engines[src].spill_prefix_blocks(keys=chain[0])
                    # the shipped entries must be host-RESIDENT before
                    # the spill target's next tick plans the admission,
                    # or the coverage walk misses and silently
                    # re-prefills.  Per-CHAIN wait, not drain(): the
                    # shared tier's queue may hold a whole prefix-set
                    # ship from a concurrent drain, and this submit
                    # must not flush strangers' jobs — a timeout just
                    # re-prefills, the fallback every tier path shares
                    src_cache = self.engines[src].pool.prefix_cache
                    have = (
                        len(src_cache.match(chain[0]))
                        if src_cache is not None else 0
                    )
                    if have:
                        tier.await_resident(chain[0][:have])
        tracer = getattr(self.engines[replica], "tracer", None)
        if tracer is not None:
            tracer.instant("route", cat="router", args={
                "rid": rid, "replica": replica, "spilled": spilled,
                "trace": req.extra.get("trace"),
            })
        if chain is not None:
            # hand the router's hash chain to the engine's admission
            # plan — same content, same width, same chain — so the
            # prompt is SHA-256'd once per submit, not twice
            keys, width = chain
            req.extra["prefix_keys"] = keys
            req.extra["prefix_keys_width"] = width
        req.extra["replica"] = replica
        self._owner[rid] = replica
        return req

    def abort(self, request_id: int) -> bool:
        idx = self._owner.get(request_id)
        if idx is None:
            return False
        return self.engines[idx].abort(request_id)

    def step(self) -> bool:
        """One tick across the fleet; True while any replica has work."""
        has_work = False
        for i, engine in enumerate(self.engines):
            if self.alive[i]:
                has_work |= engine.step()
        return has_work

    def run_until_complete(self, max_ticks: int = 100_000) -> None:
        for _ in range(max_ticks):
            if not self.step():
                return
        raise RuntimeError(
            f"replica set did not drain within {max_ticks} ticks"
        )

    @property
    def finished(self) -> list[Request]:
        """Terminal requests across the fleet, submission order."""
        out = [r for e in self.engines for r in e.scheduler.finished]
        return sorted(out, key=lambda r: r.req_id)

    # -- fleet lifecycle ----------------------------------------------
    def kill_replica(self, idx: int) -> list[Request]:
        """Simulate one replica's death: mark it dead (the router stops
        naming it; its sticky prefixes re-home) and return its in-flight
        requests — what a supervisor would replay.  The dead engine is
        left untouched for inspection, exactly like a hung tick thread's
        engine object."""
        self.alive[idx] = False
        self.router.forget_replica(idx)
        return list(self.engines[idx]._requests.values())

    def restart_replica(self, idx: int) -> None:
        """Supervised-restart discipline, driven synchronously: rebuild
        the replica via ``clone_fresh`` (compiled steps shared — a
        restart never recompiles) and replay its in-flight requests
        teacher-forced (``recover``), token-identically.  Peers keep
        serving between ``kill_replica`` and this call — nothing here
        touches them."""
        old = self.engines[idx]
        engine = old.clone_fresh()
        # the same adoption body as a drain/roll (_adopt_recovered):
        # an in-flight request whose tokens already reached its budget
        # (or a stop token) moves straight to the `finished` ledger
        # with its terminal event delivered — not just counted
        self._replay_in_place(old, engine)
        # terminal history survives the rebuild: the fleet's `finished`
        # ledger (and the parity checks reading it) must keep the
        # requests this replica completed BEFORE it died
        engine.scheduler.finished.extend(old.scheduler.finished)
        engine.scheduler.aborted.extend(old.scheduler.aborted)
        self.engines[idx] = engine
        self.alive[idx] = True

    # -- fleet lifecycle: rolling upgrade + elastic DP -----------------
    def _drain_to_peers(self, idx: int, *,
                        prefer_version: int | None = None) -> list[int]:
        """Move replica ``idx``'s in-flight requests onto live peers —
        the PR 9 drain-to-peer discipline driven synchronously: each
        request re-routes through the router AFTER ``idx``'s sticky
        prefixes were forgotten, is replayed teacher-forced on the peer
        (token-identical — deterministic (seed, content-pos) keys), and
        keeps its admission-time ``weights_version`` tag.  With
        ``prefer_version`` set (a mid-roll drain), peers still on that
        weight version are preferred so a stream is served end-to-end
        by one version whenever such a peer exists; when none is left
        (the last old-version replica draining), any live peer adopts
        it — the tag still reports the admission version.  Caller has
        already marked ``idx`` dead and forgotten its prefixes."""
        alive = list(self.alive)
        if prefer_version is not None:
            same = [
                ok and self.engines[i].weights_version == prefer_version
                for i, ok in enumerate(alive)
            ]
            if any(same):
                alive = same
        stops = tuple(self.engines[idx].stop_tokens or ())
        # fleet block shipping: the draining replica's prefixes are
        # about to re-home, so ship its registered prefix blocks
        # through the shared host tier FIRST — the adopting peers'
        # teacher-forced recover() admissions (and any later traffic on
        # those prefixes) then restore the K/V instead of re-prefilling
        # it (the tier's writer thread pays the copies; a dead pool —
        # pages yanked — ships nothing, which is the drop-and-recompute
        # behavior the tier-less fleet always had)
        tier = getattr(self.engines[idx], "host_tier", None)
        if tier is not None:
            self.engines[idx].spill_prefix_blocks()
            tier.drain()  # entries must be resident before peers plan
        # the draining replica's journal segment must terminate each
        # moved stream (the peer's recover() re-admits it into the
        # peer's segment) — otherwise a restart scanning both segments
        # replays it twice.  Same rule as the HTTP fleet's _drain_dead.
        src_journal = getattr(self.engines[idx], "journal", None)
        drained: list[int] = []
        inflight = sorted(
            self.engines[idx]._requests.values(), key=lambda r: r.req_id
        )
        for req in inflight:
            key, _ = self.router.affinity_chain(req.prompt)
            peer, _ = self.router.route(
                key, loads=self._loads(),
                queue_depths=self._queue_depths(), alive=alive,
            )
            engine = self.engines[peer]
            lineage = {
                "replays": int(req.extra.get("replays", 0)),
                "drains": int(req.extra.get("drains", 0)) + 1,
            }
            tracer = getattr(engine, "tracer", None)
            if tracer is not None:
                tracer.request_instant(req.req_id, "drain-to-peer", args={
                    "trace": req.extra.get("trace"),
                    "from_replica": idx, "to_replica": peer,
                })
            self._adopt_recovered(engine, req, lineage=lineage,
                                  stops=stops)
            if src_journal is not None:
                src_journal.terminal(req.req_id, "drained")
            self._owner[req.req_id] = peer
            drained.append(req.req_id)
        return drained

    def _adopt_recovered(self, engine: Any, req: Any, *,
                         lineage: dict[str, int],
                         stops: tuple[int, ...]) -> None:
        """The ONE done/stopped/recover adoption body shared by
        ``_drain_to_peers`` and ``_replay_in_place``: a fully generated
        stream moves only its terminal bookkeeping (the fleet's
        ``finished`` ledger reads scheduler state, so a drained-terminal
        request must appear there like any other finish, and the
        client's final event carries the remaining text); anything else
        is replayed teacher-forced through ``recover`` with its lineage
        and admission-time ``weights_version`` tag."""
        wv = req.extra.get("weights_version")
        tokens = list(req.generated)
        done = len(tokens) >= req.max_new_tokens
        stopped = bool(tokens) and tokens[-1] in stops
        if done or stopped:
            reason = "stop" if stopped else "length"
            tail = engine.finish_recovered(
                req.prompt, req.max_new_tokens,
                request_id=req.req_id, generated=tokens,
                reason=reason,
                trace_id=req.extra.get("trace"), lineage=lineage,
                tenant=getattr(req, "tenant", "default"),
                weights_version=wv,
            )
            req.finish_reason = reason
            engine.scheduler.finished.append(req)
            if req.on_event is not None:
                req.extra["final_text_delta"] = tail
                req.on_event(req, reason)
        else:
            engine.recover(
                req.prompt, req.max_new_tokens,
                request_id=req.req_id, seed=req.seed,
                generated=tokens, callback=req.callback,
                on_event=req.on_event, deadline_at=req.deadline,
                trace_id=req.extra.get("trace"), lineage=lineage,
                speculative=req.speculative,
                tenant=getattr(req, "tenant", "default"),
                weights_version=wv,
            )

    def _replay_in_place(self, old: Any, engine: Any) -> int:
        """Fleet-of-one roll: no peer to drain to, so the rebuilt
        engine replays its own in-flight streams teacher-forced —
        delivered tokens never change; tokens still to come sample
        from the new weights (there is no same-version peer to finish
        them on, and the request's version tag records its admission
        version either way)."""
        stops = tuple(old.stop_tokens or ())
        n = 0
        for req in sorted(old._requests.values(),
                          key=lambda r: r.req_id):
            lineage = {
                "replays": int(req.extra.get("replays", 0)) + 1,
                "drains": int(req.extra.get("drains", 0)),
            }
            self._adopt_recovered(engine, req, lineage=lineage,
                                  stops=stops)
            n += 1
        return n

    def rolling_upgrade(self, params_fn: Callable[[], Any], *,
                        version: int | None = None,
                        steps_between: int = 1) -> dict[str, Any]:
        """Swap the fleet onto fresh weights with zero downtime: one
        replica at a time is drained to its peers (in-flight streams
        complete token-identically there), rebuilt on ``params_fn()``'s
        weights via ``clone_fresh(params=...)``, and returned to
        routing; ``steps_between`` fleet ticks run after each swap so
        traffic keeps flowing mid-roll.

        Compile discipline (pinned by tests + the compile_counter
        section): the first rolled replica keeps its own jitted step
        callables (params are call arguments — same-shaped weights
        reuse every warm compile, different avals re-trace once), and
        every later rolled replica adopts the first one's callables via
        ``share_compiled_steps`` — new weights are jitted once per
        FLEET, never per replica.

        A checkpoint failure (``params_fn`` raising, or the
        ``upgrade_ckpt`` chaos site) aborts the roll CLEANLY with
        ``UpgradeAborted``: the replica being rolled was not yet
        drained, so it stays live on its old weights and the fleet
        never drops below N-1 capacity.  Replicas already rolled stay
        on the new weights (the version tag says which weights served
        each request)."""
        from llm_np_cp_tpu.serve.lifecycle import (
            cache_params_fn,
            load_upgrade_params,
        )

        order = [i for i, ok in enumerate(self.alive) if ok]
        if not order:
            raise RuntimeError("no alive replica to upgrade")
        if version is None:
            version = max(e.weights_version for e in self.engines) + 1
        params_once = cache_params_fn(params_fn)
        rolled: list[int] = []
        drained_total = 0
        first_rolled: Any = None
        for idx in order:
            old = self.engines[idx]
            params = load_upgrade_params(
                params_once, replica=idx, faults=old.faults,
                metrics=old.metrics, rolled=rolled, version=version,
            )
            old_version = old.weights_version
            self.alive[idx] = False
            self.router.forget_replica(idx)
            # fleet of one (or every peer already dead): nothing to
            # drain TO — the rebuilt engine replays its own streams in
            # place instead (the EngineRunner fleet-of-one discipline)
            had_peer = any(self.alive)
            drained = (
                self._drain_to_peers(idx, prefer_version=old_version)
                if had_peer else []
            )
            drained_total += len(drained)
            engine = old.clone_fresh(params=params,
                                     weights_version=version)
            if first_rolled is None:
                first_rolled = engine
            else:
                engine.share_compiled_steps(first_rolled)
            if not had_peer:
                self._replay_in_place(old, engine)
            engine.scheduler.finished.extend(old.scheduler.finished)
            engine.scheduler.aborted.extend(old.scheduler.aborted)
            self.engines[idx] = engine
            self.alive[idx] = True
            engine.metrics.on_lifecycle_action("upgrade_replica")
            tracer = getattr(engine, "tracer", None)
            if tracer is not None:
                tracer.instant("upgrade-replica", cat="lifecycle", args={
                    "replica": idx, "version": version,
                    "drained": len(drained),
                })
            rolled.append(idx)
            for _ in range(steps_between):
                self.step()
        return {
            "rolled": rolled, "version": version,
            "drained": drained_total,
        }

    def add_replica(self, engine: Any = None) -> int:
        """Grow the fleet at runtime: a warmed clone of a live replica
        (compiled steps shared — joining compiles nothing; fresh
        metrics/sentinel/policy — per-thread state is never shared
        across replicas), appended under a new index the router starts
        routing to first-sight.  Returns the new replica index."""
        src_idx = next(
            (i for i, ok in enumerate(self.alive) if ok), None)
        if src_idx is None:
            raise RuntimeError("no alive replica to clone from")
        if engine is None:
            engine = _fresh_replica_engine(self.engines[src_idx])
        _check_homogeneous([self.engines[src_idx], engine])
        self.engines.append(engine)
        self.alive.append(True)
        idx = len(self.engines) - 1
        self.router.grow(len(self.engines))
        self._next_id = max(self._next_id, engine._next_id)
        engine.metrics.on_lifecycle_action("add_replica")
        tracer = getattr(engine, "tracer", None)
        if tracer is not None:
            tracer.instant("add-replica", cat="lifecycle",
                           args={"replica": idx})
        return idx

    def remove_replica(self, idx: int) -> list[int]:
        """Shrink the fleet at runtime — the SIGTERM-style drain: the
        replica leaves routing, its sticky prefixes re-home, and every
        in-flight stream is adopted by a peer (teacher-forced, token-
        identical).  The engine object keeps its slot (indices are
        stable forever; ``alive`` is the membership mask) so its
        terminal history stays readable.  Returns the drained request
        ids."""
        if not (0 <= idx < len(self.engines)) or not self.alive[idx]:
            raise ValueError(f"replica {idx} is not an alive replica")
        if sum(self.alive) < 2:
            raise RuntimeError(
                "cannot remove the last alive replica — scale-down "
                "floor is 1"
            )
        self.alive[idx] = False
        self.router.forget_replica(idx)
        drained = self._drain_to_peers(idx)
        self.engines[idx].metrics.on_lifecycle_action("remove_replica")
        tracer = getattr(self.engines[idx], "tracer", None)
        if tracer is not None:
            tracer.instant("remove-replica", cat="lifecycle", args={
                "replica": idx, "drained": len(drained),
            })
        return drained

    # -- aggregate observability ---------------------------------------
    def snapshot(self) -> dict[str, Any]:
        """Fleet-level metrics: summed counters, percentile stats over
        the CONCATENATED per-request samples (a request's TTFT does not
        care which replica served it), per-replica snapshots, and the
        router's verdict counters."""
        per = [e.metrics.snapshot() for e in self.engines]
        out: dict[str, Any] = {
            "replicas": per,
            "n_replicas": len(self.engines),
            "alive_replicas": sum(1 for a in self.alive if a),
            "weights_versions": [
                e.weights_version for e in self.engines
            ],
            "router_routed": self.router.routed,
            "router_spilled": self.router.spilled,
        }
        for key in ("submitted", "finished", "aborted", "rejected",
                    "recovered", "ticks", "preemptions",
                    "total_generated_tokens"):
            out[key] = sum(s[key] for s in per)
        span = max((s["wall_s"] for s in per), default=0.0)
        out["wall_s"] = span
        out["throughput_tok_s"] = (
            out["total_generated_tokens"] / span if span > 0 else 0.0
        )
        ttft: list[float] = []
        for e in self.engines:
            with e.metrics._lock:
                ttft.extend(e.metrics.ttft_s)
        if ttft:
            arr = np.asarray(ttft, dtype=np.float64)
            for q, name in ((50, "p50"), (90, "p90"), (99, "p99")):
                out[f"ttft_s_{name}"] = float(np.percentile(arr, q))
        req = sum(s.get("prefix_blocks_requested", 0) for s in per)
        hit = sum(s.get("prefix_blocks_hit", 0) for s in per)
        out["prefix_blocks_requested"] = req
        out["prefix_blocks_hit"] = hit
        if req:
            out["prefix_hit_rate"] = hit / req
        # fleet roofline telemetry (serve/telemetry.py): summed byte/
        # time ledgers, with the aggregate utilization recomputed from
        # the SUMS (a mean of per-replica ratios would weight an idle
        # replica like a loaded one — the burn-rate discipline)
        rf = [s for s in per if "roofline_ticks" in s]
        if rf:
            for key in ("roofline_ticks", "kv_read_bytes_total",
                        "kv_write_bytes_total", "weight_bytes_total",
                        "device_time_s_total"):
                out[key] = sum(s[key] for s in rf)
            dev = out["device_time_s_total"]
            total_bytes = (out["kv_read_bytes_total"]
                           + out["kv_write_bytes_total"]
                           + out["weight_bytes_total"])
            hbm = next(
                (s["hbm_gbps"] for s in rf if s.get("hbm_gbps")), None
            )
            out["hbm_gbps"] = hbm
            if dev > 0:
                out["roofline_gbps"] = total_bytes / dev / 1e9
                if hbm:
                    out["roofline_util"] = out["roofline_gbps"] / hbm
        # fleet SLO accounting: summed verdicts, burn rates recomputed
        # from summed window totals (serve/slo.aggregate_slo)
        from llm_np_cp_tpu.serve.slo import aggregate_slo

        agg = aggregate_slo(
            [getattr(e.metrics, "slo", None) for e in self.engines]
        )
        out.update({k: v for k, v in agg.items() if k != "policy"})
        # fleet tenant accounting: per-tenant counters summed across
        # replica ledgers, cost shares and SLO burn recomputed from the
        # sums (serve/tenants.aggregate_tenants)
        from llm_np_cp_tpu.serve.tenants import aggregate_tenants

        tn = aggregate_tenants(
            [getattr(e, "tenants", None) for e in self.engines]
        )
        if tn:
            out["tenants"] = tn["tenants"]
            out["n_tenants"] = tn["n_tenants"]
        return out

    # ------------------------------------------------------------------
    def replay_trace(self, trace: list[dict[str, Any]], *,
                     realtime: bool = False,
                     max_ticks: int = 100_000) -> dict[str, Any]:
        """The single-engine trace replay over the fleet (same loop —
        serve/trace.replay_arrivals — same virtual-clock discipline),
        with routing per arrival."""
        from llm_np_cp_tpu.serve.trace import replay_arrivals

        return replay_arrivals(
            self, trace, self.snapshot,
            realtime=realtime, max_ticks=max_ticks,
        )


class ReplicaRunner:
    """The HTTP-mode fleet: per-replica ``EngineRunner`` supervision
    behind the one runner interface ``HttpServer`` speaks.

    Every replica keeps its OWN tick thread, watchdog, restart budget,
    and recovery replay — a crash or hang on one replica degrades the
    fleet (``state == "degraded"``) while its peers keep streaming; the
    server only reports ``crashed`` (503) when EVERY replica is
    terminally dark.  Routing happens at submit time on the event-loop
    thread: the router reads each runner's live-stream count and each
    scheduler's queue depth (both plain int reads — racing a tick by one
    request is harmless for placement).
    """

    def __init__(self, engines: list, *,
                 request_timeout: float | None = None,
                 tick_deadline: float | None = None,
                 max_restarts: int = 0,
                 restart_backoff_s: float = 0.5,
                 restart_window_s: float = 300.0,
                 spill_queue_depth: int | None = 4) -> None:
        from functools import partial

        from llm_np_cp_tpu.serve.http.server import EngineRunner

        _check_homogeneous(engines)
        # supervision config, kept so an elastic add_replica builds its
        # runner with the SAME watchdog/restart policy as the founders
        self._supervision = dict(
            request_timeout=request_timeout,
            tick_deadline=tick_deadline, max_restarts=max_restarts,
            restart_backoff_s=restart_backoff_s,
            restart_window_s=restart_window_s,
        )
        self.replicas = [
            EngineRunner(e, **self._supervision) for e in engines
        ]
        for i, runner in enumerate(self.replicas):
            # fleet drain: a replica going terminally dark hands its
            # unterminated streams to the peers the router re-homes its
            # prefixes to, instead of abort-flushing them
            runner.on_terminal_crash = partial(self._drain_dead, i)
            # request-log lines tag which replica served the request
            runner.replica_index = i
        e0 = engines[0]
        self.router = PrefixRouter(
            len(engines), block_size=e0.block_size,
            prefill_chunk=e0.prefill_chunk,
            spill_queue_depth=spill_queue_depth,
        )
        self.faults = self.replicas[0].faults
        self._owner: dict[int, int] = {}
        self._rid = itertools.count(max(
            max(getattr(e, "_next_id", 0) for e in engines),
            # journal-replayed rids must never be re-issued — PARKED
            # (finished-while-detached) ones included: finish_recovered
            # never bumps the engine's _next_id, and a fresh request
            # reusing the rid would shadow the stream its client is
            # about to resume (the EngineRunner.__init__ defense,
            # fleet-wide)
            max((r for runner in self.replicas
                 for r in (*runner._inflight, *runner._resumable)),
                default=-1) + 1,
        ))
        self._dead: set[int] = set()  # replicas whose death was forgotten
        # lifecycle membership: replicas mid-upgrade (back after the
        # swap) and replicas removed for good — both leave routing;
        # indices are stable forever, `alive` is the membership mask.
        # Mutated only by the admin/lifecycle thread, read racily by
        # submit-time routing (a set membership read is GIL-atomic and
        # one stale verdict just routes one request to a replica that
        # immediately drains it — harmless, like the load reads)
        self._lifecycle: set[int] = set()
        self._removed: set[int] = set()
        self._upgrade_lock = threading.Lock()

    # -- the EngineRunner interface ------------------------------------
    @property
    def engine(self) -> Any:
        """A representative engine (tokenizer / tracer / clock access —
        geometry-identical across the fleet by construction)."""
        return self.replicas[0].engine

    def start(self) -> None:
        for r in self.replicas:
            r.start()

    def stop(self, timeout: float = 10.0) -> None:
        for r in self.replicas:
            r.stop(timeout=timeout)

    def next_rid(self) -> int:
        return next(self._rid)

    @property
    def inflight(self) -> int:
        return sum(r.inflight for r in self.replicas)

    @property
    def restarts(self) -> int:
        return sum(r.restarts for r in self.replicas)

    @property
    def recovery_latency_s(self) -> list[float]:
        return [v for r in self.replicas for v in r.recovery_latency_s]

    @property
    def journal_replayed(self) -> int:
        return sum(r.journal_replayed for r in self.replicas)

    @property
    def journal_resumed(self) -> int:
        return sum(r.journal_resumed for r in self.replicas)

    @property
    def crashed(self) -> str | None:
        """Terminal only when the WHOLE fleet is dark — a single crashed
        replica is a degradation the router routes around.  Replicas
        removed by elastic scale-down left the fleet on purpose and do
        not count either way."""
        downs = {
            i: r.crashed for i, r in enumerate(self.replicas)
            if i not in self._removed
        }
        if downs and all(downs.values()):
            return "; ".join(
                f"replica {i}: {c}" for i, c in sorted(downs.items())
            )
        return None

    @property
    def state(self) -> str:
        if self.crashed:
            return "crashed"
        if any(r.crashed or r.recovering for r in self.replicas):
            return "degraded"
        return "ok"

    def replica_states(self) -> list[dict[str, Any]]:
        """Per-replica health for ``/healthz``."""
        return [
            {
                "replica": i,
                "state": (
                    "removed" if i in self._removed
                    else "upgrading" if i in self._lifecycle
                    else r.state
                ),
                "restarts": r.restarts,
                "inflight": r.inflight,
                "weights_version": getattr(r.engine, "weights_version", 0),
                "mesh": getattr(r.engine, "mesh_desc", None),
            }
            for i, r in enumerate(self.replicas)
        ]

    def _routable(self, i: int) -> bool:
        """May the router place NEW work on replica ``i``?  Not crashed,
        not removed, not mid-upgrade."""
        return (
            self.replicas[i].crashed is None
            and i not in self._removed
            and i not in self._lifecycle
        )

    def _alive(self) -> list[bool]:
        alive = []
        for i, r in enumerate(self.replicas):
            ok = r.crashed is None
            if not ok and i not in self._dead:
                # first sight of a terminal crash: its sticky prefixes
                # re-home to survivors
                self._dead.add(i)
                self.router.forget_replica(i)
            alive.append(ok and i not in self._removed
                         and i not in self._lifecycle)
        return alive

    def submit(self, rid: int, payload: Any, loop: Any, aq: Any) -> None:
        alive = self._alive()
        if not any(alive):
            # mimic EngineRunner's crash answer so handlers need no
            # fleet-awareness
            aq.put_nowait(("error",
                           f"engine tick thread crashed: {self.crashed}"))
            return
        key = self.router.affinity_key(payload.prompt_ids)
        loads = [r.inflight for r in self.replicas]
        qd = [r.engine.scheduler.queue_depth for r in self.replicas]
        idx, spilled = self.router.route(
            key, loads=loads, queue_depths=qd, alive=alive,
        )
        # the routing verdict rides the payload into the engine thread:
        # the canonical request log reports route + spill per request
        payload.route_spilled = spilled
        tracer = getattr(self.engine, "tracer", None)
        if tracer is not None:
            # routing decisions are part of the request's trace: the
            # instant carries the SAME trace id the engine spans will
            tracer.instant("route", cat="router", args={
                "rid": rid, "replica": idx, "spilled": spilled,
                "trace": getattr(payload, "trace_id", None),
            })
        if len(self._owner) > 64 + 4 * max(self.inflight, 1):
            self._owner = {
                r: i for r, i in self._owner.items()
                if r in self.replicas[i]._live
            }
        self._owner[rid] = idx
        self.replicas[idx].submit(rid, payload, loop, aq)

    def abort(self, rid: int) -> None:
        idx = self._owner.get(rid)
        if idx is not None:
            self.replicas[idx].abort(rid)
        else:
            for r in self.replicas:
                r.abort(rid)

    def abort_all(self) -> None:
        for r in self.replicas:
            r.abort_all()

    def resume(self, rid: int, last_idx: int, loop: Any, aq: Any) -> None:
        """Route a Last-Event-ID resume to the replica holding the
        stream.  After a process restart the owner map is empty, so an
        unknown rid probes each replica's ledger/parked set (the
        journal segments replayed into their own replicas)."""
        idx = self._owner.get(rid)
        if idx is None or self.replicas[idx].crashed \
                or idx in self._removed:
            idx = next(
                (i for i, r in enumerate(self.replicas)
                 if r.crashed is None and i not in self._removed
                 and (rid in r._inflight or rid in r._resumable
                      or rid in r._claimed)),
                None,
            )
        if idx is None:
            aq.put_nowait(("gone",
                           f"unknown or expired request id {rid}"))
            return
        self._owner[rid] = idx
        self.replicas[idx].resume(rid, last_idx, loop, aq)

    def _drain_dead(self, dead_idx: int, replay: list[dict], *,
                    prefer_version: int | None = None) -> set[int]:
        """A replica went terminally dark: adopt its unterminated
        streams onto live peers — each request re-routes through the
        router AFTER its sticky prefixes are forgotten, so a stream
        lands on the peer its prefix chain re-homes to, is replayed
        teacher-forced there (token-identical), and its bridge entry
        moves so the client never sees more than a pause.  The dead
        replica's journal gets a ``drained`` terminal per adopted
        request, so a later process restart does not replay it twice.
        With ``prefer_version`` set (a mid-roll drain), peers still on
        that weight version are preferred so a stream is served
        end-to-end by one version whenever such a peer exists — same
        rule as the direct-mode ``ReplicaSet._drain_to_peers``.
        Returns the adopted rids (the dead runner abort-flushes the
        rest).  Runs on the dying replica's supervisor thread."""
        dead = self.replicas[dead_idx]
        alive = [i != dead_idx and self._routable(i)
                 for i in range(len(self.replicas))]
        if prefer_version is not None:
            same = [
                ok and getattr(self.replicas[i].engine,
                               "weights_version", 0) == prefer_version
                for i, ok in enumerate(alive)
            ]
            if any(same):
                alive = same
        if not any(alive):
            return set()
        self._dead.add(dead_idx)
        self.router.forget_replica(dead_idx)
        # fleet block shipping (the ReplicaSet._drain_to_peers twin):
        # an upgrade/scale-down drain leaves the source pool intact, so
        # its registered prefix blocks ship through the shared host
        # tier before the prefixes re-home — the adopting peers restore
        # instead of re-prefilling.  A terminal CRASH arrives here with
        # the pool slabs yanked (pages None): nothing ships, exactly
        # the drop-and-recompute the tier-less fleet always had.
        tier = getattr(dead.engine, "host_tier", None)
        if tier is not None:
            dead.engine.spill_prefix_blocks()
            tier.drain()
        dead_journal = getattr(dead.engine, "journal", None)
        adopted: set[int] = set()
        loads = [r.inflight for r in self.replicas]
        qd = [r.engine.scheduler.queue_depth for r in self.replicas]
        tracer = getattr(dead.engine, "tracer", None)
        for rec in replay:
            rid = rec["rid"]
            key = self.router.affinity_key(rec["prompt"])
            idx, _ = self.router.route(key, loads=loads,
                                       queue_depths=qd, alive=alive)
            ent = dead._live.pop(rid, None)
            if ent is not None:
                self.replicas[idx]._live[rid] = ent
            self._owner[rid] = idx
            # the adoption is a survival event: bump the drain counter
            # (it rides the peer's recovery re-admission into its
            # journal, so a later restart still reports it)
            rec = dict(rec, drains=int(rec.get("drains", 0)) + 1)
            if tracer is not None:
                # the LINK instant on the request's track: the merged
                # timeline connects the dead replica's spans to the
                # peer's continuation through the shared trace id
                tracer.request_instant(rid, "drain-to-peer", args={
                    "trace": rec.get("trace"),
                    "from_replica": dead_idx, "to_replica": idx,
                })
            self.replicas[idx]._cmds.put(("recover", rec))
            if dead_journal is not None:
                dead_journal.terminal(rid, "drained")
            loads[idx] += 1
            adopted.add(rid)
        if adopted:
            import sys

            print(f"[serve] replica {dead_idx} terminal: drained "
                  f"{len(adopted)} in-flight streams to live peers",
                  file=sys.stderr)
        return adopted

    # -- fleet lifecycle: rolling upgrade + elastic DP -----------------
    def active_replicas(self) -> int:
        return sum(
            1 for i, r in enumerate(self.replicas)
            if r.crashed is None and i not in self._removed
        )

    def serving_engines(self) -> list:
        """Engines whose ActionPolicy verdicts may govern admission:
        routable replicas only — a removed or crashed replica's tick
        thread can never release a shed flag, so its frozen verdict
        must not shed the fleet forever."""
        return [
            self.replicas[i].engine
            for i in range(len(self.replicas)) if self._routable(i)
        ]

    def rolling_upgrade(self, params_fn: Callable[[], Any], *,
                        version: int | None = None,
                        timeout_s: float = 300.0) -> dict[str, Any]:
        """The HTTP fleet's zero-downtime weight swap (the engine-level
        mechanics live in ``ReplicaSet.rolling_upgrade``'s docstring;
        this is the supervised-runner spelling): per replica — leave
        routing, supersede the tick generation, hand the in-flight
        replay snapshot to live peers through the PR 9 drain path
        (``_drain_dead``: bridge entries move, streams continue
        token-identically, ``drained`` terminals land in this replica's
        journal), rebuild the engine on the new weights on a fresh tick
        thread (``EngineRunner.rebuild_upgraded`` — clone_fresh, steps
        shared once per fleet), wait for its first loop pass, rejoin
        routing.  Serialized by ``_upgrade_lock`` — exactly one roll at
        a time.  Runs OFF the event loop (the ``POST /admin/upgrade``
        handler dispatches it to an executor thread)."""
        from llm_np_cp_tpu.serve.lifecycle import (
            cache_params_fn,
            load_upgrade_params,
        )

        if not self._upgrade_lock.acquire(blocking=False):
            raise RuntimeError("a rolling upgrade is already in progress")
        try:
            order = [i for i in range(len(self.replicas))
                     if self._routable(i)]
            if not order:
                raise RuntimeError("no live replica to upgrade")
            if version is None:
                version = max(
                    getattr(r.engine, "weights_version", 0)
                    for r in self.replicas
                ) + 1
            params_once = cache_params_fn(params_fn)
            rolled: list[int] = []
            shared_src: Any = None
            for idx in order:
                runner = self.replicas[idx]
                params = load_upgrade_params(
                    params_once, replica=idx, faults=runner.faults,
                    metrics=runner.engine.metrics, rolled=rolled,
                    version=version,
                )
                self._lifecycle.add(idx)
                try:
                    old_version = getattr(
                        runner.engine, "weights_version", 0)
                    self.router.forget_replica(idx)
                    replay = runner.detach_inflight()
                    adopted = self._drain_dead(
                        idx, replay, prefer_version=old_version)
                    leftover = [
                        dict(rec, detached_ok=True) for rec in replay
                        if rec["rid"] not in adopted
                    ]
                    runner.rebuild_upgraded(
                        params, version, leftover,
                        share_from=shared_src,
                    )
                    try:
                        runner.await_recovered(timeout_s)
                    except TimeoutError as e:
                        # the rebuild wedged — surface the same clean
                        # abort shape as a checkpoint failure (the
                        # rolled prefix serves on new weights, this
                        # replica's supervisor keeps trying)
                        from llm_np_cp_tpu.serve.lifecycle import (
                            UpgradeAborted,
                        )
                        raise UpgradeAborted(
                            f"replica {idx} rebuild timed out: {e}",
                            rolled=rolled, version=version,
                        ) from e
                finally:
                    self._lifecycle.discard(idx)
                    # _drain_dead marked it dead-and-forgotten; it is
                    # back, and a FUTURE crash must re-forget
                    self._dead.discard(idx)
                if shared_src is None:
                    shared_src = runner.engine
                runner.engine.metrics.on_lifecycle_action(
                    "upgrade_replica")
                rolled.append(idx)
            return {"rolled": rolled, "version": version}
        finally:
            self._upgrade_lock.release()

    def add_replica(self) -> int:
        """Grow the HTTP fleet at runtime: a warmed share-nothing clone
        of a live replica behind its own supervised ``EngineRunner``,
        routed to first-sight.  Returns the new index."""
        src_idx = next(
            (i for i in range(len(self.replicas)) if self._routable(i)),
            None,
        )
        if src_idx is None:
            raise RuntimeError("no live replica to clone from")
        from llm_np_cp_tpu.serve.http.server import EngineRunner

        engine = _fresh_replica_engine(self.replicas[src_idx].engine)
        runner = EngineRunner(engine, **self._supervision)
        idx = len(self.replicas)
        from functools import partial

        runner.on_terminal_crash = partial(self._drain_dead, idx)
        runner.replica_index = idx
        self.replicas.append(runner)
        self.router.grow(len(self.replicas))
        runner.start()
        engine.metrics.on_lifecycle_action("add_replica")
        return idx

    def remove_replica(self, idx: int | None = None) -> int:
        """Shrink the HTTP fleet at runtime — the SIGTERM-style drain:
        the replica leaves routing, its prefixes re-home, its in-flight
        streams are adopted by peers through the drain path (clients
        see a pause, then the peer's token-identical continuation), and
        its runner stops.  The slot stays (stable indices); ``idx``
        defaults to the highest-index active replica."""
        if idx is None:
            idx = max(
                (i for i in range(len(self.replicas))
                 if self._routable(i)), default=-1,
            )
        if idx < 0 or idx >= len(self.replicas) \
                or not self._routable(idx):
            raise ValueError(f"replica {idx} is not an active replica")
        if self.active_replicas() < 2:
            raise RuntimeError(
                "cannot remove the last active replica — scale-down "
                "floor is 1"
            )
        runner = self.replicas[idx]
        # count the action on a SURVIVOR's metrics: render_metrics
        # skips removed replicas, so a counter on the removed engine
        # would vanish from the scrape the moment the action lands
        survivor = next(
            i for i in range(len(self.replicas))
            if i != idx and self._routable(i)
        )
        self.replicas[survivor].engine.metrics.on_lifecycle_action(
            "remove_replica")
        self._removed.add(idx)
        self.router.forget_replica(idx)
        replay = runner.detach_inflight()
        adopted = self._drain_dead(idx, replay)
        # streams no peer adopted (all peers died between the check and
        # the drain): flush them with a clean terminal instead of
        # leaving clients hanging, and terminate them in the journal
        # segment too — otherwise a restart on the same path would
        # replay streams whose clients already saw 'aborted'
        journal = runner.journal
        for rec in replay:
            rid = rec["rid"]
            if rid not in adopted and rid in runner._live:
                runner._push(rid, ("finish", "aborted", None))
                runner._live.pop(rid, None)
                if journal is not None:
                    journal.terminal(rid, "aborted")
        with runner._sup_lock:
            runner.recovering = False
        runner.stop(timeout=10.0)
        return idx

    # -- scrape rendering ----------------------------------------------
    def render_metrics(self, extra_gauges: dict[str, float] | None = None,
                       ) -> str:
        """Fleet Prometheus exposition: every per-replica series carries
        a ``replica`` label (the histograms aggregate across them, which
        is why they are real histograms), HELP/TYPE headers are emitted
        once per family, and the router's verdict counters ride at the
        end."""
        blocks: list[str] = []
        seen_meta: set[str] = set()
        for i, runner in enumerate(self.replicas):
            if i in self._removed:
                # a removed replica's frozen counters would read as a
                # stalled replica on a dashboard; it left on purpose
                continue
            engine = runner.engine
            stats = engine.pool.stats()
            recov = runner.recovery_latency_s
            wv = getattr(engine, "weights_version", 0)
            per_gauges = {
                "weights_version": float(wv),
                "pool_blocks_free": stats["free"],
                "pool_blocks_request_held": stats["request_held"],
                "pool_blocks_cache_only": stats["cache_only"],
                "pool_kv_bytes_shard": stats["kv_bytes_shard"],
                "pool_kv_shards": stats["kv_shards"],
                "inflight_streams": runner.inflight,
                "queue_depth_live": engine.scheduler.queue_depth,
                "restarts_total": runner.restarts,
                "degraded": 1.0 if runner.state != "ok" else 0.0,
                "recovery_latency_s_last": recov[-1] if recov else 0.0,
                "decode_impl_degraded": (
                    1.0 if engine.decode_degraded else 0.0
                ),
            }
            journal = runner.journal
            if journal is not None:
                jstats = journal.stats()
                per_gauges.update({
                    "journal_records_total": float(jstats["records"]),
                    "journal_fsync_p99_s": jstats["fsync_p99_s"],
                    "journal_write_errors_total": float(
                        jstats["write_errors"] + jstats["fsync_errors"]),
                    "journal_epoch": float(jstats["epoch"]),
                })
            const = {"replica": str(i)}
            if wv:
                # the version label appears once a replica has rolled:
                # mid-roll the scrape shows both versions side by side,
                # and pre-upgrade series keep their exact labelsets
                const["version"] = str(wv)
            text = engine.metrics.prometheus(
                extra_gauges=per_gauges,
                const_labels=const,
            )
            ledger = getattr(engine, "tenants", None)
            if ledger is not None:
                # tenant-labeled series carry the same replica/version
                # const labels; the seen_meta dedup below collapses the
                # repeated HELP/TYPE headers across replicas
                text += ledger.prometheus(const_labels=const)
            lines = []
            for line in text.splitlines():
                if line.startswith("#"):
                    if line in seen_meta:
                        continue
                    seen_meta.add(line)
                lines.append(line)
            blocks.append("\n".join(lines))
        router = (
            "# HELP llm_serve_router_routed_total Requests routed to "
            "their prefix-affine replica (first assignments included)\n"
            "# TYPE llm_serve_router_routed_total counter\n"
            f"llm_serve_router_routed_total {self.router.routed}\n"
            "# HELP llm_serve_router_spilled_total Requests spilled off "
            "their affine replica under queue pressure\n"
            "# TYPE llm_serve_router_spilled_total counter\n"
            f"llm_serve_router_spilled_total {self.router.spilled}\n"
            # fleet-level because the injector is process-global (one
            # seeded schedule shared by every replica) — the same series
            # the single-engine scrape exports and the chaos e2e reads
            "# HELP llm_serve_faults_injected_total Chaos faults "
            "injected process-wide\n"
            "# TYPE llm_serve_faults_injected_total gauge\n"
            "llm_serve_faults_injected_total "
            f"{self.faults.injected_total if self.faults is not None else 0.0:g}"
        )
        for key, value in (extra_gauges or {}).items():
            router += (
                f"\n# HELP llm_serve_{key} Live server gauge"
                f"\n# TYPE llm_serve_{key} gauge"
                f"\nllm_serve_{key} {float(value):.10g}"
            )
        blocks.append(router)
        return "\n".join(blocks) + "\n"
