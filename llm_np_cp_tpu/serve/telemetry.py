"""Device roofline telemetry: per-tick bandwidth accounting and
per-request cost attribution.

The serve workload is bandwidth-bound (ROADMAP: the 819 GB/s HBM
roofline is the number left to chase), but the observability plane so
far only measures WALL time — nobody can say what fraction of the
roofline a tick achieved, which is the prerequisite for the operation-
fusion work ("LLM Inference Acceleration via Efficient Operation
Fusion", PAPERS.md) and for telling whether the ragged kernel ("Ragged
Paged Attention") is bandwidth-bound or dispatch-bound on a given
trace.  This module closes that gap with an ANALYTIC byte/FLOP model:

- **weight traffic** — every dispatch streams the decoder stack once
  (layers + final norm + lm_head; the tied lm_head re-reads the
  embedding matrix), plus one embedding row per packed token.
- **KV traffic** — reads from the planned tick composition (the
  per-request generalization of the engine's ``_kv_bytes_tick_mixed``:
  the ragged kernel streams each q tile's visible blocks, window-aware
  per layer, speculative verify lanes included; the XLA fallback
  materializes the padded view), writes one K/V column per packed
  token per layer.  int8 pools count their f32 scale pages.
- **FLOPs** — ``2 * active_params * tokens`` (attention FLOPs are
  second-order at serving context lengths and deliberately left out of
  the estimate — the model is for MFU *trend*, not a FLOP audit).
- **sampling-tail traffic** — the XLA epilogue materializes
  ``[rows, V]`` float32 logits (lm_head write + sampler read-back);
  that rides the weight-bytes term so attribution/conservation follow
  for free.  The FUSED epilogue (``ServeEngine.epilogue_impl ==
  "fused"``) streams lm_head tiles through VMEM and pays ZERO here —
  the model must never bill phantom logits traffic the fused kernel
  retired (``_epilogue_logits_bytes`` is the one rule; the engine's
  kv-bytes gauges keep delegating here, so gauge and model cannot
  drift).

Combined with the measured dispatch→host-sync wall of the SAME tick,
that yields **achieved GB/s**, **roofline utilization** vs
``--hbm-gbps`` (819 by default), and an **MFU estimate** — emitted as
tick args in the trace plane, gauges/histograms on ``/metrics``, and a
``roofline_deficit`` pseudo-phase the ``TickSentinel`` baselines like
any other phase, so a persistent utilization regression pages exactly
like a host_sync one (deficit = measured wall minus the roofline-ideal
wall for the tick's bytes; utilization drops = deficit grows).

**Cost attribution**: each tick's KV bytes are exact per request (the
model is per-row already); weight bytes and device time are amortized
by token share.  The engine accumulates them on ``Request``
(``kv_bytes_read`` / ``kv_bytes_written`` / ``weight_bytes_amortized``
/ ``device_time_s``) and the canonical request log carries them — the
cost basis per-tenant SLOs will bill against (ROADMAP item 2).
Attribution CONSERVES: per-request values sum to the tick totals
(test-pinned), with the one documented exception that the split-path
gather impls read every padded slot — that overhead is split evenly
across the live rows rather than invented onto a phantom request.

CALIBRATION: the byte model is analytic, not measured — on CPU the
absolute GB/s numbers are meaningless (no HBM) and on TPU they assume
perfect overlap of weight and KV streams.  Calibrating against a live
``--jax-profile`` device capture is recorded ROADMAP debt.

ZERO-OVERHEAD WHEN OFF (the FaultInjector discipline, pinned by
tools/lint R4): nothing constructs a ``TelemetryModel`` unless
requested (``--roofline``), every engine hook is a single ``is None``
check, and everything here is host-side Python/NumPy arithmetic —
attaching telemetry adds zero dispatches and zero recompiles (pinned
by the compile-counter telemetry section).

THREAD SAFETY: ``TelemetryModel`` is immutable after construction
(config-derived constants only), so one instance is safely shared
across clone_fresh rebuilds and fleet replicas; all mutable
accumulation lives in ``ServeMetrics`` (under its lock) and on
``Request`` (engine-thread-owned).
"""

from __future__ import annotations

from typing import Any

# The HBM roofline the utilization ratio is computed against, GB/s.
# 819 GB/s is the chip the ROADMAP anchors on (BENCH_TPU_LIVE_r4's
# capture); override per deployment with --hbm-gbps.
HBM_GBPS_DEFAULT = 819.0
# Peak dense bf16 throughput for the MFU estimate, TFLOP/s.
PEAK_TFLOPS_DEFAULT = 197.0


def _leaves(tree: Any):
    """Yield array leaves of a params tree without importing jax (any
    object with .nbytes/.size counts — jax arrays and numpy both do)."""
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _leaves(v)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            yield from _leaves(v)
    elif hasattr(tree, "nbytes"):
        yield tree


def _per_slot_bytes(config: Any, cache_itemsize: int) -> int:
    """K+V bytes one cache slot costs per layer (int8 pools stream
    their f32 scale pages alongside the quantized blocks)."""
    b = config.num_key_value_heads * config.head_dim * cache_itemsize * 2
    if cache_itemsize == 1:  # int8 pool: per-slot f32 scales, K and V
        b += config.num_key_value_heads * 4 * 2
    return b


def mixed_tick_kv_read(
    eng: Any,
    decode_rows: list,
    prefill_segs: list,
    *,
    per_request: bool = True,
) -> tuple[int, dict[int, int]]:
    """K/V bytes one mixed tick's attention reads — total AND per
    request (the per-request generalization of the engine's
    ``_kv_bytes_tick_mixed``; the engine's method delegates here so the
    two can never drift).  A speculating decode row's verify slice
    (``draft_len`` extra q positions) is counted when the caller runs
    the model BEFORE the accept walk resets ``draft_len`` — the
    engine's metrics call (post-walk, draft_len 0) reproduces the
    historical numbers exactly.  ``per_request=False`` skips the
    per-row dict (empty in the result) — the every-tick metrics gauge
    runs telemetry-off too and must not pay an allocation for it."""
    cfg = eng.config
    per_slot = _per_slot_bytes(cfg, eng.cache_dtype.itemsize)
    n_layers = cfg.num_hidden_layers
    qb = eng._q_tile
    per: dict[int, int] = {}
    total = 0
    if eng.ragged_attn_impl != "pallas":
        # the XLA fallback materializes every live token's full padded
        # row view (prefill tiles pad to the q tile)
        s_full = eng.max_seq_len * n_layers * per_slot
        for r in decode_rows:
            b = (1 + r.draft_len) * s_full
            total += b
            if per_request:
                per[r.req_id] = b
        for r, n in prefill_segs:
            b = (-(-n // qb) * qb) * s_full
            total += b
            if per_request:
                per[r.req_id] = b
        return total, per
    win = cfg.sliding_window
    n_sliding = (
        sum(cfg.layer_is_sliding(i) for i in range(n_layers))
        if win is not None else 0
    )
    bs = eng.block_size

    def tile_slots(pad: int, qpos0: int, qlast: int) -> tuple[int, int]:
        full = (qlast // bs - pad // bs + 1) * bs
        if not n_sliding:
            return full, 0
        lo = max(pad, qpos0 - win + 1)
        return full, (qlast // bs - lo // bs + 1) * bs

    def seg_bytes(pad: int, start: int, n: int) -> int:
        slot_layers = 0
        for k in range(-(-n // qb)):
            q0 = start + k * qb
            ql = min(qb, n - k * qb)
            g_full, g_win = tile_slots(pad, q0, q0 + ql - 1)
            slot_layers += (
                (n_layers - n_sliding) * g_full + n_sliding * g_win
            )
        return slot_layers * per_slot

    for r in decode_rows:
        b = seg_bytes(r.pad, r.cache_len - 1, 1 + r.draft_len)
        total += b
        if per_request:
            per[r.req_id] = b
    for r, n in prefill_segs:
        b = seg_bytes(r.pad, r.pad + r.prefill_done, n)
        total += b
        if per_request:
            per[r.req_id] = b
    return total, per


def split_tick_kv_read(
    eng: Any, running: list, *, per_request: bool = True,
) -> tuple[int, dict[int, float]]:
    """K/V bytes one phase-split decode dispatch reads — total and per
    request (the engine's ``_kv_bytes_tick`` delegates here; pass
    ``per_request=False`` to skip the per-row dict for the every-tick
    metrics gauge).  The gather impls materialize the full padded
    [B, S_max] view including DEAD slots; that fixed overhead is split
    evenly across the live rows (attribution must conserve, and there
    is no request to bill padding to).  The paged kernel streams only
    each row's visible blocks, so its attribution is exact."""
    cfg = eng.config
    per_slot = _per_slot_bytes(cfg, eng.cache_dtype.itemsize)
    n_layers = cfg.num_hidden_layers
    if eng.decode_attn_impl != "paged":
        total = (eng.scheduler.max_slots * eng.max_seq_len
                 * n_layers * per_slot)
        if not per_request:
            return total, {}
        share = total / len(running) if running else 0.0
        return total, {r.req_id: share for r in running}
    bs = eng.block_size
    win = cfg.sliding_window
    n_sliding = (
        sum(cfg.layer_is_sliding(i) for i in range(n_layers))
        if win is not None else 0
    )
    per: dict[int, float] = {}
    total_f = 0.0
    for r in running:
        nb_hi = -(-r.cache_len // bs)
        full = (nb_hi - r.pad // bs) * bs
        slot_layers = (n_layers - n_sliding) * full
        if n_sliding:
            pad_eff = max(r.pad, r.cache_len - win)
            slot_layers += n_sliding * (nb_hi - pad_eff // bs) * bs
        b = slot_layers * per_slot
        total_f += b
        if per_request:
            per[r.req_id] = b
    return int(total_f), per


def _epilogue_logits_bytes(eng: Any, sample_rows: int) -> float:
    """HBM traffic of the step's SAMPLING TAIL: the XLA epilogue
    materializes ``[sample_rows, V]`` float32 logits (written by the
    lm_head einsum, read back by the sampler — 8 bytes per pair, every
    slot including inactive ones: the step samples at full static
    width).  The fused epilogue never leaves VMEM with them, so it
    pays zero — billing the difference is exactly what makes the
    fused-vs-unfused roofline delta visible to ``slo_gate
    --min-bandwidth-util``."""
    if getattr(eng, "epilogue_impl", "xla") == "fused":
        return 0.0
    return float(sample_rows * eng.config.vocab_size * 4 * 2)


class TelemetryModel:
    """The analytic cost model, frozen at engine-build time from the
    params tree and config.  Methods take the engine (geometry and
    composition live there); the model itself holds no mutable state,
    so ``clone_fresh`` rebuilds and fleet replicas share one instance.
    """

    def __init__(
        self,
        config: Any,
        params: Any,
        *,
        hbm_gbps: float = HBM_GBPS_DEFAULT,
        peak_tflops: float = PEAK_TFLOPS_DEFAULT,
    ) -> None:
        if hbm_gbps <= 0:
            raise ValueError(f"hbm_gbps must be > 0, got {hbm_gbps}")
        if peak_tflops <= 0:
            raise ValueError(
                f"peak_tflops must be > 0, got {peak_tflops}"
            )
        self.hbm_gbps = float(hbm_gbps)
        self.peak_tflops = float(peak_tflops)
        total_b = total_n = 0
        for leaf in _leaves(params):
            total_b += int(leaf.nbytes)
            total_n += int(leaf.size)
        # the embed entry may itself be a subtree (quantize_params turns
        # it into {"q", "scale"}) — sum its leaves like the total does
        embed = params.get("embed_tokens") if isinstance(params, dict) \
            else None
        embed_b = embed_n = 0
        for leaf in _leaves(embed):
            embed_b += int(leaf.nbytes)
            embed_n += int(leaf.size)
        # bytes every dispatch streams: the decoder stack + final norm
        # (+ the untied lm_head, already a leaf); the embedding table is
        # GATHERED (one row per token), not streamed
        self.stream_bytes = total_b - embed_b
        # a tied lm_head re-reads the full embedding matrix for logits
        tied = bool(getattr(config, "tie_word_embeddings", False))
        self.lm_head_bytes = embed_b if tied else 0
        self.embed_row_bytes = (
            embed_b // max(config.vocab_size, 1) if embed_b else 0
        )
        # parameters that do a multiply-add per token (MFU numerator)
        self.n_flop_params = (total_n - embed_n) + (embed_n if tied else 0)

    # ------------------------------------------------------------------
    def weight_bytes(self, tokens: int, n_dispatches: int = 1) -> int:
        """HBM weight traffic for ``n_dispatches`` forward dispatches
        covering ``tokens`` packed tokens."""
        return (n_dispatches * (self.stream_bytes + self.lm_head_bytes)
                + tokens * self.embed_row_bytes)

    def _cost(self, kind: str, rows: list, kv_read: float,
              n_dispatches: int = 1,
              tail_bytes: float = 0.0) -> dict[str, Any]:
        tokens = sum(t for _, t, _, _ in rows)
        return {
            "kind": kind,
            "tokens": tokens,
            "kv_read_bytes": kv_read,
            "kv_write_bytes": float(sum(w for _, _, _, w in rows)),
            # the sampling tail's logits traffic (zero when fused)
            # rides the weight term: same streamed-per-dispatch shape,
            # and attribution/conservation follow unchanged
            "weight_bytes": float(
                self.weight_bytes(tokens, n_dispatches) + tail_bytes
            ),
            "flops": 2.0 * self.n_flop_params * tokens,
            "rows": rows,
        }

    def mixed_tick_cost(self, eng: Any, decode_rows: list,
                        prefill_segs: list) -> dict[str, Any]:
        """The unified tick's planned byte/FLOP bill.  Must run BEFORE
        the dispatch's accept walk (verify lanes live in ``draft_len``
        only until then)."""
        kv_read, per_read = mixed_tick_kv_read(eng, decode_rows,
                                               prefill_segs)
        wslot = (_per_slot_bytes(eng.config, eng.cache_dtype.itemsize)
                 * eng.config.num_hidden_layers)
        rows = []
        for r in decode_rows:
            t = 1 + r.draft_len
            rows.append((r, t, float(per_read[r.req_id]),
                         float(t * wslot)))
        for r, n in prefill_segs:
            rows.append((r, n, float(per_read[r.req_id]),
                         float(n * wslot)))
        return self._cost(
            "mixed", rows, float(kv_read),
            tail_bytes=_epilogue_logits_bytes(
                eng, eng.scheduler.max_slots * eng._spec_w
            ),
        )

    def split_tick_cost(self, eng: Any, running: list) -> dict[str, Any]:
        """The phase-split decode dispatch's bill (prefill dispatches
        are attributed separately via ``prefill_cost`` — they are
        per-request by construction)."""
        kv_read, per_read = split_tick_kv_read(eng, running)
        wslot = (_per_slot_bytes(eng.config, eng.cache_dtype.itemsize)
                 * eng.config.num_hidden_layers)
        rows = [
            (r, 1, float(per_read[r.req_id]), float(wslot))
            for r in running
        ]
        return self._cost(
            "decode", rows, float(kv_read),
            tail_bytes=_epilogue_logits_bytes(
                eng, eng.scheduler.max_slots
            ),
        )

    # ------------------------------------------------------------------
    def finish(self, cost: dict[str, Any],
               device_time_s: float) -> dict[str, Any]:
        """Combine a planned cost with the measured dispatch→host-sync
        wall of the same tick → the telemetry record the metrics/trace/
        sentinel planes consume."""
        total = (cost["kv_read_bytes"] + cost["kv_write_bytes"]
                 + cost["weight_bytes"])
        dev = max(float(device_time_s), 1e-9)
        achieved_gbps = total / dev / 1e9
        ideal_s = total / (self.hbm_gbps * 1e9)
        return {
            "kind": cost["kind"],
            "roofline": True,
            "tokens": cost["tokens"],
            "device_time_s": float(device_time_s),
            "kv_read_bytes": cost["kv_read_bytes"],
            "kv_write_bytes": cost["kv_write_bytes"],
            "weight_bytes": cost["weight_bytes"],
            "achieved_gbps": achieved_gbps,
            "roofline_util": achieved_gbps / self.hbm_gbps,
            "mfu": cost["flops"] / dev / (self.peak_tflops * 1e12),
            # the sentinel's food: wall past the roofline-ideal wall for
            # this tick's bytes, in µs — utilization drops = deficit
            # grows, so EWMA baselining flags persistent regressions
            "deficit_us": max(dev - ideal_s, 0.0) * 1e6,
            "hbm_gbps": self.hbm_gbps,
        }

    def attribute(self, cost: dict[str, Any],
                  device_time_s: float) -> None:
        """Apportion one tick's bill to its requests: KV bytes exact
        per row, weight bytes and device time by token share.  Sums
        conserve (test-pinned)."""
        total_tokens = cost["tokens"]
        if total_tokens <= 0:
            return
        wb = cost["weight_bytes"]
        for req, t, kv_read, kv_write in cost["rows"]:
            frac = t / total_tokens
            req.kv_bytes_read += kv_read
            req.kv_bytes_written += kv_write
            req.weight_bytes_amortized += wb * frac
            req.device_time_s += device_time_s * frac

    def prefill_cost(self, eng: Any, req: Any,
                     device_time_s: float) -> dict[str, Any]:
        """Split-path prefill attribution: the chunk dispatches are
        per-request already, so their whole bill lands on ``req`` and
        the returned record feeds the metrics TOTALS only
        (``roofline: False`` — a chunk window includes host Python, so
        it must not pollute the per-tick roofline gauges).  The chunk
        attention reads the temp cache, not the pool; that traffic is
        deliberately out of the model (both the request and the totals
        skip it, so conservation holds)."""
        shared_slots = req.n_shared_blocks * eng.block_size
        w = eng._prefill_width(req)
        fresh_tokens = w - shared_slots  # pads embed-gather too
        n_chunks = max(fresh_tokens // eng.prefill_chunk, 0)
        wslot = (_per_slot_bytes(eng.config, eng.cache_dtype.itemsize)
                 * eng.config.num_hidden_layers)
        fresh_slots = (
            (len(req.block_ids) - req.n_shared_blocks) * eng.block_size
        )
        kv_write = float(fresh_slots * wslot)
        weight = float(self.weight_bytes(fresh_tokens,
                                         n_dispatches=n_chunks))
        req.kv_bytes_written += kv_write
        req.weight_bytes_amortized += weight
        req.device_time_s += device_time_s
        return {
            "kind": "prefill",
            "roofline": False,
            "tokens": fresh_tokens,
            "device_time_s": float(device_time_s),
            "kv_read_bytes": 0.0,
            "kv_write_bytes": kv_write,
            "weight_bytes": weight,
            "hbm_gbps": self.hbm_gbps,
        }
