"""Canonical request log: ONE structured JSON line per terminal.

Metrics aggregate, traces sample — neither answers "what exactly
happened to request cmpl-1204?" a week later.  The canonical request
log does: at every terminal (finish / abort / recovered-terminal) the
engine emits one wide-event JSON line carrying everything forensics
needs in one place:

- identity      — ``rid``, the W3C ``trace`` id (the SAME id across
  replicas, restarts, and drains), wall ``ts``, and the normalized
  ``tenant`` (serve/tenants.py; written only when non-default);
- routing       — ``replica``, whether the router ``spilled`` it off
  its prefix-affine replica, and the ``weights_version`` that admitted
  (and serves) the request — ONE version per line, drains included;
- reuse         — prompt length, ``prefix_blocks`` claimed from the
  prefix cache;
- survival      — ``preemptions`` (evict-requeue), ``replays``
  (supervised-restart / journal recoveries), ``drains`` (adoptions by
  a peer after a replica went terminally dark);
- latency       — the per-phase breakdown (``queue_wait_s``,
  ``prefill_s``, ``ttft_s``, ``decode_s``, ``total_s``) from the same
  Request timestamps that feed the trace spans, so log and trace agree
  by construction;
- outcome       — ``reason`` (stop/length/aborted), token counts, and
  the ``slo`` verdict (when a policy is configured);
- cost          — device-cost attribution (serve/telemetry.py, when a
  TelemetryModel is attached): the request's exact KV bytes read/
  written plus its token-share of streamed weight bytes and measured
  device time — the per-tenant cost basis.

WRITER DISCIPLINE (the journal's, machine-checked by tools/lint R3's
``reqlog`` domain): the engine tick thread only ENQUEUES records under
the lock; a dedicated writer thread owns the file handle (``_wlog``)
and does all IO — a slow disk shows up as buffered lines, never as tick
latency.  IO errors are a telemetry degradation, not an outage: the
batch is dropped and counted.

ZERO-OVERHEAD WHEN OFF (tools/lint R4): nothing constructs a
``RequestLog`` unless ``--request-log PATH`` is given, and every engine
hook is a single ``is None`` check.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable


def request_record(
    req: Any,
    *,
    reason: str,
    policy: Any = None,
    clock: Callable[[], float] = time.perf_counter,
) -> dict[str, Any]:
    """Build the canonical wide-event dict for one terminal request.
    Pure (no IO): the engine calls it on the tick thread, tests call it
    directly, and the bench parity check re-derives it from metrics."""
    extra = req.extra
    finish = req.finish_time if req.finish_time is not None else clock()
    rec: dict[str, Any] = {
        "ts": time.time(),
        "rid": req.req_id,
        "trace": extra.get("trace"),
        "reason": reason,
        "replica": int(extra.get("replica", 0)),
        "spilled": bool(extra.get("spilled", False)),
        # the ONE weight version that served this request end-to-end
        # (stamped at admission; drains/replays preserve it)
        "weights_version": int(extra.get("weights_version", 0)),
        "prompt_tokens": req.prompt_len,
        "new_tokens": len(req.generated),
        "prefix_blocks": req.n_shared_blocks,
        "preemptions": req.n_preemptions,
        "replays": int(extra.get("replays", 0)),
        "drains": int(extra.get("drains", 0)),
    }
    tenant = getattr(req, "tenant", "default")
    if tenant != "default":
        # written only when non-default, so single-tenant logs stay
        # byte-stable across the tenancy feature; the id is already
        # normalized (charset-whitelisted) at the protocol boundary
        rec["tenant"] = tenant
    phases: dict[str, float] = {}
    if req.submit_time is not None:
        if req.admit_time is not None:
            phases["queue_wait_s"] = req.admit_time - req.submit_time
        phases["total_s"] = finish - req.submit_time
    if req.prefill_s:
        phases["prefill_s"] = req.prefill_s
    if req.first_token_time is not None:
        if req.submit_time is not None:
            base = extra.get("arrival_wall", req.submit_time)
            phases["ttft_s"] = req.first_token_time - base
        phases["decode_s"] = finish - req.first_token_time
    rec["phases"] = {k: round(v, 6) for k, v in phases.items()}
    if req.device_time_s or req.kv_bytes_read or req.weight_bytes_amortized:
        # device-cost attribution (serve/telemetry.py): the request's
        # exact KV traffic plus its token-share of streamed weights and
        # measured device wall — per-request sums conserve against the
        # metrics ledgers (test-pinned), and per-tenant SLOs bill
        # against these fields (ROADMAP item 2)
        rec["cost"] = {
            "kv_bytes_read": round(req.kv_bytes_read, 1),
            "kv_bytes_written": round(req.kv_bytes_written, 1),
            "weight_bytes_amortized": round(req.weight_bytes_amortized, 1),
            "device_time_s": round(req.device_time_s, 9),
        }
    if policy is not None:
        rec["slo"] = policy.verdict(req).to_dict()
    return rec


class RequestLog:
    """One JSONL file + one writer thread (the journal's ownership
    shape, without framing — lines are self-delimiting and a torn tail
    line is skipped by any JSONL reader).

    Engine-thread API: ``emit(record)`` (enqueue only, no IO).
    Control: ``flush()`` (barrier: everything enqueued before the call
    is on disk), ``close()``, ``stats()``.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        # writer-thread-owned from here on (R3 "reqlog" domain): the
        # file handle and the lines-written counter
        self._wlog = open(path, "a", encoding="utf-8")
        self._wlines = 0
        # shared under _lock: the pending queue and the stats counters
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: list = []
        self._stopping = False
        self.n_records = 0
        self.n_write_errors = 0
        self._thread = threading.Thread(
            target=self._writer_loop, name="serve-request-log-writer",
            daemon=True,
        )
        self._thread.start()

    # -- engine-thread hook (enqueue only, no IO) ----------------------
    def emit(self, record: dict[str, Any]) -> None:
        with self._lock:
            if self._stopping:
                return
            self._pending.append(record)
            self._cond.notify()

    # -- control -------------------------------------------------------
    def flush(self, timeout: float = 10.0) -> bool:
        ev = threading.Event()
        with self._lock:
            if self._stopping and self._thread.is_alive() is False:
                return True
            self._pending.append(("flush", ev))
            self._cond.notify()
        return ev.wait(timeout)

    def close(self, timeout: float = 10.0) -> None:
        with self._lock:
            if self._stopping:
                return
            self._stopping = True
            self._cond.notify()
        self._thread.join(timeout)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "records": self.n_records,
                "write_errors": self.n_write_errors,
            }

    # -- writer thread (R3 "reqlog" domain) ----------------------------
    def _writer_loop(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._stopping:
                    self._cond.wait(0.5)
                batch, self._pending = self._pending, []
                stopping = self._stopping
            if batch:
                self._writer_batch(batch)
            if stopping:
                with self._lock:
                    leftover, self._pending = self._pending, []
                if leftover:
                    self._writer_batch(leftover)
                try:
                    self._wlog.close()
                except OSError:
                    pass
                return

    def _writer_batch(self, batch: list) -> None:
        recs = [b for b in batch if isinstance(b, dict)]
        barriers = [b[1] for b in batch if not isinstance(b, dict)]
        if recs:
            try:
                for rec in recs:
                    self._wlog.write(
                        json.dumps(rec, separators=(",", ":"),
                                   sort_keys=True) + "\n"
                    )
                self._wlog.flush()
            except (OSError, TypeError, ValueError):
                # telemetry degradation, never an outage: drop + count
                with self._lock:
                    self.n_write_errors += 1
            else:
                self._wlines += len(recs)
                with self._lock:
                    self.n_records += len(recs)
        for ev in barriers:
            ev.set()


def read_request_log(path: str) -> list[dict[str, Any]]:
    """Parse a request-log file, skipping a torn tail line (the writer
    appends whole lines, so only the last can be partial)."""
    out: list[dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue  # torn tail
    except FileNotFoundError:
        pass
    return out
