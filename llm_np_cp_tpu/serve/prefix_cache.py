"""Refcounted prompt-prefix sharing over the paged KV pool.

Block tables already make a shared block *representable* — two requests
whose tables name the same pool block attend to the same K/V.  What
makes it *correct* is that a cache slot's K/V depends only on that
slot's token id and its RoPE position (``j - pad``): attention mixes
values at read time, never at write time.  So two rows laid out as
``[pad zero-slots][tokens...]`` with the same pad and the same leading
tokens have bit-identical K/V in their leading full blocks, and those
blocks can be shared outright — no copy-on-write machinery is needed
because the engine only ever shares FULL prompt blocks and every
subsequent write (decode appends, suffix prefill scatter) lands strictly
past them.

Lifecycle (all host-side, between device steps, like the free list):

- after a request's prefill, its fully-filled prompt blocks are
  *registered* under chained content keys; the cache takes one reference
  of its own per block, so the block outlives the request.
- at admission, the scheduler asks the engine for a *prefill plan*; a
  chain match claims the shared blocks (one reference per requester) and
  the engine skips the prefill chunks they cover entirely.
- ``FreeList.free`` is a decref: a block returns to the free list only
  when the last reference drops.  Blocks whose only reference is the
  cache's own are *reclaimable*: ``BlockPool.alloc`` evicts them LRU
  when the free list alone cannot satisfy a request, and
  ``BlockPool.num_free`` counts them as available — shared blocks never
  double-count against pool capacity.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict

import numpy as np


def prefix_block_keys(
    tokens: np.ndarray, pad: int, block_size: int, n_blocks: int
) -> list[bytes]:
    """Chained content keys for the first ``n_blocks`` FULL blocks of a
    row laid out as ``[pad zero-slots][tokens...]``.

    Key ``i`` commits to ``(pad, block_size, tokens of blocks 0..i)``, so
    a match at depth ``i`` implies the whole prefix matches — sharing is
    prefix-only by construction and collisions across layouts are
    impossible.  ``pad`` is folded into the seed because slot positions
    (``j - pad``) shift the entire row's K/V.
    """
    content = np.ascontiguousarray(np.asarray(tokens, dtype=np.int32))
    h = hashlib.sha256(f"pad={pad};bs={block_size};".encode())
    keys: list[bytes] = []
    for i in range(n_blocks):
        if (i + 1) * block_size - pad > content.size:
            break  # partial block — never shareable
        # clamp BOTH bounds to >= 0: a block living entirely inside the
        # pad region hashes no tokens (its K/V is position-only), and a
        # negative hi would wrap the slice around to the prompt TAIL,
        # silently defeating prefix matching whenever pad > block_size
        lo = max(i * block_size - pad, 0)
        hi = max((i + 1) * block_size - pad, 0)
        h.update(content[lo:hi].tobytes())
        keys.append(h.digest())
    return keys


class PrefixCache:
    """key → pool block id registry with LRU reclaim.

    ``free_list`` is the owning allocator (FreeList interface with
    refcounts); every registered block carries ONE reference held by the
    cache itself, dropped when the entry is reclaimed or cleared.
    """

    def __init__(self, free_list) -> None:
        self.free_list = free_list
        # LRU order: oldest entry first (move_to_end on hit)
        self._entries: OrderedDict[bytes, int] = OrderedDict()
        self._key_by_block: dict[int, bytes] = {}
        # on_reclaim(key, block_id): called for each entry ``release``
        # is about to drop, BEFORE its block returns to the free list —
        # the engine counts the eviction (reclaim used to be silent)
        # and, with the host tier attached, spills the block's K/V so
        # drop becomes spill (serve/host_tier.py).  None = reclaim
        # stays a pure free, zero overhead.
        self.on_reclaim = None

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def n_reclaimable(self) -> int:
        """Registered blocks whose ONLY reference is the cache's own —
        freeable on demand, so admission control may count them."""
        return sum(
            1 for blk in self._entries.values()
            if self.free_list.refcount(blk) == 1
        )

    # ------------------------------------------------------------------
    def match(self, keys: list[bytes]) -> list[int]:
        """Longest registered prefix of ``keys`` → block ids.  Pure
        lookup: no references move, no LRU touch."""
        out: list[int] = []
        for key in keys:
            blk = self._entries.get(key)
            if blk is None:
                break
            out.append(blk)
        return out

    def claim(self, keys: list[bytes]) -> list[int]:
        """Take one reference per matched block (the requester's) and
        LRU-touch the entries.  Callers pass keys already truncated to
        the prefix they can actually use; the claim stops at the first
        miss like ``match``."""
        ids = self.match(keys)
        if ids:
            self.free_list.incref(ids)
            for key in keys[: len(ids)]:
                self._entries.move_to_end(key)
        return ids

    def register(self, keys: list[bytes], block_ids: list[int]) -> int:
        """Insert ``key → block`` pairs after a prefill; the cache takes
        its own reference per NEW entry.  Keys already present are only
        LRU-touched (the registered twin stays canonical — the caller's
        block for that key IS the registered one on a claim hit).
        Returns the number of new entries."""
        added = 0
        for key, blk in zip(keys, block_ids):
            if key in self._entries:
                self._entries.move_to_end(key)
                continue
            if blk in self._key_by_block:
                continue  # block already registered under another chain
            self.free_list.incref([blk])
            self._entries[key] = blk
            self._key_by_block[blk] = key
            added += 1
        return added

    # ------------------------------------------------------------------
    def release(self, n: int) -> int:
        """Drop up to ``n`` LRU entries whose block is cache-only
        (refcount 1), returning those blocks to the free list.  Entries
        still referenced by live requests are skipped — eviction can
        NEVER free a block a running request's table points at."""
        freed = 0
        if n <= 0:
            return 0
        for key in list(self._entries):
            blk = self._entries[key]
            if self.free_list.refcount(blk) != 1:
                continue
            if self.on_reclaim is not None:
                # observe (and possibly spill) the block BEFORE the id
                # frees — once on the free list it may be rewritten by
                # the very allocation that triggered this reclaim
                self.on_reclaim(key, blk)
            del self._entries[key]
            del self._key_by_block[blk]
            self.free_list.free([blk])
            freed += 1
            if freed >= n:
                break
        return freed

    def items(self) -> list[tuple[bytes, int]]:
        """Snapshot of the registered ``(key, block id)`` pairs, LRU
        order (oldest first) — what the fleet's block-shipping paths
        iterate to spill a replica's whole prefix set before its
        prefixes re-home (serve/replica.py)."""
        return list(self._entries.items())

    def clear(self) -> None:
        """Drop every entry and the cache's references (blocks still
        referenced by live requests stay allocated for them)."""
        for blk in self._entries.values():
            self.free_list.free([blk])
        self._entries.clear()
        self._key_by_block.clear()
