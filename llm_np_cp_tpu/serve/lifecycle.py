"""Fleet lifecycle: rolling weight swaps, elastic replicas, auto-actions.

PRs 4/9/10 made a *single* replica survive crashes and ``kill -9``; this
module makes the *fleet* survive operators.  Every primitive already
exists — journal replay, drain-to-peer, ``clone_fresh``, router re-home,
SLO burn rates, the tick sentinel — and this layer is the orchestration
on top of them:

- **Rolling checkpoint upgrade** (``ReplicaSet.rolling_upgrade`` /
  ``ReplicaRunner.rolling_upgrade`` in serve/replica.py, the HTTP
  surface at ``POST /admin/upgrade``): drain one replica at a time to
  its peers, rebuild it on fresh weights via ``clone_fresh(params=...)``
  with the compiled steps re-jitted once per FLEET and shared across
  rolled replicas, and tag every request with the weight version it was
  admitted under — journal admission records and request-log lines
  carry ``weights_version``, so a stream that survives a mid-roll drain
  still reports ONE version end to end.
- **Elastic data parallelism** (``ReplicaSet.add_replica`` /
  ``remove_replica``): grow the fleet with a warmed clone that shares
  the compiled steps (the router starts routing to it first-sight),
  shrink it with a SIGTERM-style drain-to-peer plus router forget.  The
  optional ``Autoscaler`` policy here drives both from queue depth and
  the 5m SLO burn rate.
- **Sentinel auto-actions** (``ActionPolicy``): the closed loop from
  the PR 10 observability plane's signals to admission-side actions — a
  persistent ``host_sync`` regression (named by the ``TickSentinel``)
  sheds prefill budget in ``plan_tick``; an SLO error-budget burn rate
  past threshold flips admission to 503-first load shedding with
  ``Retry-After`` derived from the burn.  Both actions are reversible
  (they release when the signal clears), rate-limited, and observable
  (``llm_serve_lifecycle_actions_total{action=}`` counters + trace
  instants), and nothing constructs a policy unless ``--auto-actions``
  is given.

THREADING: ``ActionPolicy`` is fed from the engine tick thread
(``ServeEngine._actions_tick``) and read by the HTTP event loop (the
503 shedding check, the scrape) — its verdict state and counters are
lock-grouped under ``_lock`` (machine-checked by tools/lint R3).
``LifecycleController`` roll state (``_roll_active``/``_roll_history``)
is owned by the lifecycle domain: only controller methods mutate it.

ZERO-OVERHEAD WHEN OFF (tools/lint R4): ``ServeEngine.actions`` is
``None`` unless requested, and every engine/HTTP hook on it is a single
``is None`` check.
"""

from __future__ import annotations

import threading
import time
from collections import Counter
from typing import Any, Callable


class UpgradeAborted(RuntimeError):
    """A rolling upgrade stopped mid-roll (checkpoint read failed, or a
    loader raised).  The roll aborts CLEANLY: the replica being rolled
    was not yet drained, so it stays live on its old weights and the
    fleet never drops below N-1 capacity.  ``rolled`` names the
    replicas that already completed their swap (they stay on the new
    weights — a half-rolled fleet is mixed-version but fully serving,
    and the version tag on every request says which weights served
    it)."""

    def __init__(self, reason: str, *, rolled: list[int] | None = None,
                 version: int | None = None) -> None:
        super().__init__(reason)
        self.rolled = list(rolled or ())
        self.version = version


def load_upgrade_params(params_fn: Callable[[], Any], *, replica: int,
                        faults: Any = None, metrics: Any = None,
                        rolled: Any = (),
                        version: int | None = None) -> Any:
    """One replica's checkpoint read for a rolling upgrade: trip the
    ``upgrade_ckpt`` chaos site, then call the loader, converting any
    failure into a clean ``UpgradeAborted`` (the replica being rolled
    was not yet drained — it stays live on its old weights).  The ONE
    abort preamble shared by ReplicaSet/ReplicaRunner/EngineRunner
    rolls, so abort semantics cannot drift between them."""
    if faults is not None and faults.trip("upgrade_ckpt") is not None:
        if metrics is not None:
            metrics.on_lifecycle_action("upgrade_aborted")
        raise UpgradeAborted(
            f"chaos: injected checkpoint read failure rolling replica "
            f"{replica}", rolled=list(rolled), version=version,
        )
    try:
        return params_fn()
    except Exception as e:  # noqa: BLE001 — abort cleanly, stay serving
        if metrics is not None:
            metrics.on_lifecycle_action("upgrade_aborted")
        raise UpgradeAborted(
            f"checkpoint load failed rolling replica {replica}: {e}",
            rolled=list(rolled), version=version,
        ) from e


def cache_params_fn(params_fn: Callable[[], Any]) -> Callable[[], Any]:
    """Load the checkpoint ONCE per roll, not once per replica: the
    in-process replicas share one host, so an N-replica roll must not
    pay N full checkpoint reads for the same weights.  (The per-replica
    ``upgrade_ckpt`` chaos trip in ``load_upgrade_params`` is
    independent of this cache, so mid-roll read-failure drills still
    abort at the replica they target.)"""
    loaded: list = []

    def once() -> Any:
        if not loaded:
            loaded.append(params_fn())
        return loaded[0]

    return once


class ActionPolicy:
    """Closed-loop auto-actions from the sentinel/SLO signal plane.

    Two independent reversible actions, both rate-limited by
    ``min_flip_interval_s`` per action:

    - ``shed_prefill`` — engaged after ``engage_streak`` ticks where
      the tick sentinel named ``anomaly_phase`` (default ``host_sync``)
      an outlier within the current run of anomalous ticks; released
      after ``release_clean`` consecutive anomaly-free ticks.  While
      engaged, ``plan_budget`` shrinks the unified tick's prefill slack
      by ``shed_frac`` (decode rows are NEVER shed — the floor is
      ``max_slots``), trading admission latency for tick cadence while
      the host is struggling.
    - ``shed_load`` — engaged when the SLO error-budget burn rate over
      ``burn_window`` exceeds ``burn_threshold``; released once burn
      falls under ``burn_threshold * burn_clear_frac`` (hysteresis, so
      a burn hovering at the threshold does not flap).  While engaged
      the HTTP front-end answers NEW completions 503-first with
      ``Retry-After`` scaled from the burn (``retry_after()``), the
      standard load-shedding move: shed early at admission rather than
      miss every in-flight deadline.

    Engine-thread hook: ``on_tick(outliers, slo_tracker)`` once per
    tick (``ServeEngine._actions_tick``); returns the action flips this
    tick for the caller to count + trace.  Cross-thread reads
    (``shedding``/``retry_after``/``snapshot``) take the same lock.
    """

    def __init__(
        self,
        *,
        burn_threshold: float = 2.0,
        burn_window: str = "5m",
        burn_clear_frac: float = 0.5,
        anomaly_phase: str = "host_sync",
        engage_streak: int = 4,
        release_clean: int = 64,
        shed_frac: float = 0.5,
        min_flip_interval_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if burn_threshold <= 0:
            raise ValueError(
                f"burn_threshold must be > 0, got {burn_threshold}"
            )
        if not (0.0 < burn_clear_frac <= 1.0):
            raise ValueError(
                f"burn_clear_frac must be in (0, 1], got {burn_clear_frac}"
            )
        if engage_streak < 1 or release_clean < 1:
            raise ValueError(
                f"engage_streak/release_clean must be >= 1, got "
                f"{engage_streak}/{release_clean}"
            )
        if not (0.0 < shed_frac <= 1.0):
            raise ValueError(
                f"shed_frac must be in (0, 1], got {shed_frac}"
            )
        self.burn_threshold = burn_threshold
        self.burn_window = burn_window
        self.burn_clear_frac = burn_clear_frac
        self.anomaly_phase = anomaly_phase
        self.engage_streak = engage_streak
        self.release_clean = release_clean
        self.shed_frac = shed_frac
        self.min_flip_interval_s = min_flip_interval_s
        self.clock = clock
        self._lock = threading.Lock()
        # verdict state + counters (lock-grouped, tools/lint R3): the
        # engine tick thread writes, the HTTP loop reads
        self.shed_prefill = False
        self.shed_load = False
        self.retry_after_s = 1.0
        self.last_burn = 0.0
        self.actions_total: Counter[str] = Counter()
        self._anom_streak = 0
        self._clean_ticks = 0
        self._last_flip: dict[str, float] = {}

    def spawn(self) -> "ActionPolicy":
        """A fresh policy with the same thresholds — what a NEW elastic
        replica gets (verdict state is per-engine, never shared across
        tick threads)."""
        return ActionPolicy(
            burn_threshold=self.burn_threshold,
            burn_window=self.burn_window,
            burn_clear_frac=self.burn_clear_frac,
            anomaly_phase=self.anomaly_phase,
            engage_streak=self.engage_streak,
            release_clean=self.release_clean,
            shed_frac=self.shed_frac,
            min_flip_interval_s=self.min_flip_interval_s,
            clock=self.clock,
        )

    # -- engine-thread hook --------------------------------------------
    def _can_flip(self, action: str, now: float) -> bool:
        # caller holds the lock.  Rate limit per action: a noisy signal
        # at the threshold cannot flap the action faster than
        # min_flip_interval_s
        last = self._last_flip.get(action)
        return last is None or now - last >= self.min_flip_interval_s

    def on_tick(self, outliers: list[dict], slo: Any) -> list[str]:
        """Fold one tick's signals in; returns the action flips (e.g.
        ``["shed_prefill_on"]``) for the engine to count + trace."""
        now = self.clock()
        anom = any(o.get("phase") == self.anomaly_phase for o in outliers)
        burn = (
            slo.burn_rate(self.burn_window) if slo is not None else 0.0
        )
        flipped: list[str] = []
        with self._lock:
            self.last_burn = burn
            if anom:
                self._anom_streak += 1
                self._clean_ticks = 0
            else:
                self._clean_ticks += 1
                if self._clean_ticks >= self.release_clean:
                    self._anom_streak = 0
            if (
                not self.shed_prefill
                and self._anom_streak >= self.engage_streak
                and self._can_flip("shed_prefill", now)
            ):
                self.shed_prefill = True
                self._last_flip["shed_prefill"] = now
                self.actions_total["shed_prefill_on"] += 1
                flipped.append("shed_prefill_on")
            elif (
                self.shed_prefill
                and self._clean_ticks >= self.release_clean
                and self._can_flip("shed_prefill", now)
            ):
                self.shed_prefill = False
                self._last_flip["shed_prefill"] = now
                self.actions_total["shed_prefill_off"] += 1
                flipped.append("shed_prefill_off")
            if (
                not self.shed_load
                and burn > self.burn_threshold
                and self._can_flip("shed_load", now)
            ):
                self.shed_load = True
                self._last_flip["shed_load"] = now
                self.actions_total["shed_load_on"] += 1
                flipped.append("shed_load_on")
            elif (
                self.shed_load
                and burn <= self.burn_threshold * self.burn_clear_frac
                and self._can_flip("shed_load", now)
            ):
                self.shed_load = False
                self._last_flip["shed_load"] = now
                self.actions_total["shed_load_off"] += 1
                flipped.append("shed_load_off")
            if self.shed_load:
                # Retry-After from the burn magnitude: the hotter the
                # burn, the longer clients should back off (bounded —
                # a 503 storm must stay retryable)
                self.retry_after_s = float(
                    min(30, max(1, round(burn / self.burn_threshold)))
                )
        return flipped

    def plan_budget(self, budget: int, floor: int) -> int:
        """The shed-prefill verdict applied to the unified tick's token
        budget: decode rows (``floor`` = max_slots) are never shed —
        only the prefill slack above them shrinks by ``shed_frac``."""
        with self._lock:
            if not self.shed_prefill:
                return budget
        return max(
            floor, floor + int((budget - floor) * (1.0 - self.shed_frac))
        )

    # -- cross-thread reads --------------------------------------------
    @property
    def shedding(self) -> bool:
        with self._lock:
            return self.shed_load

    def retry_after(self) -> float:
        with self._lock:
            return self.retry_after_s

    def state_args(self) -> dict[str, Any]:
        """Trace-instant args: the verdict state at a flip."""
        with self._lock:
            return {
                "shed_prefill": self.shed_prefill,
                "shed_load": self.shed_load,
                "burn": round(self.last_burn, 3),
                "retry_after_s": self.retry_after_s,
            }

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "shed_prefill": self.shed_prefill,
                "shed_load": self.shed_load,
                "burn": round(self.last_burn, 4),
                "retry_after_s": self.retry_after_s,
                "actions_total": dict(self.actions_total),
            }


class Autoscaler:
    """Elastic-DP policy: queue depth + burn rate → replica count.

    Pure verdicts (no fleet mutation — ``LifecycleController`` applies
    them): ``verdict()`` returns +1 (add a replica), -1 (drain one
    away), or 0, with a ``cooldown_s`` gap between verdicts so a scale
    action's effect is observed before the next one fires.  Scale-up
    triggers on EITHER signal (deep queues mean latency is already
    lost; a hot burn means the SLO is already missing); scale-down
    needs BOTH quiet (shallow queues AND burn well under the scale-up
    threshold) — growing is cheap, shrinking under pressure is not.
    """

    def __init__(
        self,
        *,
        min_replicas: int = 1,
        max_replicas: int = 8,
        scale_up_queue_depth: float = 4.0,
        scale_up_burn: float = 2.0,
        scale_down_queue_depth: float = 0.5,
        cooldown_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if not (1 <= min_replicas <= max_replicas):
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}/{max_replicas}"
            )
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.scale_up_queue_depth = scale_up_queue_depth
        self.scale_up_burn = scale_up_burn
        self.scale_down_queue_depth = scale_down_queue_depth
        self.cooldown_s = cooldown_s
        self.clock = clock
        self._last_verdict_t: float | None = None

    def verdict(self, *, n_replicas: int, queue_depth_per_replica: float,
                burn_5m: float = 0.0) -> int:
        now = self.clock()
        if (
            self._last_verdict_t is not None
            and now - self._last_verdict_t < self.cooldown_s
        ):
            return 0
        if n_replicas < self.max_replicas and (
            queue_depth_per_replica >= self.scale_up_queue_depth
            or burn_5m > self.scale_up_burn
        ):
            self._last_verdict_t = now
            return 1
        if (
            n_replicas > self.min_replicas
            and queue_depth_per_replica <= self.scale_down_queue_depth
            and burn_5m < 0.5 * self.scale_up_burn
        ):
            self._last_verdict_t = now
            return -1
        return 0


class LifecycleController:
    """Direct-mode lifecycle driver over a ``ReplicaSet``: serializes
    rolling upgrades (one roll at a time — two concurrent rolls would
    drain the same peers out from under each other) and applies the
    ``Autoscaler``'s verdicts.  The HTTP fleet's equivalent lives in
    ``HttpServer`` (``POST /admin/upgrade`` / ``POST /admin/scale``),
    which serializes through its own lock.

    ``_roll_active``/``_roll_history`` are lifecycle-domain-owned
    (tools/lint R3): only controller methods mutate them.
    """

    def __init__(self, fleet: Any, *, autoscaler: Autoscaler | None = None,
                 ) -> None:
        self.fleet = fleet
        self.autoscaler = autoscaler
        self._roll_active = False
        self._roll_history: list[dict[str, Any]] = []

    @property
    def roll_active(self) -> bool:
        return self._roll_active

    @property
    def roll_history(self) -> list[dict[str, Any]]:
        return list(self._roll_history)

    def rolling_upgrade(self, params_fn: Callable[[], Any], *,
                        version: int | None = None,
                        steps_between: int = 1) -> dict[str, Any]:
        if self._roll_active:
            raise RuntimeError("a rolling upgrade is already in progress")
        self._roll_active = True
        try:
            out = self.fleet.rolling_upgrade(
                params_fn, version=version, steps_between=steps_between,
            )
            self._roll_history.append(out)
            return out
        finally:
            self._roll_active = False

    def autoscale_tick(self) -> int:
        """Evaluate the autoscaler against the fleet's live signals and
        apply its verdict.  Returns the verdict (+1/-1/0).  Call it
        from whatever cadence drives the fleet (the bench/test loop, or
        an operator cron) — it is cheap enough for every tick."""
        if self.autoscaler is None:
            return 0
        fleet = self.fleet
        alive = [i for i, a in enumerate(fleet.alive) if a]
        if not alive:
            return 0
        depth = sum(
            fleet.engines[i].scheduler.queue_depth for i in alive
        ) / len(alive)
        from llm_np_cp_tpu.serve.slo import aggregate_slo

        agg = aggregate_slo([
            getattr(fleet.engines[i].metrics, "slo", None) for i in alive
        ])
        burn = float(agg.get("slo_burn_rate_5m", 0.0))
        v = self.autoscaler.verdict(
            n_replicas=len(alive), queue_depth_per_replica=depth,
            burn_5m=burn,
        )
        if v > 0:
            self.fleet.add_replica()
        elif v < 0:
            # drain the least-loaded live replica — fewest streams to
            # move to peers
            idx = min(alive, key=lambda i: len(fleet.engines[i]._requests))
            self.fleet.remove_replica(idx)
        return v
