"""Continuous-batching scheduler: requests → decode slots + blocks.

Static batching (``Generator.generate_many``) holds a whole batch until
its slowest row finishes; the chip idles on every early-EOS row.  Here
the schedulable unit is one request and one decode tick: queued requests
are admitted into free decode slots as soon as the block pool can hold
their prefill (join-on-prefill), and a finished request's slot + blocks
are reusable at the very next tick.

Policies (deliberately boring — the interesting state is in the pool):
- **Admission**: strict FIFO.  The head of the queue is admitted when a
  decode slot is free AND the pool can allocate its prefill blocks while
  keeping ``decode_reserve`` blocks spare (so a fresh admission cannot
  instantly OOM the running set).  No queue-jumping → no starvation.
- **Backpressure**: an optional ``max_queue`` depth cap — ``add`` raises
  ``QueueFull`` instead of growing the queue without bound (the HTTP
  front-end maps it to 429 + Retry-After).  Preemption requeues are
  EXEMPT: they re-enter at the front and were already admitted once, so
  the cap can never deadlock the running set.
- **Abort**: a request can be cancelled in any live state.  Queued
  requests just leave the queue (they hold no blocks); running requests
  release their slot and decref their blocks — shared prefix blocks
  survive for their other holders exactly as on finish/eviction.
- **Growth**: before each decode tick every running request whose next
  token would overflow its allocated blocks gets one more block.
- **Eviction**: if that allocation fails, the *youngest* running request
  (most recent admission) is preempted: its block references drop (a
  block returns to the pool only when its LAST sharer lets go — prefix
  blocks shared with other requests survive) and it is requeued at the
  FRONT of the queue with its generated tokens kept.  On readmission it re-prefills prompt+generated (teacher-forced)
  and continues — with a deterministic sampler this reproduces the
  uninterrupted output exactly (pinned in tests).  Preempting youngest +
  requeue-at-front preserves FIFO completion order, so no request
  starves.

Pure Python/NumPy over the ``FreeList`` accounting interface — no jax —
so scheduling policies are simulatable and testable without a model
(tests/test_serve_scheduler.py drives thousands of ticks in
milliseconds).
"""

from __future__ import annotations

import dataclasses
import enum
from collections import deque
from typing import Any, Callable

import numpy as np


class RequestState(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"
    ABORTED = "aborted"


class QueueFull(RuntimeError):
    """Admission rejected: the scheduler's queue-depth cap is reached.

    Deliberately NOT a ValueError — callers must be able to tell "this
    request can never run" (ValueError at submit) apart from "try again
    later" (this), because only the latter maps to HTTP 429."""

    def __init__(self, depth: int, cap: int) -> None:
        super().__init__(
            f"scheduler queue is full ({depth} waiting, cap {cap})"
        )
        self.depth = depth
        self.cap = cap


class TenantThrottled(QueueFull):
    """Admission rejected by the per-tenant in-flight cap
    (``--tenant-max-inflight``).  A ``QueueFull`` subclass so every
    existing "try again later" handler (HTTP 429 + Retry-After) applies
    unchanged; carries the tenant for the throttle counter and trace
    instant."""

    def __init__(self, tenant: str, inflight: int, cap: int) -> None:
        # bypass QueueFull.__init__: the message names the TENANT's
        # live count, not the queue depth
        RuntimeError.__init__(
            self,
            f"tenant {tenant!r} is at its in-flight cap "
            f"({inflight} live, cap {cap})"
        )
        self.tenant = tenant
        self.depth = inflight
        self.cap = cap


@dataclasses.dataclass
class Request:
    """One generation request and its serving-side bookkeeping."""

    req_id: int
    prompt: np.ndarray  # [P] int32
    max_new_tokens: int
    arrival_time: float = 0.0
    seed: int = 0
    # callback(request, token_id, text_delta_or_None) per generated token
    callback: Callable[["Request", int, str | None], None] | None = None

    # -- scheduler/engine state ---------------------------------------
    state: RequestState = RequestState.QUEUED
    # terminal outcome: "stop" | "length" | "aborted" (None while live);
    # the SAME vocabulary flows through engine events, the metrics
    # snapshot, and the HTTP ``finish_reason`` field
    finish_reason: str | None = None
    # absolute deadline on the engine clock; the engine aborts past it
    deadline: float | None = None
    # on_event(request, event) — terminal events ("stop"/"length"/
    # "aborted") plus the non-terminal "evicted-requeued" preemption
    # notice; token-level streaming stays on ``callback``
    on_event: Callable[["Request", str], None] | None = None
    generated: list[int] = dataclasses.field(default_factory=list)
    block_ids: list[int] = dataclasses.field(default_factory=list)
    # leading entries of block_ids claimed from the prefix cache (their
    # K/V is already in the pool; the engine skips those prefill chunks)
    n_shared_blocks: int = 0
    pad: int = 0  # left-pad slots in this request's cache region
    # -- unified-tick (mixed_step) prefill progress -------------------
    # content tokens whose K/V is already in the pool this admission
    # (prefix-cache hits pre-seed it — covered content never consumes
    # tick budget), the content length this admission must reach, and
    # the completion flag the planner keys on.  The phase-split engine
    # leaves these untouched; a preemption resets them with pad.
    prefill_done: int = 0
    prefill_target: int = 0
    prefilled: bool = False
    # -- speculative decoding (unified tick only) ---------------------
    # opt-in flag (per-request `"speculative": true` over HTTP); the
    # engine only drafts for it when built with spec_k > 0
    speculative: bool = False
    # -- multi-tenancy (serve/tenants.py) -----------------------------
    # normalized tenant id (X-Tenant-Id header / "tenant" body field;
    # absent → "default"), carried through journal replay, drain, and
    # every observability surface
    tenant: str = "default"
    # draft tokens packed for THIS tick's verify lane (set by the
    # engine's draft pass, trimmed by plan_tick's budget, consumed by
    # the accept walk; always 0 between ticks).  Growth covers
    # cache_len + draft_len so every verify write has a block.
    draft_len: int = 0
    slot: int = -1  # decode slot while RUNNING
    n_preemptions: int = 0
    # -- device-cost attribution (serve/telemetry.py) -----------------
    # cumulative over the request's lifetime (preemption re-prefills
    # keep adding — the cost was really paid): exact KV bytes its
    # attention read / its tokens wrote, plus its token-share of each
    # tick's streamed weight bytes and measured device wall.  Zero
    # unless a TelemetryModel is attached; the canonical request log
    # carries them (the per-tenant cost basis, ROADMAP item 2).
    kv_bytes_read: float = 0.0
    kv_bytes_written: float = 0.0
    weight_bytes_amortized: float = 0.0
    device_time_s: float = 0.0
    # -- metrics timestamps -------------------------------------------
    submit_time: float | None = None
    # first admission into a decode slot (queue_wait_s = admit_time -
    # submit_time; preemption requeues keep the FIRST admission — the
    # user-visible wait ended when work first started)
    admit_time: float | None = None
    # cumulative wall time spent in prefill dispatch for this request
    # (re-prefills after preemption/recovery add to it)
    prefill_s: float = 0.0
    first_token_time: float | None = None
    finish_time: float | None = None
    extra: dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def total_len(self) -> int:
        """Prompt + generated tokens (the sequence content length)."""
        return self.prompt_len + len(self.generated)

    @property
    def cache_len(self) -> int:
        """Cache slots used: left pads + content."""
        return self.pad + self.total_len

    @property
    def done(self) -> bool:
        return len(self.generated) >= self.max_new_tokens

    def effective_prompt(self) -> np.ndarray:
        """Prefill input: the prompt plus any already-generated tokens
        (teacher-forced after a preemption)."""
        if not self.generated:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, dtype=np.int32)]
        )


class Scheduler:
    """Admission + growth + eviction over a block allocator.

    ``allocator`` is anything with the FreeList interface (alloc/free/
    num_free); ``blocks_for_prefill(req)`` maps a request to the block
    count its prefill will occupy (the engine's bucketing decides this —
    the scheduler does not assume a layout).
    """

    def __init__(
        self,
        allocator: Any,
        *,
        max_slots: int,
        block_size: int,
        blocks_for_prefill: Callable[[Request], int] | None = None,
        prefill_plan: Callable[[Request], tuple[list[int], int]] | None = None,
        decode_reserve: int = 1,
        max_queue: int | None = None,
    ) -> None:
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1 or None, got {max_queue}")
        self.allocator = allocator
        self.max_slots = max_slots
        self.block_size = block_size
        self.decode_reserve = decode_reserve
        self._blocks_for_prefill = blocks_for_prefill or (
            lambda req: -(-req.total_len // block_size)
        )
        # prefill_plan(req) → (shared_block_ids, fresh_need): shared ids
        # arrive ALREADY claimed (one reference each, prefix-cache hit);
        # admission either completes with them at the head of
        # req.block_ids or releases them before backing off.  Default:
        # no sharing, everything fresh.
        self._prefill_plan = prefill_plan or (
            lambda req: ([], self._blocks_for_prefill(req))
        )
        self.max_queue = max_queue
        self.queue: deque[Request] = deque()
        self.running: list[Request] = []  # admission order (oldest first)
        self.finished: list[Request] = []
        self.aborted: list[Request] = []
        self._free_slots: list[int] = list(range(max_slots - 1, -1, -1))
        self.n_preemptions = 0

    # ------------------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def has_work(self) -> bool:
        return bool(self.queue or self.running)

    def add(self, req: Request, *, exempt_cap: bool = False) -> None:
        """Enqueue a NEW request; raises ``QueueFull`` past ``max_queue``.
        Preemption requeues bypass this (``_preempt`` appendleft's
        directly), and supervisor recovery replays pass ``exempt_cap``:
        both were already admitted once and must be able to come back,
        cap or no cap."""
        if (
            not exempt_cap
            and self.max_queue is not None
            and len(self.queue) >= self.max_queue
        ):
            raise QueueFull(len(self.queue), self.max_queue)
        req.state = RequestState.QUEUED
        self.queue.append(req)

    # ------------------------------------------------------------------
    def admit(self) -> list[Request]:
        """Admit queue-head requests into free slots while blocks last.

        Allocates each admitted request's prefill blocks (req.block_ids)
        and assigns its decode slot.  Returns the newly admitted requests
        (the engine prefills them).
        """
        admitted: list[Request] = []
        while self.queue and self._free_slots:
            req = self.queue[0]
            shared, need = self._prefill_plan(req)
            if self.allocator.num_free < need + self.decode_reserve:
                if shared:  # release the claim before backing off
                    self.allocator.free(shared)
                break  # strict FIFO: never skip the head
            ids = self.allocator.alloc(need)
            if ids is None:
                if shared:
                    self.allocator.free(shared)
                break
            self.queue.popleft()
            req.block_ids = shared + ids
            req.n_shared_blocks = len(shared)
            req.slot = self._free_slots.pop()
            req.state = RequestState.RUNNING
            self.running.append(req)
            admitted.append(req)
        return admitted

    # ------------------------------------------------------------------
    def plan_tick(
        self, budget: int, max_chunk: int, *,
        prefill_order: Callable[
            [list[Request]], list[Request]] | None = None,
    ) -> tuple[list[Request], list[tuple[Request, int]]]:
        """The unified-tick token-budget planner: split this tick's
        ``budget`` tokens between decode rows and prefill chunk slices.

        Returns ``(decode_rows, prefill_segments)`` where each segment is
        ``(request, n_tokens)``.  Policy (the SLO-aware co-schedule):

        - **decode first, never starved**: every running request that
          has finished prefill gets its one decode token before any
          prefill work is budgeted — a long prefill can no longer stall
          the decoding batch, it only fills the REMAINING budget.
        - **prefill fills the rest, oldest first**: mid-prefill rows
          (admission order, so FIFO completion order is preserved) take
          up to ``max_chunk`` tokens each from what is left.  Token
          granularity: a segment smaller than a full chunk is legal, so
          any ``budget >= max_slots`` guarantees forward progress.
          ``prefill_order`` overrides the candidate ORDER only (the
          tenant-fairness hook — smallest cost share first, a stable
          re-sort so ties keep admission order); ``None`` is the
          byte-identical oldest-first default.
        - **budgets are exact**: the planned token count never exceeds
          ``budget`` (pinned by tests/test_serve_scheduler.py).
        - **prefix-cache hits are free**: covered content was pre-marked
          done at admission (``Request.prefill_done``), so shared blocks
          consume zero budget — the cap applies to work, not to reuse.
        - **verify widths are tokens**: a speculating decode row's draft
          lanes (``Request.draft_len``) are budgeted AFTER prefill, out
          of whatever budget remains — speculation spends the tick's
          slack, so enabling it can never stall an admission's TTFT.
          Drafts that don't fit are trimmed (``draft_len`` shrinks),
          never the row's base token.

        Pure accounting (no allocation): callers run it after admission
        and block growth, then build the packed mixed batch from it.
        """
        decode = [r for r in self.running if r.prefilled and r.generated]
        left = budget - len(decode)
        prefill: list[tuple[Request, int]] = []
        candidates = (
            self.running if prefill_order is None
            else prefill_order(self.running)
        )
        for r in candidates:
            if r.prefilled or left <= 0:
                continue
            n = min(max_chunk, r.prefill_target - r.prefill_done, left)
            if n > 0:
                prefill.append((r, n))
                left -= n
        for r in decode:
            if r.draft_len > left:
                r.draft_len = max(left, 0)
            left -= r.draft_len
        return decode, prefill

    # ------------------------------------------------------------------
    def ensure_decode_blocks(self) -> list[Request]:
        """Grow every running request that needs a block for its next
        token; evict (preempt → requeue) youngest-first on OOM.  A
        preempted request is fully unwound HERE (blocks freed, slot
        released, requeued at the front) — the returned list is
        informational only (metrics/tests); callers must NOT release
        anything again."""
        preempted: list[Request] = []
        # oldest first, so older requests steal from younger ones
        for req in list(self.running):
            if req.state is not RequestState.RUNNING:
                continue  # already preempted below
            # this tick writes slot cache_len-1, so the allocation is
            # short only when cache_len EXCEEDS it (at an exact block
            # boundary the last slot still fits — growing there would
            # preempt a victim for a block that may never be used)
            while req.cache_len > len(req.block_ids) * self.block_size:
                ids = self.allocator.alloc(1)
                if ids is not None:
                    req.block_ids.extend(ids)
                    continue
                victim = self._pick_victim(req)
                self._preempt(victim)
                preempted.append(victim)
                if victim is req:
                    break
            # speculative verify lanes write slots up to
            # cache_len-1+draft_len; grow to cover them, but NEVER evict
            # for a draft — speculation is opportunistic, so under
            # pressure the draft is trimmed to the blocks that exist and
            # the scheduling trajectory stays identical to plain decode
            if req.state is RequestState.RUNNING and req.draft_len:
                while (req.cache_len + req.draft_len
                       > len(req.block_ids) * self.block_size):
                    ids = self.allocator.alloc(1)
                    if ids is None:
                        req.draft_len = max(
                            len(req.block_ids) * self.block_size
                            - req.cache_len, 0,
                        )
                        break
                    req.block_ids.extend(ids)
        return preempted

    def _pick_victim(self, needing: Request) -> Request:
        """Always the youngest running request — including the needing
        request itself when it IS the youngest.  Evicting anything older
        would invert FIFO completion order and let a young request starve
        an old one by repeatedly re-evicting it on each growth."""
        return self.running[-1]

    def _preempt(self, req: Request) -> None:
        self.allocator.free(req.block_ids)
        req.block_ids = []
        req.n_shared_blocks = 0
        req.pad = 0
        # unified-tick prefill progress is per-admission state: the
        # readmission re-prefills prompt+generated from scratch
        req.prefill_done = 0
        req.prefill_target = 0
        req.prefilled = False
        req.draft_len = 0
        self._release_slot(req)
        self.running.remove(req)
        req.state = RequestState.QUEUED
        self.queue.appendleft(req)
        req.n_preemptions += 1
        self.n_preemptions += 1

    # ------------------------------------------------------------------
    def finish(self, req: Request) -> None:
        self.allocator.free(req.block_ids)
        req.block_ids = []
        self._release_slot(req)
        self.running.remove(req)
        req.state = RequestState.FINISHED
        self.finished.append(req)

    def abort(self, req: Request) -> None:
        """Cancel a live request in whatever state it is in.

        QUEUED (including a preemption requeue waiting at the front)
        holds no blocks — it just leaves the queue.  RUNNING releases its
        decode slot and drops one reference per block: the same decref
        path as finish/eviction, so prefix blocks shared with other
        requests survive and only this request's references return to
        the pool.  Terminal states are a hard error — the caller
        (``ServeEngine.abort``) filters those, and a double-abort here
        would double-free blocks."""
        if req.state is RequestState.QUEUED:
            self.queue.remove(req)
        elif req.state is RequestState.RUNNING:
            self.allocator.free(req.block_ids)
            req.block_ids = []
            req.n_shared_blocks = 0
            self._release_slot(req)
            self.running.remove(req)
        else:
            raise ValueError(
                f"abort on request {req.req_id} in terminal state "
                f"{req.state.value}"
            )
        req.state = RequestState.ABORTED
        self.aborted.append(req)

    def _release_slot(self, req: Request) -> None:
        if req.slot >= 0:
            self._free_slots.append(req.slot)
            req.slot = -1
