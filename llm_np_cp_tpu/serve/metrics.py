"""Serving metrics: what an operator needs to see on one screen.

Collected by ``ServeEngine`` per tick and per request, exported as one
flat dict (``snapshot()``) so the CLI, bench.py, and tests consume the
same numbers:

- ``queue_depth_*``        — requests waiting (sampled per tick)
- ``ttft_s_*``             — arrival (realtime replay) or submit → first
                             emitted token, per request
- ``decode_tok_s_*``       — per-request steady decode rate (tokens
                             after the first / time after first token)
- ``occupancy_*``          — fraction of allocatable blocks held
- ``active_slots_*``       — decode slots busy (batch efficiency)
- ``preemptions``          — evict-on-OOM count (requeues)
- ``throughput_tok_s``     — total generated tokens / wall span
- ``prefix_hit_rate``      — prompt blocks reused from the prefix cache
                             / shareable prompt blocks requested
- ``kv_bytes_tick_*``      — K/V bytes the decode attention touches per
                             tick (the gather→paged observable: the XLA
                             gather path streams the full padded view,
                             the paged kernel only each row's visible
                             blocks)

Percentiles are p50/p90/p99 over whatever was recorded — no windowing;
a serving front-end would wire these into a real metrics sink
(ROADMAP follow-up).
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from llm_np_cp_tpu.serve.scheduler import Request


def _pcts(values: list[float], name: str) -> dict[str, float]:
    if not values:
        return {}
    arr = np.asarray(values, dtype=np.float64)
    return {
        f"{name}_p50": float(np.percentile(arr, 50)),
        f"{name}_p90": float(np.percentile(arr, 90)),
        f"{name}_p99": float(np.percentile(arr, 99)),
        f"{name}_mean": float(arr.mean()),
    }


class ServeMetrics:
    def __init__(self, clock=time.perf_counter) -> None:
        self.clock = clock
        self.t_start = clock()
        self.t_last: float | None = None
        self.n_submitted = 0
        self.n_finished = 0
        self.n_ticks = 0
        self.preemptions = 0
        self.total_generated = 0
        self.ttft_s: list[float] = []
        self.decode_tok_s: list[float] = []
        self.queue_depth: list[int] = []
        self.occupancy: list[float] = []
        self.active_slots: list[int] = []
        self.kv_bytes_tick: list[float] = []
        self.prefix_blocks_requested = 0
        self.prefix_blocks_hit = 0

    # -- record hooks (engine calls these) -----------------------------
    def on_submit(self, req: Request) -> None:
        if self.n_submitted == 0:
            # wall span starts at first traffic, not engine build — idle
            # time before the first request must not deflate throughput
            self.t_start = self.clock()
        self.n_submitted += 1

    def on_tick(
        self, *, queue_depth: int, occupancy: float, active_slots: int,
        preemptions_total: int, kv_bytes: int = 0,
    ) -> None:
        self.n_ticks += 1
        self.t_last = self.clock()
        self.queue_depth.append(queue_depth)
        self.occupancy.append(occupancy)
        self.active_slots.append(active_slots)
        self.preemptions = preemptions_total
        if active_slots:
            # only decode ticks stream cache; idle/admission-only ticks
            # would dilute the per-tick gauge with zeros
            self.kv_bytes_tick.append(float(kv_bytes))

    def on_prefix(self, *, requested: int, hits: int) -> None:
        """One prefill's prefix-cache outcome: ``requested`` shareable
        prompt blocks were looked up, ``hits`` were reused."""
        self.prefix_blocks_requested += requested
        self.prefix_blocks_hit += hits

    def on_token(self, req: Request) -> None:
        self.total_generated += 1

    def on_finish(self, req: Request) -> None:
        self.n_finished += 1
        if req.submit_time is not None and req.first_token_time is not None:
            # realtime replay records the wall arrival, so TTFT includes
            # the wait before the tick loop noticed the request; the
            # virtual clock is incommensurable with wall time, so
            # virtual-mode TTFT is based at submit
            base = req.extra.get("arrival_wall", req.submit_time)
            self.ttft_s.append(req.first_token_time - base)
            n_after_first = len(req.generated) - 1
            span = (req.finish_time or self.clock()) - req.first_token_time
            if n_after_first > 0 and span > 0:
                self.decode_tok_s.append(n_after_first / span)

    # ------------------------------------------------------------------
    def snapshot(self) -> dict[str, Any]:
        span = (self.t_last or self.clock()) - self.t_start
        out: dict[str, Any] = {
            "submitted": self.n_submitted,
            "finished": self.n_finished,
            "ticks": self.n_ticks,
            "preemptions": self.preemptions,
            "total_generated_tokens": self.total_generated,
            "throughput_tok_s": self.total_generated / span if span > 0 else 0.0,
            "wall_s": span,
        }
        out.update(_pcts(self.ttft_s, "ttft_s"))
        out.update(_pcts(self.decode_tok_s, "decode_tok_s"))
        out.update(_pcts([float(q) for q in self.queue_depth], "queue_depth"))
        out.update(_pcts(self.occupancy, "occupancy"))
        out.update(_pcts([float(a) for a in self.active_slots], "active_slots"))
        out.update(_pcts(self.kv_bytes_tick, "kv_bytes_tick"))
        out["kv_bytes_total"] = float(sum(self.kv_bytes_tick))
        out["prefix_blocks_requested"] = self.prefix_blocks_requested
        out["prefix_blocks_hit"] = self.prefix_blocks_hit
        if self.prefix_blocks_requested:
            out["prefix_hit_rate"] = (
                self.prefix_blocks_hit / self.prefix_blocks_requested
            )
        return out

    def format(self) -> str:
        """One operator-readable block (the CLI prints this)."""
        s = self.snapshot()

        def g(key: str, fmt: str = "{:.3f}") -> str:
            return fmt.format(s[key]) if key in s else "-"

        mb_tick = (
            f"{s['kv_bytes_tick_mean'] / 2**20:.2f}"
            if "kv_bytes_tick_mean" in s else "-"
        )
        prefix = (
            f"{s['prefix_hit_rate']:.2f} "
            f"({s['prefix_blocks_hit']}/{s['prefix_blocks_requested']} blocks)"
            if "prefix_hit_rate" in s else "-"
        )
        return (
            f"requests: {s['submitted']} submitted, {s['finished']} finished, "
            f"{s['preemptions']} preemptions over {s['ticks']} ticks\n"
            f"throughput: {s['throughput_tok_s']:.1f} tok/s total "
            f"({s['total_generated_tokens']} tokens in {s['wall_s']:.2f}s)\n"
            f"ttft_s      p50 {g('ttft_s_p50')}  p90 {g('ttft_s_p90')}  "
            f"p99 {g('ttft_s_p99')}\n"
            f"decode_tok_s p50 {g('decode_tok_s_p50', '{:.1f}')}  "
            f"p90 {g('decode_tok_s_p90', '{:.1f}')}\n"
            f"queue_depth p50 {g('queue_depth_p50', '{:.1f}')}  "
            f"p99 {g('queue_depth_p99', '{:.1f}')}; "
            f"occupancy p50 {g('occupancy_p50', '{:.2f}')}  "
            f"p99 {g('occupancy_p99', '{:.2f}')}; "
            f"active_slots mean {g('active_slots_mean', '{:.2f}')}\n"
            f"kv MiB/tick mean {mb_tick}; prefix cache hit rate {prefix}"
        )
