"""Serving metrics: what an operator needs to see on one screen.

Collected by ``ServeEngine`` per tick and per request, exported as one
flat dict (``snapshot()``) so the CLI, bench.py, tests, and the HTTP
``/metrics`` endpoint consume the same numbers:

- ``queue_depth_*``        — requests waiting (sampled per tick)
- ``ttft_s_*``             — arrival (realtime replay) or submit → first
                             emitted token, per request
- ``decode_tok_s_*``       — per-request steady decode rate (tokens
                             after the first / time after first token)
- ``occupancy_*``          — fraction of allocatable blocks held
- ``active_slots_*``       — decode slots busy (batch efficiency)
- ``preemptions``          — evict-on-OOM count (requeues)
- ``aborted`` / ``rejected`` — cancelled requests (client disconnect or
                             deadline) and queue-full admission rejects
- ``finish_reasons``       — terminal outcome counts by reason
                             (``stop``/``length``/``aborted``)
- ``throughput_tok_s``     — total generated tokens / wall span
- ``prefix_hit_rate``      — prompt blocks reused from the prefix cache
                             / shareable prompt blocks requested
- ``kv_bytes_tick_*``      — K/V bytes the decode attention touches per
                             tick (the gather→paged observable: the XLA
                             gather path streams the full padded view,
                             the paged kernel only each row's visible
                             blocks)
- ``roofline_*`` / ``*_bytes_total`` / ``device_time_s_total`` — device
                             roofline telemetry (serve/telemetry.py):
                             achieved GB/s, utilization vs --hbm-gbps
                             and MFU per graded dispatch, plus the
                             exact byte/time ledgers per-request cost
                             attribution sums back to (present only
                             when a TelemetryModel is attached)
- ``queue_wait_s_*`` / ``prefill_s_*`` — per-request phase splits
                             (submit → first admission; cumulative
                             prefill dispatch time incl. re-prefills),
                             derived from the same timestamps that feed
                             the request spans in serve/tracing.py — so
                             a scrape answers "queueing or compute?"
                             without a trace file.

Percentiles are p50/p90/p99 over whatever was recorded — no windowing.

``ttft_s`` and ``decode_tok_s`` additionally maintain REAL Prometheus
histograms (cumulative ``_bucket``/``_sum``/``_count`` series over the
fixed ``TTFT_BUCKETS`` / ``DECODE_TOK_S_BUCKETS``): the bucket counters
are updated incrementally at record time, so they stay exact forever
even when ``max_samples`` trims the percentile windows — and unlike the
quantile gauges they aggregate correctly across replicas.

THREAD SAFETY: the engine tick loop mutates these counters from its own
thread while the HTTP scrape handler renders them from the event loop —
every record hook and ``snapshot()`` serialize on one lock, and
``snapshot()`` copies the value lists before computing percentiles, so a
scrape always sees a consistent point-in-time view (copy-on-read).
``prometheus()`` renders the text exposition format (0.0.4) from that
same snapshot.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import Counter
from typing import Any

import numpy as np

from llm_np_cp_tpu.serve.scheduler import Request

# Fixed histogram buckets (upper bounds, seconds / tokens-per-second).
# Fixed so series are comparable across runs and joinable across
# replicas; spans roughly host-CPU test ticks to live-TPU serving.
TTFT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0, 2.5, 5.0, 10.0)
DECODE_TOK_S_BUCKETS = (0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0,
                        200.0, 500.0, 1000.0)
# Speculative accept length per verify round (accepted draft tokens,
# 0..spec_k): integer upper bounds; the tail bucket absorbs any larger
# spec_k an operator configures
SPEC_ACCEPT_BUCKETS = (0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0)
# Roofline utilization per tick (achieved GB/s over --hbm-gbps, from
# serve/telemetry.py): log-ish lower buckets because CPU test runs sit
# far below the roofline while a healthy TPU tick should land in the
# top few buckets
ROOFLINE_UTIL_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                         0.2, 0.35, 0.5, 0.65, 0.8, 0.9, 1.0)


def _pcts(values: list[float], name: str) -> dict[str, float]:
    if not values:
        return {}
    arr = np.asarray(values, dtype=np.float64)
    return {
        f"{name}_p50": float(np.percentile(arr, 50)),
        f"{name}_p90": float(np.percentile(arr, 90)),
        f"{name}_p99": float(np.percentile(arr, 99)),
        f"{name}_mean": float(arr.mean()),
    }


class ServeMetrics:
    def __init__(self, clock=time.perf_counter,
                 max_samples: int | None = None,
                 slo: Any = None) -> None:
        self.clock = clock
        self._lock = threading.Lock()
        # SLO goodput accounting (serve/slo.SLOTracker): judged per
        # request at terminal time inside _record_latencies, under this
        # lock.  None (the default) = a single is-None check per
        # terminal — the zero-overhead hook discipline
        self.slo = slo
        # tick anomaly sentinel verdicts (serve/slo.TickSentinel via
        # ServeEngine._sentinel_observe): per-phase outlier counts,
        # exported as llm_serve_anomaly_ticks_total{phase=}
        self.anomaly_ticks: Counter[str] = Counter()
        # fleet lifecycle events (serve/lifecycle.ActionPolicy flips,
        # rolling upgrades, elastic add/remove), exported as
        # llm_serve_lifecycle_actions_total{action=}
        self.lifecycle_actions: Counter[str] = Counter()
        # bounded-retention mode for long-running servers: None (bench/
        # test traces — exact full-trace percentiles) keeps every sample;
        # an int caps each value list, dropping the oldest half on
        # overflow (percentiles become a recent-window view; counters
        # stay exact forever).  The HTTP runner sets this — an unbounded
        # list per tick would leak for the server's whole lifetime.
        self.max_samples = max_samples
        self.t_start = clock()
        self.t_last: float | None = None
        self.n_submitted = 0
        self.n_finished = 0
        self.n_aborted = 0
        self.n_rejected = 0
        self.n_recovered = 0
        self.n_ticks = 0
        self.preemptions = 0
        self.total_generated = 0
        self.finish_reasons: Counter[str] = Counter()
        self.ttft_s: list[float] = []
        self.decode_tok_s: list[float] = []
        # per-request phase splits (queueing vs compute), recorded at
        # terminal time from Request.admit_time / Request.prefill_s
        self.queue_wait_s: list[float] = []
        self.prefill_s: list[float] = []
        # exact cumulative histogram state (never trimmed): per-bucket
        # increments + running sum; bucket i counts values <= bucket[i],
        # the trailing slot is the +Inf overflow
        self.ttft_hist = [0] * (len(TTFT_BUCKETS) + 1)
        self.ttft_hist_sum = 0.0
        self.decode_hist = [0] * (len(DECODE_TOK_S_BUCKETS) + 1)
        self.decode_hist_sum = 0.0
        self.queue_depth: list[int] = []
        self.occupancy: list[float] = []
        self.active_slots: list[int] = []
        self.kv_bytes_tick: list[float] = []
        self.prefix_blocks_requested = 0
        self.prefix_blocks_hit = 0
        # prefix-cache LRU reclaim (always counted — reclaim used to be
        # silent, so drop-vs-spill behavior was invisible on a scrape)
        # + the host-RAM KV tier's flow (serve/host_tier.py): spill and
        # restore ledgers in blocks AND bytes, restore-latency samples,
        # and the resident/breakeven gauges the engine refreshes on
        # tier-active ticks.  Zero/absent unless a tier is attached.
        self.prefix_evicted_blocks = 0
        self.prefix_evicted_bytes = 0.0
        self.tier_spilled_blocks = 0
        self.tier_spilled_bytes = 0.0
        self.tier_restored_blocks = 0
        self.tier_restored_bytes = 0.0
        self.tier_restore_s: list[float] = []
        self.tier_resident_bytes = 0.0
        self.tier_breakeven: float | None = None
        # unified-tick (mixed_step) utilization: how this engine's token
        # budget was actually spent — exact counters, never trimmed
        self.mixed_prefill_tokens = 0
        self.mixed_decode_tokens = 0
        # speculative draft-then-verify accounting (exact counters +
        # a real accept-length histogram over SPEC_ACCEPT_BUCKETS —
        # one observation per verify round, value = accepted drafts)
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.spec_rounds = 0
        self.spec_hist = [0] * (len(SPEC_ACCEPT_BUCKETS) + 1)
        self.spec_hist_sum = 0.0
        # device roofline telemetry (serve/telemetry.py): exact byte/
        # time ledgers (never trimmed — per-request attribution must
        # keep summing to them) plus per-dispatch gauge windows and a
        # real utilization histogram.  Empty/zero unless a
        # TelemetryModel is attached to the engine.
        self.roofline_ticks = 0
        self.kv_read_bytes_total = 0.0
        self.kv_write_bytes_total = 0.0
        self.weight_bytes_total = 0.0
        self.device_time_s_total = 0.0
        self.hbm_gbps: float | None = None
        self.roofline_gbps: list[float] = []
        self.roofline_util: list[float] = []
        self.mfu_tick: list[float] = []
        self.util_hist = [0] * (len(ROOFLINE_UTIL_BUCKETS) + 1)
        self.util_hist_sum = 0.0

    # -- record hooks (engine calls these) -----------------------------
    def on_submit(self, req: Request) -> None:
        with self._lock:
            if self.n_submitted == 0:
                # wall span starts at first traffic, not engine build —
                # idle time before the first request must not deflate
                # throughput
                self.t_start = self.clock()
            self.n_submitted += 1

    def on_reject(self) -> None:
        """A submit bounced off the queue-depth cap (HTTP 429)."""
        with self._lock:
            self.n_rejected += 1

    def on_recover(self) -> None:
        """A supervisor replayed an in-flight request into a rebuilt
        engine (teacher-forced resubmit).  Counted apart from submits —
        the request was already counted at its original submit, and
        finish/abort will still fire exactly once."""
        with self._lock:
            self.n_recovered += 1

    def _trim(self, values: list) -> None:
        # caller holds the lock
        if self.max_samples is not None and len(values) > self.max_samples:
            del values[: len(values) // 2]

    def on_tick(
        self, *, queue_depth: int, occupancy: float, active_slots: int,
        preemptions_total: int, kv_bytes: int = 0,
        prefill_tokens: int = 0, decode_tokens: int = 0,
    ) -> None:
        with self._lock:
            self.mixed_prefill_tokens += prefill_tokens
            self.mixed_decode_tokens += decode_tokens
            self.n_ticks += 1
            self.t_last = self.clock()
            self.queue_depth.append(queue_depth)
            self.occupancy.append(occupancy)
            self.active_slots.append(active_slots)
            self.preemptions = preemptions_total
            if active_slots:
                # only decode ticks stream cache; idle/admission-only
                # ticks would dilute the per-tick gauge with zeros
                self.kv_bytes_tick.append(float(kv_bytes))
            for vals in (self.queue_depth, self.occupancy,
                         self.active_slots, self.kv_bytes_tick):
                self._trim(vals)

    def on_anomaly(self, phase: str) -> None:
        """The tick sentinel named ``phase`` as an outlier this tick."""
        with self._lock:
            self.anomaly_ticks[phase] += 1

    def on_lifecycle_action(self, action: str) -> None:
        """One fleet lifecycle event: an ActionPolicy flip
        (shed_prefill_on/off, shed_load_on/off), a rolled replica
        (upgrade_replica), an aborted roll, or an elastic
        add/remove_replica."""
        with self._lock:
            self.lifecycle_actions[action] += 1

    def on_spec(self, *, drafted: int, accepted: int) -> None:
        """One speculative verify round for one request: ``drafted``
        candidate tokens rode the tick's dispatch, ``accepted`` of them
        matched the verifier's deterministic samples."""
        with self._lock:
            self.spec_drafted += drafted
            self.spec_accepted += accepted
            self.spec_rounds += 1
            self.spec_hist[
                bisect.bisect_left(SPEC_ACCEPT_BUCKETS, float(accepted))
            ] += 1
            self.spec_hist_sum += accepted

    def on_telemetry(self, tel: dict[str, Any]) -> None:
        """One telemetry record (serve/telemetry.py): a roofline-graded
        dispatch (``roofline: True`` — the unified tick's one dispatch
        or the split tick's decode dispatch) feeds the per-tick gauges
        and the utilization histogram; a totals-only record (split-path
        prefill, whose wall includes host Python) feeds just the byte/
        time ledgers, which per-request attribution sums back to."""
        with self._lock:
            self.kv_read_bytes_total += tel["kv_read_bytes"]
            self.kv_write_bytes_total += tel["kv_write_bytes"]
            self.weight_bytes_total += tel["weight_bytes"]
            self.device_time_s_total += tel["device_time_s"]
            self.hbm_gbps = tel.get("hbm_gbps", self.hbm_gbps)
            if not tel.get("roofline", True):
                return
            self.roofline_ticks += 1
            util = tel["roofline_util"]
            self.roofline_gbps.append(tel["achieved_gbps"])
            self.roofline_util.append(util)
            self.mfu_tick.append(tel["mfu"])
            self.util_hist[
                bisect.bisect_left(ROOFLINE_UTIL_BUCKETS, util)
            ] += 1
            self.util_hist_sum += util
            for vals in (self.roofline_gbps, self.roofline_util,
                         self.mfu_tick):
                self._trim(vals)

    def on_prefix(self, *, requested: int, hits: int) -> None:
        """One prefill's prefix-cache outcome: ``requested`` shareable
        prompt blocks were looked up, ``hits`` were reused."""
        with self._lock:
            self.prefix_blocks_requested += requested
            self.prefix_blocks_hit += hits

    def on_prefix_evicted(self, *, blocks: int, nbytes: int) -> None:
        """LRU reclaim dropped ``blocks`` prefix-cache entries (their
        K/V bytes included) — with the host tier attached the same
        blocks ALSO count as spills; without it this is the only
        record a prefix was recomputable work thrown away."""
        with self._lock:
            self.prefix_evicted_blocks += blocks
            self.prefix_evicted_bytes += nbytes

    def on_tier_spill(self, *, blocks: int, nbytes: int) -> None:
        """``blocks`` evicted prefix blocks were handed to the host
        tier's writer thread instead of being dropped."""
        with self._lock:
            self.tier_spilled_blocks += blocks
            self.tier_spilled_bytes += nbytes

    def on_tier_restore(self, *, blocks: int, nbytes: int,
                        latency_s: float) -> None:
        """One admission's host-tier span landed back in the pool:
        ``blocks`` restored (``nbytes`` of K/V that did NOT re-prefill)
        after ``latency_s`` of writer-thread staging."""
        with self._lock:
            self.tier_restored_blocks += blocks
            self.tier_restored_bytes += nbytes
            self.tier_restore_s.append(latency_s)
            self._trim(self.tier_restore_s)

    def on_tier_gauge(self, *, resident_bytes: int,
                      breakeven: float | None) -> None:
        """Refresh the tier's live gauges: host bytes resident and the
        measured restore-vs-recompute breakeven ratio (>1 = restoring
        one block is cheaper than re-prefilling it; 0 until both sides
        are measured)."""
        with self._lock:
            self.tier_resident_bytes = float(resident_bytes)
            self.tier_breakeven = breakeven

    def on_token(self, req: Request) -> None:
        with self._lock:
            self.total_generated += 1

    def on_finish(self, req: Request) -> None:
        with self._lock:
            self.n_finished += 1
            self.finish_reasons[req.finish_reason or "length"] += 1
            self._record_latencies(req)

    def on_abort(self, req: Request) -> None:
        """Request cancelled (disconnect or deadline).  Counted apart
        from ``finished`` — its TTFT still records if a token got out."""
        with self._lock:
            self.n_aborted += 1
            self.finish_reasons["aborted"] += 1
            self._record_latencies(req)

    def _record_latencies(self, req: Request) -> None:
        # caller holds the lock
        if self.slo is not None:
            # every terminal gets an SLO verdict (ok / miss / untimed)
            # — aborts are misses, recovered-without-timestamps are
            # untimed, see serve/slo.SLOPolicy.verdict
            self.slo.observe(req)
        if req.submit_time is not None and req.first_token_time is not None:
            # realtime replay records the wall arrival, so TTFT includes
            # the wait before the tick loop noticed the request; the
            # virtual clock is incommensurable with wall time, so
            # virtual-mode TTFT is based at submit
            base = req.extra.get("arrival_wall", req.submit_time)
            ttft = req.first_token_time - base
            self.ttft_s.append(ttft)
            self._trim(self.ttft_s)
            self.ttft_hist[bisect.bisect_left(TTFT_BUCKETS, ttft)] += 1
            self.ttft_hist_sum += ttft
            n_after_first = len(req.generated) - 1
            span = (req.finish_time or self.clock()) - req.first_token_time
            if n_after_first > 0 and span > 0:
                rate = n_after_first / span
                self.decode_tok_s.append(rate)
                self._trim(self.decode_tok_s)
                self.decode_hist[
                    bisect.bisect_left(DECODE_TOK_S_BUCKETS, rate)
                ] += 1
                self.decode_hist_sum += rate
        if req.submit_time is not None and req.admit_time is not None:
            self.queue_wait_s.append(req.admit_time - req.submit_time)
            self._trim(self.queue_wait_s)
        if req.prefill_s:
            self.prefill_s.append(req.prefill_s)
            self._trim(self.prefill_s)

    # ------------------------------------------------------------------
    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            span = (self.t_last or self.clock()) - self.t_start
            out: dict[str, Any] = {
                "submitted": self.n_submitted,
                "finished": self.n_finished,
                "aborted": self.n_aborted,
                "rejected": self.n_rejected,
                "recovered": self.n_recovered,
                "ticks": self.n_ticks,
                "preemptions": self.preemptions,
                "total_generated_tokens": self.total_generated,
                "throughput_tok_s": (
                    self.total_generated / span if span > 0 else 0.0
                ),
                "wall_s": span,
                "finish_reasons": dict(self.finish_reasons),
            }
            # copy-on-read: percentile math sees frozen lists even while
            # the tick loop keeps appending
            ttft = list(self.ttft_s)
            decode = list(self.decode_tok_s)
            qwait = list(self.queue_wait_s)
            prefill = list(self.prefill_s)
            qd = [float(q) for q in self.queue_depth]
            occ = list(self.occupancy)
            act = [float(a) for a in self.active_slots]
            kvb = list(self.kv_bytes_tick)
            prefix_req = self.prefix_blocks_requested
            prefix_hit = self.prefix_blocks_hit
            tier_restore = list(self.tier_restore_s)
            out["prefix_evicted_blocks"] = self.prefix_evicted_blocks
            out["prefix_evicted_bytes"] = self.prefix_evicted_bytes
            if (self.tier_spilled_blocks or self.tier_restored_blocks
                    or self.tier_breakeven is not None):
                # reported only once a tier is attached/active (the
                # spec/SLO discipline: fabricated zeros would read as a
                # wedged tier on a fleet dashboard)
                out["tier_spilled_blocks"] = self.tier_spilled_blocks
                out["tier_spilled_bytes"] = self.tier_spilled_bytes
                out["tier_restored_blocks"] = self.tier_restored_blocks
                out["tier_restored_bytes"] = self.tier_restored_bytes
                out["tier_resident_bytes"] = self.tier_resident_bytes
                out["tier_breakeven_ratio"] = self.tier_breakeven or 0.0
            out["mixed_prefill_tokens"] = self.mixed_prefill_tokens
            out["mixed_decode_tokens"] = self.mixed_decode_tokens
            if self.spec_rounds:
                # reported only once a verify round ran (like the SLO
                # block): a fabricated 0-acceptance series on a
                # non-spec engine would read as "speculation broken"
                out["spec_drafted_tokens"] = self.spec_drafted
                out["spec_accepted_tokens"] = self.spec_accepted
                out["spec_rejected_tokens"] = (
                    self.spec_drafted - self.spec_accepted
                )
                out["spec_rounds"] = self.spec_rounds
                out["spec_accept_rate"] = (
                    self.spec_accepted / self.spec_drafted
                    if self.spec_drafted else 0.0
                )
                out["spec_accept_len_mean"] = (
                    self.spec_accepted / self.spec_rounds
                )
            if self.slo is not None:
                out.update(self.slo.snapshot())
            if self.anomaly_ticks:
                out["anomaly_ticks"] = dict(self.anomaly_ticks)
            if self.lifecycle_actions:
                out["lifecycle_actions"] = dict(self.lifecycle_actions)
            # roofline telemetry: emitted only once a graded dispatch
            # ran (the spec/SLO discipline — fabricated zeros would
            # read as a broken deployment on a fleet dashboard)
            rf_gbps = list(self.roofline_gbps)
            rf_util = list(self.roofline_util)
            rf_mfu = list(self.mfu_tick)
            if self.roofline_ticks:
                out["roofline_ticks"] = self.roofline_ticks
                out["hbm_gbps"] = self.hbm_gbps
                out["kv_read_bytes_total"] = self.kv_read_bytes_total
                out["kv_write_bytes_total"] = self.kv_write_bytes_total
                out["weight_bytes_total"] = self.weight_bytes_total
                out["device_time_s_total"] = self.device_time_s_total
                out["roofline_gbps_last"] = rf_gbps[-1]
                out["roofline_util_last"] = rf_util[-1]
                out["mfu_last"] = rf_mfu[-1]
        out.update(_pcts(ttft, "ttft_s"))
        out.update(_pcts(decode, "decode_tok_s"))
        out.update(_pcts(qwait, "queue_wait_s"))
        out.update(_pcts(prefill, "prefill_s"))
        out.update(_pcts(qd, "queue_depth"))
        out.update(_pcts(occ, "occupancy"))
        out.update(_pcts(act, "active_slots"))
        out.update(_pcts(kvb, "kv_bytes_tick"))
        out.update(_pcts(tier_restore, "tier_restore_s"))
        out.update(_pcts(rf_gbps, "roofline_gbps"))
        out.update(_pcts(rf_util, "roofline_util"))
        out.update(_pcts(rf_mfu, "mfu"))
        # *_last: the most recent per-tick sample — the live gauge a
        # scrape wants, vs the trace-wide percentiles above
        if qd:
            out["queue_depth_last"] = qd[-1]
        if occ:
            out["occupancy_last"] = occ[-1]
        if act:
            out["active_slots_last"] = act[-1]
        out["kv_bytes_total"] = float(sum(kvb))
        out["prefix_blocks_requested"] = prefix_req
        out["prefix_blocks_hit"] = prefix_hit
        if prefix_req:
            out["prefix_hit_rate"] = prefix_hit / prefix_req
        return out

    # ------------------------------------------------------------------
    def prometheus(
        self, extra_gauges: dict[str, float] | None = None,
        prefix: str = "llm_serve",
        const_labels: dict[str, str] | None = None,
    ) -> str:
        """Text exposition format (0.0.4) for a ``GET /metrics`` scrape.

        Rendered from ``snapshot()`` (so a scrape is one locked copy, no
        torn reads).  ``extra_gauges`` lets the HTTP server add live
        gauges the metrics object cannot know (current queue depth, pool
        free blocks, in-flight streams).  ``const_labels`` are spliced
        into EVERY sample's labelset — how a multi-replica server tags
        each engine's series with ``replica="N"`` so counters and
        histograms aggregate across the fleet.
        """
        s = self.snapshot()
        lines: list[str] = []
        const = ",".join(
            f'{k}="{v}"' for k, v in (const_labels or {}).items()
        )

        def lab(labels: str) -> str:
            if not const:
                return labels
            if not labels:
                return "{" + const + "}"
            return labels[:-1] + "," + const + "}"

        def emit(name: str, mtype: str, help_: str,
                 samples: list[tuple[str, float]]) -> None:
            full = f"{prefix}_{name}"
            lines.append(f"# HELP {full} {help_}")
            lines.append(f"# TYPE {full} {mtype}")
            for labels, value in samples:
                lines.append(f"{full}{lab(labels)} {value:.10g}")

        emit("requests_submitted_total", "counter",
             "Requests accepted into the scheduler queue",
             [("", s["submitted"])])
        emit("requests_finished_total", "counter",
             "Requests that ran to a natural finish",
             [("", s["finished"])])
        emit("requests_aborted_total", "counter",
             "Requests cancelled (client disconnect or deadline)",
             [("", s["aborted"])])
        emit("requests_rejected_total", "counter",
             "Submits bounced off the queue-depth cap (HTTP 429)",
             [("", s["rejected"])])
        emit("requests_recovered_total", "counter",
             "In-flight requests replayed into a rebuilt engine after a "
             "supervised restart",
             [("", s["recovered"])])
        emit("finish_total", "counter",
             "Terminal events by finish reason",
             [(f'{{reason="{r}"}}', n)
              for r, n in sorted(s["finish_reasons"].items())] or
             [('{reason="stop"}', 0)])
        emit("preemptions_total", "counter",
             "Evict-on-OOM requeues", [("", s["preemptions"])])
        emit("tokens_generated_total", "counter",
             "Generated tokens across all requests",
             [("", s["total_generated_tokens"])])
        emit("ticks_total", "counter",
             "Scheduler ticks", [("", s["ticks"])])
        emit("queue_depth", "gauge",
             "Requests waiting for admission (last tick sample)",
             [("", s.get("queue_depth_last", 0.0))])
        emit("pool_occupancy", "gauge",
             "Fraction of allocatable KV blocks held (last tick sample)",
             [("", s.get("occupancy_last", 0.0))])
        emit("active_slots", "gauge",
             "Decode slots busy (last tick sample)",
             [("", s.get("active_slots_last", 0.0))])
        emit("prefix_hit_rate", "gauge",
             "Prompt blocks reused from the prefix cache / shareable "
             "blocks requested",
             [("", s.get("prefix_hit_rate", 0.0))])
        emit("prefix_evicted_total", "counter",
             "Prefix-cache blocks LRU-reclaimed under pool pressure "
             "(spilled to the host tier when --kv-tier host, dropped "
             "otherwise)",
             [("", s["prefix_evicted_blocks"])])
        # -- host-RAM KV tier (only once a tier is attached — constant
        # zeros would read as a wedged tier on a fleet dashboard)
        if "tier_spilled_blocks" in s:
            emit("kv_tier_blocks_total", "counter",
                 "Host-tier block flow: spill = evicted prefix blocks "
                 "copied to host RAM, restore = blocks staged back as "
                 "pool blocks instead of re-prefilling",
                 [('{op="spill"}', s["tier_spilled_blocks"]),
                  ('{op="restore"}', s["tier_restored_blocks"])])
            emit("kv_tier_bytes_total", "counter",
                 "Host-tier byte flow (the restored-bytes ledger is "
                 "prefill work the tier saved)",
                 [('{op="spill"}', s["tier_spilled_bytes"]),
                  ('{op="restore"}', s["tier_restored_bytes"])])
            emit("kv_tier_resident_bytes", "gauge",
                 "Host RAM currently holding spilled KV blocks",
                 [("", s["tier_resident_bytes"])])
            emit("kv_tier_breakeven_ratio", "gauge",
                 "Measured restore-vs-recompute breakeven (re-prefill "
                 "seconds per block / restore seconds per block; >1 = "
                 "restoring is cheaper; 0 = not yet measured)",
                 [("", s["tier_breakeven_ratio"])])
        emit("kv_bytes_tick_mean", "gauge",
             "Mean K/V bytes decode attention touches per tick",
             [("", s.get("kv_bytes_tick_mean", 0.0))])
        emit("mixed_tokens_total", "counter",
             "Unified-tick token budget spent, split by work kind",
             [('{kind="prefill"}', s["mixed_prefill_tokens"]),
              ('{kind="decode"}', s["mixed_decode_tokens"])])
        # -- speculative decoding (only once a verify round ran — a
        # constant-zero series on a plain engine would read as a broken
        # speculation deployment on a fleet dashboard)
        if "spec_drafted_tokens" in s:
            emit("spec_tokens_total", "counter",
                 "Speculative draft tokens by verify outcome",
                 [('{kind="drafted"}', s["spec_drafted_tokens"]),
                  ('{kind="accepted"}', s["spec_accepted_tokens"]),
                  ('{kind="rejected"}', s["spec_rejected_tokens"])])
            emit("spec_accept_rate", "gauge",
                 "Accepted / drafted speculative tokens over the "
                 "traffic span",
                 [("", s["spec_accept_rate"])])
        emit("throughput_tok_s", "gauge",
             "Generated tokens per second over the traffic span",
             [("", s["throughput_tok_s"])])
        # -- SLO goodput accounting (only when a policy is attached:
        # series that are always 0-with-no-policy would read as "a
        # perfect SLO" on a dashboard that aggregates the fleet)
        if "slo_ok" in s:
            emit("goodput_tok_s", "gauge",
                 "SLO-attaining tokens per second over the traffic span "
                 "(tokens of requests that met every latency target)",
                 [("", s["goodput_tok_s"])])
            if "slo_attainment" in s:
                # omitted (not defaulted) until a timed verdict exists:
                # a fabricated 1.0 would read as a perfect SLO
                emit("slo_attainment", "gauge",
                     "Fraction of timed terminal requests meeting the "
                     "SLO",
                     [("", s["slo_attainment"])])
            emit("slo_requests_total", "counter",
                 "Terminal requests by SLO verdict (untimed = recovered "
                 "with no surviving timestamps; excluded from attainment)",
                 [('{verdict="ok"}', s["slo_ok"]),
                  ('{verdict="miss"}', s["slo_miss"]),
                  ('{verdict="untimed"}', s["slo_untimed"])])
            burn = [
                (f'{{window="{k[len("slo_burn_rate_"):]}"}}', s[k])
                for k in sorted(s) if k.startswith("slo_burn_rate_")
            ]
            if burn:
                emit("slo_burn_rate", "gauge",
                     "Error-budget burn rate per window (observed miss "
                     "rate / budgeted miss rate; >1 = overspending)",
                     burn)
        # -- device roofline telemetry (only once a graded dispatch ran
        # — serve/telemetry.py; constant zeros would read as a stalled
        # device on a fleet dashboard)
        if "roofline_ticks" in s:
            emit("device_bytes_total", "counter",
                 "Modeled HBM traffic by kind (analytic byte model, "
                 "serve/telemetry.py)",
                 [('{kind="kv_read"}', s["kv_read_bytes_total"]),
                  ('{kind="kv_write"}', s["kv_write_bytes_total"]),
                  ('{kind="weight"}', s["weight_bytes_total"])])
            emit("device_time_seconds_total", "counter",
                 "Measured dispatch-to-host-sync wall attributed to "
                 "device work",
                 [("", s["device_time_s_total"])])
            emit("roofline_gbps", "gauge",
                 "Achieved GB/s of the last graded dispatch (modeled "
                 "bytes / measured wall)",
                 [("", s["roofline_gbps_last"])])
            emit("roofline_util", "gauge",
                 "Achieved GB/s over the --hbm-gbps roofline, last "
                 "graded dispatch",
                 [("", s["roofline_util_last"])])
            emit("mfu", "gauge",
                 "Model FLOP utilization estimate, last graded dispatch",
                 [("", s["mfu_last"])])
            emit("hbm_gbps_target", "gauge",
                 "The HBM roofline utilization is graded against",
                 [("", s["hbm_gbps"] or 0.0)])
        if s.get("anomaly_ticks"):
            emit("anomaly_ticks_total", "counter",
                 "Ticks where the sentinel flagged this phase as an "
                 "outlier vs its rolling baseline",
                 [(f'{{phase="{p}"}}', n)
                  for p, n in sorted(s["anomaly_ticks"].items())])
        if s.get("lifecycle_actions"):
            emit("lifecycle_actions_total", "counter",
                 "Fleet lifecycle events: auto-action flips "
                 "(shed_prefill/shed_load on/off), rolled replicas, "
                 "elastic add/remove",
                 [(f'{{action="{a}"}}', n)
                  for a, n in sorted(s["lifecycle_actions"].items())])
        # -- real histograms: cumulative _bucket/_sum/_count from the
        # incrementally-maintained counters (exact forever, unlike the
        # trimmed percentile windows; aggregable across replicas)
        with self._lock:
            ttft_hist = list(self.ttft_hist)
            ttft_hist_sum = self.ttft_hist_sum
            decode_hist = list(self.decode_hist)
            decode_hist_sum = self.decode_hist_sum
            spec_hist = list(self.spec_hist)
            spec_hist_sum = self.spec_hist_sum
            spec_rounds = self.spec_rounds
            util_hist = list(self.util_hist)
            util_hist_sum = self.util_hist_sum
            roofline_ticks = self.roofline_ticks

        def emit_hist(name: str, help_: str, buckets: tuple,
                      counts: list[int], total: float) -> None:
            full = f"{prefix}_{name}"
            lines.append(f"# HELP {full} {help_}")
            lines.append(f"# TYPE {full} histogram")
            cum = 0
            for le, n in zip(buckets, counts):
                cum += n
                labels = lab('{le="%.10g"}' % le)
                lines.append(f"{full}_bucket{labels} {cum}")
            cum += counts[-1]
            labels = lab('{le="+Inf"}')
            lines.append(f"{full}_bucket{labels} {cum}")
            lines.append(f"{full}_sum{lab('')} {total:.10g}")
            lines.append(f"{full}_count{lab('')} {cum}")

        emit_hist("ttft_seconds",
                  "Submit/arrival to first token, per request",
                  TTFT_BUCKETS, ttft_hist, ttft_hist_sum)
        emit_hist("decode_tok_s",
                  "Per-request steady decode rate (tokens after the "
                  "first / time after first token)",
                  DECODE_TOK_S_BUCKETS, decode_hist, decode_hist_sum)
        if spec_rounds:
            emit_hist("spec_accept_length",
                      "Accepted draft tokens per speculative verify "
                      "round",
                      SPEC_ACCEPT_BUCKETS, spec_hist, spec_hist_sum)
        if roofline_ticks:
            emit_hist("roofline_util_hist",
                      "Roofline utilization per graded dispatch "
                      "(achieved GB/s over --hbm-gbps)",
                      ROOFLINE_UTIL_BUCKETS, util_hist, util_hist_sum)

        # -- trace-wide quantile gauges alongside the histograms (the
        # single-process view; percentile windows, see max_samples) and
        # the per-request phase split — "queueing or compute?" straight
        # off the scrape, no trace file needed
        for base, help_ in (
            ("ttft_s", "TTFT quantiles over the recorded window"),
            ("decode_tok_s",
             "Decode-rate quantiles over the recorded window"),
            ("queue_wait_s",
             "Submit to first admission into a decode slot, per request"),
            ("prefill_s",
             "Cumulative prefill dispatch time per request "
             "(re-prefills after preemption/recovery included)"),
            ("tier_restore_s",
             "Host-tier restore staging latency per restored span"),
            ("roofline_gbps",
             "Achieved-GB/s quantiles over the recorded dispatch "
             "window"),
            ("roofline_util",
             "Roofline-utilization quantiles over the recorded "
             "dispatch window"),
        ):
            samples = [(f'{{quantile="{q}"}}', s[f"{base}_{p}"])
                       for q, p in (("0.5", "p50"), ("0.9", "p90"),
                                    ("0.99", "p99"))
                       if f"{base}_{p}" in s]
            if samples:
                emit(f"{base}_quantile", "gauge", help_, samples)
        for key, value in (extra_gauges or {}).items():
            emit(key, "gauge", "Live server gauge", [("", float(value))])
        return "\n".join(lines) + "\n"

    def format(self) -> str:
        """One operator-readable block (the CLI prints this)."""
        s = self.snapshot()

        def g(key: str, fmt: str = "{:.3f}") -> str:
            return fmt.format(s[key]) if key in s else "-"

        mb_tick = (
            f"{s['kv_bytes_tick_mean'] / 2**20:.2f}"
            if "kv_bytes_tick_mean" in s else "-"
        )
        prefix = (
            f"{s['prefix_hit_rate']:.2f} "
            f"({s['prefix_blocks_hit']}/{s['prefix_blocks_requested']} blocks)"
            if "prefix_hit_rate" in s else "-"
        )
        aborts = (
            f", {s['aborted']} aborted" if s["aborted"] else ""
        ) + (
            f", {s['rejected']} rejected" if s["rejected"] else ""
        )
        spec = (
            f"\nspeculative: {s['spec_accept_rate']:.2f} accept rate "
            f"({s['spec_accepted_tokens']}/{s['spec_drafted_tokens']} "
            f"drafts over {s['spec_rounds']} rounds, "
            f"mean accept len {s['spec_accept_len_mean']:.2f})"
            if "spec_drafted_tokens" in s else ""
        )
        tier = (
            f"\nkv tier: {s['tier_restored_blocks']} blocks restored "
            f"({s['tier_restored_bytes'] / 2**20:.2f} MiB of prefill "
            f"saved), {s['tier_spilled_blocks']} spilled, "
            f"{s['prefix_evicted_blocks']} evictions, breakeven "
            f"{s['tier_breakeven_ratio']:.2f}"
            if "tier_spilled_blocks" in s else ""
        )
        roofline = (
            f"\nroofline: {s['roofline_gbps_mean']:.2f} GB/s mean "
            f"({s['roofline_util_mean']:.2%} of {s['hbm_gbps']:g} GB/s, "
            f"p99 util {s.get('roofline_util_p99', 0.0):.2%}, "
            f"mfu {s['mfu_mean']:.4%}) over {s['roofline_ticks']} "
            "graded dispatches"
            if "roofline_ticks" in s else ""
        )
        return (
            f"requests: {s['submitted']} submitted, {s['finished']} finished"
            f"{aborts}, "
            f"{s['preemptions']} preemptions over {s['ticks']} ticks\n"
            f"throughput: {s['throughput_tok_s']:.1f} tok/s total "
            f"({s['total_generated_tokens']} tokens in {s['wall_s']:.2f}s)\n"
            f"ttft_s      p50 {g('ttft_s_p50')}  p90 {g('ttft_s_p90')}  "
            f"p99 {g('ttft_s_p99')}\n"
            f"queue_wait_s p50 {g('queue_wait_s_p50')}  "
            f"p99 {g('queue_wait_s_p99')}; "
            f"prefill_s p50 {g('prefill_s_p50')}  "
            f"p99 {g('prefill_s_p99')}\n"
            f"decode_tok_s p50 {g('decode_tok_s_p50', '{:.1f}')}  "
            f"p90 {g('decode_tok_s_p90', '{:.1f}')}\n"
            f"queue_depth p50 {g('queue_depth_p50', '{:.1f}')}  "
            f"p99 {g('queue_depth_p99', '{:.1f}')}; "
            f"occupancy p50 {g('occupancy_p50', '{:.2f}')}  "
            f"p99 {g('occupancy_p99', '{:.2f}')}; "
            f"active_slots mean {g('active_slots_mean', '{:.2f}')}\n"
            f"kv MiB/tick mean {mb_tick}; prefix cache hit rate {prefix}"
            f"{spec}{tier}{roofline}"
        )
