"""Request-lifecycle + tick-phase tracing for the serving stack.

``ServeMetrics`` answers *how much* (counters, percentiles); this module
answers *where the time went*: when a p99 TTFT regresses or a chaos run
recovers slowly, the operator needs a timeline — queue wait vs prefill
chunks vs decode dispatch vs host sync vs SSE delivery — not another
percentile.  ``TraceRecorder`` collects that timeline as Chrome/Perfetto
trace-event JSON (stdlib only, like the rest of the HTTP stack; open the
dump at ui.perfetto.dev or chrome://tracing):

- **per-request spans** — async events (``ph`` b/e/n) on one track per
  request id: ``queued`` → ``prefill`` (prefix-cache hits annotated,
  one ``prefill_chunk`` slice per dispatched chunk) → ``decode`` →
  a terminal ``finish`` instant (reason-tagged), with instants for
  ``evicted-requeued`` preemptions and ``recovery-replay`` resubmits
  after a supervised restart.  The HTTP layer brackets the whole thing
  with an ``http`` span starting at socket accept, so queue wait is
  visibly split from network/parse time.
- **per-tick phase spans** — complete events (``ph`` X) on the engine
  tick thread: ``admission`` / ``prefill`` / ``grow`` /
  ``decode_dispatch`` / ``host_sync`` / ``deliver`` slices nested under
  one ``tick`` event.  The phases are measured at consecutive
  timestamps, so they sum to the tick span by construction — the
  invariant tests pin.
- the dispatch phases also run under ``jax.profiler.TraceAnnotation``
  named scopes, so this host timeline lines up against a device profile
  captured with ``--jax-profile DIR`` (the live-TPU tuning workflow).

ZERO-OVERHEAD WHEN OFF (the ``FaultInjector`` discipline): nothing
constructs a recorder unless tracing is requested (``--trace-out`` /
``--trace-ring``), and every hook in the engine/HTTP hot path is a
single ``is None`` check — no allocation, no call.  Pinned by
``tools/compile_counter.assert_tracing_hooks_guarded`` (an AST lint over
the hot-path modules) plus a zero-new-compiles test.

THREAD SAFETY: events arrive from the engine tick thread, the asyncio
event loop, the watchdog, and the supervisor's rebuild thread — one lock
serializes every append, and readers (``events()`` / ``to_dict()`` /
the ``GET /debug/trace`` handler) copy under it.  With ``ring=N`` the
recorder keeps only the newest N events (a long-running server must not
grow without bound); ``dropped`` counts what the ring displaced.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from typing import Any, Callable

# The request-lifecycle phase names, in order; ``request_phase``
# transitions between them (ending whatever span is open) and
# ``request_end`` closes the track with a reason-tagged ``finish``
# instant.  tools/summarize_trace.py renders these (plus the HTTP
# layer's "http" bracket) as its lifecycle columns — that tool stays
# stdlib-only, so it carries its own copy, pinned equal to this one by
# tests/test_serve_tracing.py.
REQUEST_PHASES = ("queued", "prefill", "decode")
# Tick-phase names, in tick order (see ServeEngine._step_split).
TICK_PHASES = (
    "admission", "prefill", "grow", "decode_dispatch", "host_sync",
    "deliver",
)
# Unified-tick phase names (ServeEngine._step_mixed): the separate
# prefill phase collapses into the single mixed dispatch, the
# token-budget planner gets its own slice, and ``draft`` is the
# host-side speculative proposal pass (prompt-lookup over each
# speculating request's history — dictionary probes, no device work;
# ~0 on non-spec engines).  Same consecutive-timestamps sum-to-tick
# contract; tick args additionally carry the prefill_tokens/
# decode_tokens budget split — plus spec_draft_tokens/
# spec_accept_tokens on spec-enabled engines — for
# tools/summarize_trace.py's utilization line.
MIXED_TICK_PHASES = (
    "admission", "draft", "grow", "plan", "mixed_dispatch", "host_sync",
    "deliver",
)

# ----------------------------------------------------------------------
# W3C trace context (the `traceparent` header): the ONE request identity
# that survives the fleet.  A request routed by the PrefixRouter, killed
# with its process, journal-replayed, and drained to a peer replica
# keeps the SAME 32-hex trace id through every hop — span args carry it,
# so tools/summarize_trace.py --merge can stitch per-replica/per-process
# trace files back into one request-ordered timeline.
# Format: `00-<32 hex trace id>-<16 hex parent span id>-<2 hex flags>`.
# ----------------------------------------------------------------------
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def gen_trace_id() -> str:
    return os.urandom(16).hex()


def gen_span_id() -> str:
    return os.urandom(8).hex()


def parse_traceparent(header: str | None) -> tuple[str, str] | None:
    """``traceparent`` header → ``(trace_id, parent_span_id)``, or None
    when absent/malformed (a bad header means a FRESH trace, never a
    400 — trace context must not be able to fail a request)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    version, trace_id, parent_id, _flags = m.groups()
    if version == "ff":  # forbidden version
        return None
    if trace_id == "0" * 32 or parent_id == "0" * 16:
        return None  # all-zero ids are invalid per spec
    return trace_id, parent_id


def make_traceparent(trace_id: str, span_id: str | None = None) -> str:
    """Render the header this server emits back (sampled flag set —
    we recorded the request, whatever upstream decided)."""
    return f"00-{trace_id}-{span_id or gen_span_id()}-01"


class TraceRecorder:
    def __init__(
        self,
        *,
        clock: Callable[[], float] = time.perf_counter,
        ring: int | None = None,
    ) -> None:
        if ring is not None and ring < 1:
            raise ValueError(f"ring must be >= 1 or None, got {ring}")
        self.clock = clock
        self.ring = ring
        self._t0 = clock()
        # wall-clock anchor of the trace epoch: per-process perf_counter
        # timestamps are incommensurable across replicas/restarts, so
        # --merge rebases each file's events by its anchor before
        # stitching per-replica timelines together
        self.wall_epoch = time.time()
        self._pid = os.getpid()
        self._lock = threading.Lock()
        self._events: deque | list = (
            deque(maxlen=ring) if ring is not None else []
        )
        # optional OTLP span sink (serve/otel.OtlpExporter): every event
        # the recorder keeps is also offered to the exporter's pending
        # queue (enqueue only — its writer thread does the IO).  None =
        # one is-None check per event, the standard zero-overhead hook
        # discipline (tools/lint R4 covers the ``otel`` hook)
        self.otel: Any = None
        self.dropped = 0
        # rid → currently-open lifecycle phase name (exactly one per
        # live request; the http bracket span is tracked separately by
        # async_begin/async_end)
        self._req_phase: dict[int, str] = {}
        self._named_threads: set[int] = set()

    # -- clock ---------------------------------------------------------
    def now_us(self) -> float:
        """Microseconds since recorder construction (the trace epoch)."""
        return (self.clock() - self._t0) * 1e6

    # -- low-level event append (callers hold no lock) -----------------
    def _ensure_thread_named(self, tid: int) -> None:
        # caller holds the lock; first event from a thread gets the
        # thread_name metadata event viewers use to label its track
        if tid not in self._named_threads:
            self._named_threads.add(tid)
            self._push({
                "name": "thread_name", "ph": "M", "pid": self._pid,
                "tid": tid,
                "args": {"name": threading.current_thread().name},
            })

    def _append(self, ev: dict, tid: int | None = None) -> None:
        tid = threading.get_ident() if tid is None else tid
        ev.setdefault("pid", self._pid)
        ev.setdefault("tid", tid)
        with self._lock:
            self._ensure_thread_named(tid)
            self._push(ev)

    def _push(self, ev: dict) -> None:
        # caller holds the lock; the exporter's offer() is a single
        # lock-protected append (recorder lock → exporter lock, never
        # the reverse — the exporter never calls back into the recorder)
        if self.ring is not None and len(self._events) == self.ring:
            self.dropped += 1
        self._events.append(ev)
        if self.otel is not None:
            self.otel.offer(ev)

    # -- synchronous (thread-track) events -----------------------------
    def complete(
        self, name: str, start_us: float, end_us: float | None = None,
        *, cat: str = "phase", args: dict | None = None,
    ) -> None:
        """One ``ph: X`` slice on the calling thread's track."""
        if end_us is None:
            end_us = self.now_us()
        ev: dict[str, Any] = {
            "name": name, "cat": cat, "ph": "X",
            "ts": start_us, "dur": max(end_us - start_us, 0.0),
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def instant(self, name: str, *, cat: str = "tick",
                args: dict | None = None) -> None:
        ev: dict[str, Any] = {
            "name": name, "cat": cat, "ph": "i", "ts": self.now_us(),
            "s": "t",
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def tick(
        self, start_us: float,
        phases: tuple[tuple[str, float, float], ...],
        *, args: dict | None = None,
    ) -> None:
        """One tick: the wrapper ``tick`` slice plus its phase slices,
        appended atomically (a ``/debug/trace`` read never sees a tick
        missing half its phases).  Phases are ``(name, t0_us, t1_us)``
        measured at consecutive timestamps, so their durations sum to
        the tick span by construction."""
        end_us = self.now_us()
        tid = threading.get_ident()
        events = [{
            "name": "tick", "cat": "tick", "ph": "X", "ts": start_us,
            "dur": max(end_us - start_us, 0.0), "pid": self._pid,
            "tid": tid, **({"args": args} if args else {}),
        }]
        for name, p0, p1 in phases:
            events.append({
                "name": name, "cat": "phase", "ph": "X", "ts": p0,
                "dur": max(p1 - p0, 0.0), "pid": self._pid, "tid": tid,
            })
        with self._lock:
            self._ensure_thread_named(tid)
            for ev in events:
                self._push(ev)

    # -- request-lifecycle (async-track) events ------------------------
    def async_begin(self, rid: int, name: str, *,
                    ts_us: float | None = None,
                    args: dict | None = None) -> None:
        ev: dict[str, Any] = {
            "name": name, "cat": "request", "ph": "b", "id": rid,
            "ts": self.now_us() if ts_us is None else ts_us,
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def async_end(self, rid: int, name: str, *,
                  ts_us: float | None = None) -> None:
        self._append({
            "name": name, "cat": "request", "ph": "e", "id": rid,
            "ts": self.now_us() if ts_us is None else ts_us,
        })

    def request_phase(self, rid: int, phase: str, *,
                      args: dict | None = None) -> None:
        """Transition request ``rid`` into ``phase``: end whatever
        lifecycle span is open and begin the new one (back-to-back, one
        timestamp — no gap, no overlap)."""
        now = self.now_us()
        with self._lock:
            open_phase = self._req_phase.get(rid)
            self._req_phase[rid] = phase
        if open_phase is not None:
            self.async_end(rid, open_phase, ts_us=now)
        self.async_begin(rid, phase, ts_us=now, args=args)

    def request_instant(self, rid: int, name: str, *,
                        args: dict | None = None) -> None:
        """Async instant (``ph: n``) on the request's track —
        annotations like ``evicted-requeued`` / ``recovery-replay``."""
        ev: dict[str, Any] = {
            "name": name, "cat": "request", "ph": "n", "id": rid,
            "ts": self.now_us(),
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def request_end(self, rid: int, reason: str, *,
                    args: dict | None = None) -> None:
        """Terminal: close the open lifecycle span and stamp a
        reason-tagged ``finish`` instant (span-vs-metrics parity counts
        these against the finish_reasons counters)."""
        now = self.now_us()
        with self._lock:
            open_phase = self._req_phase.pop(rid, None)
        if open_phase is not None:
            self.async_end(rid, open_phase, ts_us=now)
        merged = {"reason": reason}
        if args:
            merged.update(args)
        self._append({
            "name": "finish", "cat": "request", "ph": "n", "id": rid,
            "ts": now, "args": merged,
        })

    # -- export --------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def events(self) -> list[dict]:
        """Point-in-time copy (the ring keeps mutating underneath)."""
        with self._lock:
            return list(self._events)

    def to_dict(self) -> dict:
        return {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "otherData": {
                "dropped_events": self.dropped,
                "wall_epoch": self.wall_epoch,
            },
        }

    def dump(self, path: str) -> int:
        """Write the Chrome trace-event JSON; returns the event count."""
        payload = self.to_dict()
        with open(path, "w") as f:
            json.dump(payload, f)
        return len(payload["traceEvents"])
